"""Native host runtime (C++): serial baseline, block I/O, layout conversion.

Built on demand with ``python -m parallel_convolution_tpu.native.build``
(plain g++, no external deps).  Everything here has a NumPy fallback in the
pure-Python modules — the native tier exists because the reference's serial
baseline and I/O are native C, and a Python stand-in would not be an honest
baseline for benchmark comparisons.
"""

from __future__ import annotations

import ctypes
import os
from pathlib import Path

_LIB_NAME = "libpctpu.so"
_lib = None


def lib_path() -> Path:
    return Path(__file__).resolve().parent / _LIB_NAME


def is_built() -> bool:
    return lib_path().exists()


def load():
    """Load (building lazily if needed) the native library; raises if absent
    and unbuildable."""
    global _lib
    if _lib is not None:
        return _lib
    if not is_built():
        from parallel_convolution_tpu.native import build

        build.build()
    lib = ctypes.CDLL(os.fspath(lib_path()))
    c = ctypes
    i64, u8p, fp = c.c_int64, c.POINTER(c.c_uint8), c.POINTER(c.c_float)
    lib.pctpu_run_serial_u8.argtypes = [
        u8p, u8p, i64, i64, i64, fp, c.c_int, c.c_int, c.c_int
    ]
    lib.pctpu_run_serial_u8.restype = None
    lib.pctpu_num_threads.restype = c.c_int
    for fn in (lib.pctpu_read_block, lib.pctpu_write_block):
        fn.argtypes = [c.c_char_p, i64, i64, i64, i64, i64, i64, i64, u8p]
        fn.restype = c.c_int
    for fn in (lib.pctpu_interleaved_to_planar, lib.pctpu_planar_to_interleaved):
        fn.argtypes = [u8p, u8p, i64, i64, i64]
        fn.restype = None
    _lib = lib
    return lib
