"""Build the native library with g++ (no network, no external deps).

Usage: ``python -m parallel_convolution_tpu.native.build``

Flag notes: ``-O3 -march=native -fopenmp`` mirror the reference's
``-O3 -fopenmp`` Makefiles; ``-ffp-contract=off`` is load-bearing — an fma
contraction of ``acc += tap * px`` would round once instead of twice and
break bit-exactness against the NumPy/XLA oracle semantics.
"""

from __future__ import annotations

import subprocess
import sys
from pathlib import Path

HERE = Path(__file__).resolve().parent


def build(verbose: bool = False) -> Path:
    src = HERE / "src" / "pctpu.cpp"
    out = HERE / "libpctpu.so"
    cmd = [
        "g++", "-O3", "-march=native", "-fopenmp", "-fPIC", "-shared",
        "-ffp-contract=off", "-fno-fast-math",
        "-o", str(out), str(src),
    ]
    if verbose:
        print(" ".join(cmd))
    subprocess.run(cmd, check=True, capture_output=not verbose)
    return out


if __name__ == "__main__":
    path = build(verbose=True)
    print(f"built {path}")
    sys.exit(0)
