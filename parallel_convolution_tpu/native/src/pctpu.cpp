// Native runtime tier: serial/OpenMP stencil baseline + raw image block I/O.
//
// The reference's native components are plain C: the serial convolute()
// baseline (component C1/C2), its OpenMP-threaded hybrid variant (C9), and
// raw-image I/O (C7).  The TPU compute path of this framework is Pallas/XLA;
// this library is the *host-side* native tier: the honest CPU baseline the
// benchmarks compare against (what "1 process / N threads" buys on this
// host) and fast block I/O for huge images.
//
// Semantics contract (must match ops/oracle.py bit-exactly):
//   * zero ghost ring of width r = k/2 each iteration;
//   * per pixel/channel: float32 accumulation over taps in row-major order
//     (one fused multiply-add per tap is NOT allowed — an fma would round
//     differently than a*b+c in two steps, so we compile without
//     -ffast-math and keep the explicit  acc += tap * px  form);
//   * store-back: clip(rint(acc), 0, 255) with rint in round-half-to-even
//     (the default FE_TONEAREST mode of std::nearbyintf).

#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <vector>

#ifdef _OPENMP
#include <omp.h>
#endif

extern "C" {

// One u8-semantics iteration: src -> dst, both interleaved (H, W, C) u8.
// taps: k*k float32, row-major.  threads <= 0 means "all available".
static void convolve_once_u8(const uint8_t* src, uint8_t* dst,
                             int64_t H, int64_t W, int64_t C,
                             const float* taps, int k, int threads) {
  const int r = k / 2;
#ifdef _OPENMP
  if (threads > 0) omp_set_num_threads(threads);
#pragma omp parallel for schedule(static)
#endif
  for (int64_t y = 0; y < H; ++y) {
    for (int64_t x = 0; x < W; ++x) {
      for (int64_t c = 0; c < C; ++c) {
        float acc = 0.0f;
        int t = 0;
        for (int dy = -r; dy <= r; ++dy) {
          const int64_t yy = y + dy;
          for (int dx = -r; dx <= r; ++dx, ++t) {
            const int64_t xx = x + dx;
            float px = 0.0f;  // zero ghost ring outside the image
            if (yy >= 0 && yy < H && xx >= 0 && xx < W)
              px = (float)src[(yy * W + xx) * C + c];
            acc += taps[t] * px;  // fixed order, no fma (see header note)
          }
        }
        float q = std::nearbyintf(acc);  // round half to even
        q = q < 0.0f ? 0.0f : (q > 255.0f ? 255.0f : q);
        dst[(y * W + x) * C + c] = (uint8_t)q;
      }
    }
  }
}

// iters u8 iterations with double buffering (the reference's pointer swap).
void pctpu_run_serial_u8(const uint8_t* img, uint8_t* out,
                         int64_t H, int64_t W, int64_t C,
                         const float* taps, int k, int iters, int threads) {
  if (iters <= 0) {
    std::memcpy(out, img, (size_t)(H * W * C));
    return;
  }
  std::vector<uint8_t> buf;
  uint8_t* bufs[2] = {out, out};
  if (iters > 1) {
    buf.resize((size_t)(H * W * C));
    bufs[1] = buf.data();
  }
  const uint8_t* src = img;
  for (int t = 0; t < iters; ++t) {
    // Alternate destinations so iteration iters-1 lands in `out`; the
    // source is always the other buffer (or `img` on the first pass).
    uint8_t* dst = bufs[(iters - 1 - t) % 2];
    convolve_once_u8(src, dst, H, W, C, taps, k, threads);
    src = dst;
  }
}

int pctpu_num_threads(void) {
#ifdef _OPENMP
  return omp_get_max_threads();
#else
  return 1;
#endif
}

// ---- raw image block I/O (C7): pread/pwrite at row offsets --------------

// Read rows [r0, r1) x cols [c0, c1) of a (rows, cols, ch) u8 raw file
// into `out` (contiguous (r1-r0, c1-c0, ch)).  Returns 0 on success.
int pctpu_read_block(const char* path, int64_t rows, int64_t cols, int64_t ch,
                     int64_t r0, int64_t r1, int64_t c0, int64_t c1,
                     uint8_t* out) {
  if (r0 < 0 || c0 < 0 || r1 > rows || c1 > cols || r0 > r1 || c0 > c1)
    return -2;
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  const int64_t bw = (c1 - c0) * ch;
  for (int64_t y = r0; y < r1; ++y) {
    const int64_t off = (y * cols + c0) * ch;
    if (std::fseek(f, (long)off, SEEK_SET) != 0 ||
        std::fread(out + (y - r0) * bw, 1, (size_t)bw, f) != (size_t)bw) {
      std::fclose(f);
      return -3;
    }
  }
  std::fclose(f);
  return 0;
}

// Write a (r1-r0, c1-c0, ch) block into a pre-sized raw file in place.
int pctpu_write_block(const char* path, int64_t rows, int64_t cols, int64_t ch,
                      int64_t r0, int64_t r1, int64_t c0, int64_t c1,
                      const uint8_t* block) {
  if (r0 < 0 || c0 < 0 || r1 > rows || c1 > cols || r0 > r1 || c0 > c1)
    return -2;
  FILE* f = std::fopen(path, "r+b");
  if (!f) return -1;
  const int64_t bw = (c1 - c0) * ch;
  for (int64_t y = r0; y < r1; ++y) {
    const int64_t off = (y * cols + c0) * ch;
    if (std::fseek(f, (long)off, SEEK_SET) != 0 ||
        std::fwrite(block + (y - r0) * bw, 1, (size_t)bw, f) != (size_t)bw) {
      std::fclose(f);
      return -3;
    }
  }
  std::fclose(f);
  return 0;
}

// ---- layout conversion: interleaved (H,W,C) <-> planar (C,H,W) ----------

void pctpu_interleaved_to_planar(const uint8_t* in, uint8_t* out,
                                 int64_t H, int64_t W, int64_t C) {
#ifdef _OPENMP
#pragma omp parallel for schedule(static)
#endif
  for (int64_t y = 0; y < H; ++y)
    for (int64_t x = 0; x < W; ++x)
      for (int64_t c = 0; c < C; ++c)
        out[c * H * W + y * W + x] = in[(y * W + x) * C + c];
}

void pctpu_planar_to_interleaved(const uint8_t* in, uint8_t* out,
                                 int64_t H, int64_t W, int64_t C) {
#ifdef _OPENMP
#pragma omp parallel for schedule(static)
#endif
  for (int64_t y = 0; y < H; ++y)
    for (int64_t x = 0; x < W; ++x)
      for (int64_t c = 0; c < C; ++c)
        out[(y * W + x) * C + c] = in[c * H * W + y * W + x];
}

}  // extern "C"
