"""NumPy-facing wrappers over the native C++ library."""

from __future__ import annotations

import ctypes
import os

import numpy as np

from parallel_convolution_tpu.native import load
from parallel_convolution_tpu.ops.filters import Filter

_U8P = ctypes.POINTER(ctypes.c_uint8)
_F32P = ctypes.POINTER(ctypes.c_float)


def _u8p(a: np.ndarray):
    return a.ctypes.data_as(_U8P)


def run_serial_u8(img: np.ndarray, filt: Filter, iters: int,
                  threads: int = 0) -> np.ndarray:
    """Native serial/OpenMP run with oracle-identical u8 semantics.

    ``threads=0`` uses all cores (the reference's hybrid C9 tier);
    ``threads=1`` is the strict serial baseline (C1).
    """
    lib = load()
    img = np.ascontiguousarray(img, dtype=np.uint8)
    H, W = img.shape[:2]
    C = 1 if img.ndim == 2 else img.shape[2]
    out = np.empty_like(img)
    taps = np.ascontiguousarray(filt.taps, dtype=np.float32)
    lib.pctpu_run_serial_u8(
        _u8p(img), _u8p(out), H, W, C,
        taps.ctypes.data_as(_F32P), filt.size, int(iters), int(threads),
    )
    return out


def num_threads() -> int:
    return int(load().pctpu_num_threads())


def read_block(path, rows, cols, mode, r0, r1, c0, c1) -> np.ndarray:
    lib = load()
    ch = 3 if mode == "rgb" else 1
    shape = (r1 - r0, c1 - c0) if ch == 1 else (r1 - r0, c1 - c0, ch)
    out = np.empty(shape, np.uint8)
    rc = lib.pctpu_read_block(os.fspath(path).encode(), rows, cols, ch,
                              r0, r1, c0, c1, _u8p(out))
    if rc != 0:
        raise OSError(f"pctpu_read_block failed with code {rc} for {path}")
    return out


def write_block(path, rows, cols, mode, r0, c0, block: np.ndarray) -> None:
    lib = load()
    ch = 3 if mode == "rgb" else 1
    block = np.ascontiguousarray(block, np.uint8)
    r1, c1 = r0 + block.shape[0], c0 + block.shape[1]
    rc = lib.pctpu_write_block(os.fspath(path).encode(), rows, cols, ch,
                               r0, r1, c0, c1, _u8p(block))
    if rc != 0:
        raise OSError(f"pctpu_write_block failed with code {rc} for {path}")


def interleaved_to_planar(img: np.ndarray) -> np.ndarray:
    lib = load()
    if img.ndim == 2:
        return img[None].copy()
    img = np.ascontiguousarray(img, np.uint8)
    H, W, C = img.shape
    out = np.empty((C, H, W), np.uint8)
    lib.pctpu_interleaved_to_planar(_u8p(img), _u8p(out), H, W, C)
    return out


def planar_to_interleaved(img: np.ndarray) -> np.ndarray:
    lib = load()
    img = np.ascontiguousarray(img, np.uint8)
    C, H, W = img.shape
    if C == 1:
        return img[0].copy()
    out = np.empty((H, W, C), np.uint8)
    lib.pctpu_planar_to_interleaved(_u8p(img), _u8p(out), H, W, C)
    return out
