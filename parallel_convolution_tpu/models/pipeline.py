"""The flagship model: distributed iterative image convolution end-to-end.

Equivalent user surface to the reference's parallel ``main()`` (SURVEY.md
§3.2) — read raw image, decompose over the device grid, iterate the stencil
with halo exchange, write raw output — as a reusable object instead of an
inlined program.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from parallel_convolution_tpu.ops.filters import Filter, get_filter
from parallel_convolution_tpu.parallel import step as step_lib
from parallel_convolution_tpu.parallel.mesh import make_grid_mesh
from parallel_convolution_tpu.utils import imageio


@dataclasses.dataclass
class ConvolutionModel:
    """Iterative stencil filtering of grey/RGB images over a 2D TPU mesh.

    Args:
      filt: a :class:`Filter` or registry name (default: the reference's
        blur kernel).
      mesh: the 2D ('x','y') device mesh; defaults to all devices in a
        near-square grid (the MPI_Dims_create default).
      backend: 'shifted' (normative XLA path), 'pallas' (TPU stencil
        kernel), or 'xla_conv' (conv_general_dilated).
      quantize: apply uint8 store-back semantics each iteration (the
        reference's behavior for images); False = float Jacobi mode.
      fallback: probe the backend once per (mesh, config) and walk the
        degradation chain pallas_rdma → pallas → shifted on a
        classified-transient compile/launch failure instead of dying
        (resilience.degrade).  The backend actually used is recorded in
        ``self.effective_backend`` after each run — a degraded run can
        always be told apart from the requested tier.
    """

    filt: Filter | str = "blur3"
    mesh: Mesh | None = None
    backend: str = "shifted"  # any BACKENDS name, or "auto": resolve
    #                backend (and any None knobs below) through the tuning
    #                subsystem — plan cache first, cost model otherwise
    quantize: bool = True
    storage: str = "f32"  # 'bf16' halves HBM/ICI traffic, still bit-exact
    #                        in quantize mode (u8 values are exact in bf16)
    fuse: int | None = 1  # iterations per halo exchange (temporal fusion,
    #                T*r-deep halos once instead of r-deep every iteration);
    #                None = let backend="auto" tune the depth
    boundary: str = "zero"  # 'periodic' = torus wrap (ring topology)
    tile: tuple[int, int] | None = None  # Pallas kernel output-tile (TH, TW)
    #                override; None = per-kernel tuned default
    interior_split: bool = False  # unmasked-interior launch split (fused
    #                Pallas on a 1x1 grid; bit-identical, opt-in experiment)
    overlap: bool | None = None  # interior-first overlapped halo pipeline
    #                (RDMA kernels): None = off for explicit backends /
    #                tuned for backend="auto"; True is a clamped request —
    #                the resolved knob lands in self.effective_overlap
    col_mode: str | None = None  # RDMA column-slab transport (packed |
    #                strided | auto; None = auto) — the resolved value
    #                lands in self.effective_col_mode
    fallback: bool = False  # graceful backend degradation on transient
    #                compile/launch failure (resilience.degrade)

    def __post_init__(self) -> None:
        if isinstance(self.filt, str):
            self.filt = get_filter(self.filt)
        if self.mesh is None:
            self.mesh = make_grid_mesh()
        step_lib._check_storage(self.storage, self.quantize)
        if self.fuse is None and self.backend != "auto":
            raise ValueError(
                "fuse=None means 'tune it' and needs backend='auto'")
        # The backend the last run ACTUALLY used (== self.backend unless
        # auto resolved it / fallback degraded it); None until a run
        # happens.  plan_source records the auto resolution's provenance
        # (measured|interpolated|predicted), or 'explicit'.
        self.effective_backend: str | None = None
        self.plan_source: str = "explicit"
        # The overlap knob the last run ACTUALLY compiled with (clamped
        # request / tuned decision / degrade re-clamp); None until a run.
        self.effective_overlap: bool | None = None
        # The column transport the last run ACTUALLY compiled with.
        self.effective_col_mode: str | None = None

    def set_mesh(self, mesh) -> "ConvolutionModel":
        """Swap the device mesh mid-object (elastic recovery).

        ``mesh`` is a Mesh or an ``"RxC"`` spec string.  Only mesh-scoped
        state resets (the recorded effective backend / plan provenance —
        both are per-mesh verdicts); everything else, including compiled
        runners for OTHER meshes, is untouched: ``parallel.step``'s build
        caches and ``resilience.degrade``'s probe cache both key on the
        mesh, so swapping back later reuses the old executables with zero
        re-tracing.  Output bytes are mesh-invariant by the framework's
        core contract, so a swap never changes results — only topology.
        """
        from parallel_convolution_tpu.parallel.mesh import mesh_from_spec

        self.mesh = mesh_from_spec(mesh) if isinstance(mesh, str) else mesh
        self.effective_backend = None
        self.plan_source = "explicit"
        self.effective_overlap = None
        self.effective_col_mode = None
        return self

    def _resolved_knobs(
            self, hw: tuple[int, int],
            channels: int = 1) -> tuple[str, int, object, bool, str]:
        """Resolve for the REAL (H, W) workload: the probe must compile
        the same kernel family (block geometry + storage dtype) the run
        will, or it could pass while the run crashes.

        ``backend="auto"`` resolves through the tuning subsystem FIRST
        (plan cache, else cost model); the degradation walk then guards
        the resolved backend like any explicitly-named one.  The overlap
        knob resolves alongside (tuned for auto, clamped request
        otherwise) and is re-clamped if degradation leaves the RDMA tier.
        """
        backend, fuse, tile = self.backend, self.fuse, self.tile
        overlap, col_mode = self.overlap, self.col_mode
        if backend == "auto":
            from parallel_convolution_tpu import tuning

            res = tuning.resolve(
                self.mesh, self.filt, (channels, *hw),
                storage=self.storage, quantize=self.quantize,
                boundary=self.boundary, fuse=fuse,
                tile=step_lib._norm_tile(tile), overlap=overlap,
                col_mode=col_mode)
            backend, fuse, tile = res.backend, res.fuse, res.tile
            overlap, col_mode = res.overlap, res.col_mode
            self.plan_source = res.source
        else:
            fuse = 1 if fuse is None else fuse
            self.plan_source = "explicit"
        overlap = step_lib.resolve_overlap(overlap, backend, self.mesh)
        from parallel_convolution_tpu.parallel.mesh import (
            grid_shape, padded_extent,
        )

        R, C = grid_shape(self.mesh)
        block_hw = (padded_extent(hw[0], R) // R, padded_extent(hw[1], C) // C)
        col_mode = step_lib.resolve_col_mode(
            col_mode, backend, self.mesh, block_hw, self.filt.radius,
            int(fuse), self.storage)
        if not self.fallback:
            self.effective_backend = backend
            self.effective_overlap = overlap
            self.effective_col_mode = col_mode
            return backend, fuse, tile, overlap, col_mode
        eff = step_lib._resolve_fallback(
            self.mesh, self.filt, backend, self.quantize, fuse,
            self.boundary, step_lib._norm_tile(tile),
            self.interior_split, self.storage, block_hw=block_hw,
            overlap=overlap, col_mode=col_mode)
        overlap = overlap and eff == "pallas_rdma"
        col_mode = step_lib.clamp_col_mode(col_mode, eff)
        self.effective_backend = eff
        self.effective_overlap = overlap
        self.effective_col_mode = col_mode
        return eff, fuse, tile, overlap, col_mode

    # -- array-level API ----------------------------------------------------
    def run_planar(self, x, iters: int) -> jnp.ndarray:
        """(C, H, W) f32 in → (C, H, W) f32 out after ``iters`` iterations."""
        backend, fuse, tile, overlap, col_mode = self._resolved_knobs(
            x.shape[-2:], x.shape[0])
        return step_lib.sharded_iterate(
            x, self.filt, iters, mesh=self.mesh,
            quantize=self.quantize, backend=backend,
            storage=self.storage, fuse=fuse, boundary=self.boundary,
            tile=tile, interior_split=self.interior_split, overlap=overlap,
            col_mode=col_mode,
        )

    def run_image(self, img: np.ndarray, iters: int) -> np.ndarray:
        """uint8 (H, W[, 3]) in → uint8 out; the one-call user entrypoint."""
        x = imageio.interleaved_to_planar(img).astype(np.float32)
        out = self.run_planar(x, iters)
        return imageio.planar_to_interleaved(
            np.asarray(out).astype(np.uint8)
        )

    def run_images(self, imgs, iters: int) -> list[np.ndarray]:
        """Batch of same-sized images in one device program.

        Channels are independent in the stencil, so a batch is just more
        planes on the leading axis — the framework's data-parallel tier
        (SURVEY.md §2 parallelism inventory: DP 'falls out free').
        """
        planar = [imageio.interleaved_to_planar(im) for im in imgs]
        counts = [p.shape[0] for p in planar]
        x = np.concatenate(planar, axis=0).astype(np.float32)
        out = np.asarray(self.run_planar(x, iters)).astype(np.uint8)
        res, i0 = [], 0
        for c in counts:
            res.append(imageio.planar_to_interleaved(out[i0 : i0 + c]))
            i0 += c
        return res

    # -- file-level API (the reference CLI contract) ------------------------
    def run_raw_file(
        self, src: str, dst: str, rows: int, cols: int, mode: str, iters: int
    ) -> None:
        """raw file → raw file, the reference's ``main()`` end to end."""
        img = imageio.read_raw(src, rows, cols, mode)
        imageio.write_raw(dst, self.run_image(img, iters))

    def run_raw_file_sharded(
        self, src: str, dst: str, rows: int, cols: int, mode: str, iters: int
    ) -> None:
        """Huge-image path: block-reads from disk straight into the device
        sharding, iterates, block-writes back — the full image never exists
        in one host buffer (the MPI-IO workflow, SURVEY.md §7)."""
        import numpy as np

        from parallel_convolution_tpu.parallel.step import STORAGE_DTYPES
        from parallel_convolution_tpu.utils import sharded_io

        xs = sharded_io.load_sharded(
            src, rows, cols, mode, self.mesh,
            dtype=np.dtype(STORAGE_DTYPES[self.storage]),
        )
        backend, fuse, tile, overlap, col_mode = self._resolved_knobs(
            (rows, cols), 3 if mode == "rgb" else 1)
        out = step_lib.iterate_prepared(
            xs, self.filt, iters, self.mesh, (rows, cols),
            quantize=self.quantize, backend=backend,
            fuse=fuse, boundary=self.boundary, tile=tile,
            interior_split=self.interior_split, overlap=overlap,
            col_mode=col_mode,
        )
        sharded_io.save_sharded(dst, out, rows, cols, mode)
