"""End-to-end pipelines: the user-facing 'model' layer.

The reference's user surface is three ``main()`` binaries (serial / MPI /
hybrid) that read a raw image, iterate a filter, and write the result.  Here
that surface is :class:`ConvolutionModel` (the flagship distributed
pipeline) and :class:`JacobiSolver` (run-to-convergence smoothing, BASELINE
config 5), both driving the same sharded step machinery.
"""

from parallel_convolution_tpu.models.pipeline import ConvolutionModel
from parallel_convolution_tpu.models.jacobi import JacobiSolver

__all__ = ["ConvolutionModel", "JacobiSolver"]
