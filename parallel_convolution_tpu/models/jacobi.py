"""Run-to-convergence Jacobi smoothing (BASELINE config 5; component C6).

The reference's optional early-stop — every N iterations each rank computes
a local diff flag and the grid agrees via ``MPI_Allreduce`` — generalized
into a proper iterative solver: float32 carry, max-abs convergence norm,
``lax.while_loop`` + ``lax.pmax`` entirely on-device.
"""

from __future__ import annotations

import dataclasses

import numpy as np
from jax.sharding import Mesh

from parallel_convolution_tpu.ops.filters import Filter, get_filter
from parallel_convolution_tpu.parallel import step as step_lib
from parallel_convolution_tpu.parallel.mesh import make_grid_mesh


@dataclasses.dataclass
class JacobiSolver:
    """Iterate a smoothing stencil until the field stops changing.

    ``tol`` is the max-abs single-iteration change below which the run
    stops; ``check_every`` matches the reference's every-N reduction cadence
    (larger = fewer collectives, up to N-1 extra iterations).
    """

    filt: Filter | str = "jacobi3"
    tol: float = 1e-3
    max_iters: int = 10_000
    check_every: int = 10
    mesh: Mesh | None = None
    backend: str = "shifted"
    quantize: bool = False
    boundary: str = "zero"
    storage: str = "f32"  # iteration-carry dtype (see sharded_converge)
    fuse: int | None = 1  # fused iterations between convergence checks;
    #                None = tune it (backend="auto", resolved in
    #                sharded_converge through the tuning subsystem)
    tile: tuple[int, int] | None = None  # Pallas kernel tile override
    interior_split: bool = False  # unmasked-interior launch split (see
    #                ConvolutionModel; fused chunks only)
    overlap: bool | None = None  # interior-first overlapped halo pipeline
    #                (see ConvolutionModel; resolved in sharded_converge)
    col_mode: str | None = None  # RDMA column-slab transport (packed |
    #                strided | auto; see ConvolutionModel)
    solver: str = "jacobi"  # convergence strategy (utils.config.SOLVERS):
    #                "jacobi" = the reference's sweep loop; "multigrid" =
    #                the geometric V-cycle (solvers.multigrid) — same
    #                stopping measure, ~orders of magnitude fewer
    #                fine-grid work units on smooth problems
    mg_levels: int | None = None  # multigrid level-count cap (None =
    #                coarsen to the planner's floor); ignored for jacobi
    last_mg: object = dataclasses.field(default=None, repr=False,
                                        compare=False)  # the MGResult of
    #                the most recent multigrid solve (cycles, work_units,
    #                per-level grids) — None until one runs

    def __post_init__(self) -> None:
        from parallel_convolution_tpu.utils.config import SOLVERS

        if self.solver not in SOLVERS:
            raise ValueError(
                f"solver must be one of {SOLVERS}, got {self.solver!r}")
        if isinstance(self.filt, str):
            self.filt = get_filter(self.filt)
        if self.mesh is None:
            self.mesh = make_grid_mesh()

    def set_mesh(self, mesh) -> "JacobiSolver":
        """Swap the device mesh (elastic recovery) — same contract as
        ``ConvolutionModel.set_mesh``: solver config and compiled state
        for other meshes are untouched, results are mesh-invariant."""
        from parallel_convolution_tpu.parallel.mesh import mesh_from_spec

        self.mesh = mesh_from_spec(mesh) if isinstance(mesh, str) else mesh
        return self

    def solve(self, x) -> tuple[np.ndarray, int]:
        """(C, H, W) f32 field → (solved field, work count).

        The work count is solver-shaped: Jacobi iterations run, or
        V-cycles run for ``solver="multigrid"`` (whose full accounting —
        fine-grid ``work_units``, the per-level schedule — lands in
        ``self.last_mg``, an :class:`solvers.multigrid.MGResult`).
        """
        if self.solver == "multigrid":
            from parallel_convolution_tpu.solvers import multigrid

            out, res = multigrid.mg_converge(
                x, self.filt, tol=self.tol, max_iters=self.max_iters,
                mesh=self.mesh, quantize=self.quantize,
                backend=self.backend, storage=self.storage,
                boundary=self.boundary, fuse=self.fuse, tile=self.tile,
                overlap=self.overlap, mg_levels=self.mg_levels,
                col_mode=self.col_mode,
            )
            self.last_mg = res
            return np.asarray(out), res.cycles
        out, iters = step_lib.sharded_converge(
            x, self.filt, tol=self.tol, max_iters=self.max_iters,
            check_every=self.check_every, mesh=self.mesh,
            quantize=self.quantize, backend=self.backend,
            boundary=self.boundary, storage=self.storage,
            fuse=self.fuse, tile=self.tile,
            interior_split=self.interior_split, overlap=self.overlap,
            col_mode=self.col_mode,
        )
        return np.asarray(out), iters
