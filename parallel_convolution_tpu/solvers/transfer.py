"""Multigrid transfer operators as first-class stencil forms.

Restriction (full weighting) and prolongation (bilinear) are the
inter-grid couplings of the V-cycle — and they are *stencils*: full
weighting is exactly the 3×3 ``[[1,2,1],[2,4,2],[1,2,1]]/16`` tap array
(the ``blur3`` pyramid kernel already in the filter registry) applied at
every coarse-aligned fine point, bilinear prolongation is its adjoint up
to scaling.  The wafer-scale stencil paper (PAPERS.md) makes the same
move: treating transfer operators as ordinary stencil programs lets
them ride the existing halo machinery instead of being host-side
bolt-ons.

Both operators register in the kernel-form registry
(``parallel.kernels``) under their own ``stencil_form`` classes
(``restrict`` / ``prolong``), keyed ``(rank=2, name, boundary)`` — the
same dispatch surface the smoothers resolve through — and build
per-block functions that run INSIDE ``shard_map`` on the level's mesh:

* ghost cells come from the same two-phase ``halo.halo_exchange``
  (depth 1: both operators touch at most one neighbor point);
* out-of-image positions are re-masked through the same
  ``step._valid_mask`` invariant, so the pad-to-multiple rim behaves
  exactly like the serial zero-pad formula the unit tests check
  against.

Grid-alignment contract — THE load-bearing detail (measured, not
asserted: the even-centered zero-boundary variant diverges at ≥3
levels because the coarse ghost line drifts off the fine ghost line by
h per level):

* ``zero``     — ODD-centered: coarse ``k`` sits at fine ``2k+1``, so
  the coarse ghost ring (coarse index −1 → fine ``2·(−1)+1 = −1``)
  coincides EXACTLY with the fine ghost ring at every level, and the
  zero boundary stays representable all the way down.  Coarse extent
  ``(n−1)//2`` (for even ``n`` the last coarse point stays one fine
  cell inside the boundary — the outside choice re-introduces the
  misalignment).
* ``periodic`` — EVEN-centered: coarse ``k`` at fine ``2k`` (a torus
  has no boundary line to align; wrap preserves itself under even
  coarsening).  Coarse extent ``n//2``; the level planner refuses to
  coarsen a torus level whose extents cannot keep grid-divisible
  alignment.

With EVEN per-device fine blocks (the level planner's padding rule)
both centerings keep every coarse point's stencil within the device's
fine block plus a depth-1 halo — no gather, no resharding inside the
operator.
"""

from __future__ import annotations

import jax.numpy as jnp

from parallel_convolution_tpu.ops import conv
from parallel_convolution_tpu.ops.filters import get_filter
from parallel_convolution_tpu.parallel import halo, kernels as kernel_forms

__all__ = ["FW_FILTER", "build_prolong_bilinear", "build_prolong_trilinear",
           "build_restrict_fw", "build_restrict_fw3", "coarse_extent"]

# Full weighting IS the /16 pyramid stencil — the registry's blur3 taps.
FW_FILTER = get_filter("blur3")


def coarse_extent(n: int, boundary: str = "zero") -> int:
    """Coarse-grid extent of a fine extent ``n`` under the centering the
    boundary requires: ``(n−1)//2`` for zero (odd-centered — coarse k at
    fine 2k+1, last point strictly inside), ``n//2`` for periodic
    (even-centered wrap; the planner enforces even ``n``)."""
    n = int(n)
    return n // 2 if boundary == "periodic" else (n - 1) // 2


def _check_even_block(block_hw, op: str) -> None:
    bh, bw = block_hw
    if bh % 2 or bw % 2:
        raise ValueError(
            f"{op} needs even per-device blocks (coarse-aligned fine "
            f"points stay device-local), got {block_hw}; the level "
            "planner pads extents to 2*grid multiples")


def build_restrict_fw(grid, valid_hw, block_hw, boundary: str = "zero"):
    """Per-block full-weighting restriction ``(C, bh, bw) → (C, bh/2,
    bw/2)`` for use inside ``shard_map`` on the fine level's mesh.

    One depth-1 halo exchange, one ``blur3``-tap correlation (the full
    weighting stencil), the centering subsample (odd fine indices for
    zero, even for periodic), and the coarse validity mask — the coarse
    output obeys the same masking invariant as every iterate: positions
    beyond ``coarse_extent(valid)`` are zero.
    """
    _check_even_block(block_hw, "restrict_fw")
    periodic = boundary == "periodic"
    cvalid = (coarse_extent(valid_hw[0], boundary),
              coarse_extent(valid_hw[1], boundary))
    cblock = (block_hw[0] // 2, block_hw[1] // 2)
    needs_mask = not periodic and (
        cvalid[0] != cblock[0] * grid[0] or cvalid[1] != cblock[1] * grid[1])
    # Local index of coarse point 0's fine image: 1 (odd-centered, zero)
    # or 0 (even-centered, periodic).  Device-locality: with even blocks,
    # fine 2k+off for local coarse k lands in [off, bh-2+off] — inside
    # the block either way; the FW taps then reach at most one cell
    # beyond, which the depth-1 halo provides.
    off = 0 if periodic else 1

    def restrict(v):
        from parallel_convolution_tpu.parallel.step import _valid_mask

        p = halo.halo_exchange(v, 1, grid, boundary)
        c = conv.correlate_padded(p, FW_FILTER)[:, off::2, off::2]
        if needs_mask:
            c = c * _valid_mask(cvalid, cblock).astype(c.dtype)
        return c.astype(v.dtype)

    return restrict


def build_prolong_bilinear(grid, valid_hw, block_hw, boundary: str = "zero"):
    """Per-block bilinear prolongation ``(C, bh/2, bw/2) → (C, bh, bw)``
    for use inside ``shard_map`` on the FINE level's mesh (the coarse
    correction arrives resharded onto the fine mesh at half blocks).

    Coarse-aligned fine points copy their coarse point; the points
    between average the two (four, at the diagonal) bracketing coarse
    points — the tensor product of the 1D ``[1/2, 1, 1/2]`` interpolation
    stencil, realized as two interleave passes over the depth-1
    halo-padded coarse block.  Beyond-extent coarse reads are exactly the
    boundary's ghost convention: 0 for zero (the adjoint of the
    odd-centered restriction's inside rule), wrap for periodic.
    """
    _check_even_block(block_hw, "prolong_bilinear")
    periodic = boundary == "periodic"
    m, n = block_hw[0] // 2, block_hw[1] // 2
    needs_mask = not periodic and (
        valid_hw[0] != block_hw[0] * grid[0]
        or valid_hw[1] != block_hw[1] * grid[1])

    def interleave(a, b, axis):
        """Alternate a/b along ``axis``: out[2i] = a[i], out[2i+1] = b[i]."""
        stacked = jnp.stack([a, b], axis=axis + 1)
        shape = list(a.shape)
        shape[axis] *= 2
        return stacked.reshape(shape)

    def prolong(c):
        from parallel_convolution_tpu.parallel.step import _valid_mask

        p = halo.halo_exchange(c, 1, grid, boundary)  # (C, m+2, n+2)
        if periodic:
            # Even-centered: fine 2k = coarse k; fine 2k+1 = mean(k, k+1).
            a = p[:, 1:m + 1, :]
            b = p[:, 2:m + 2, :]
            rows = interleave(a, (a + b) * 0.5, axis=1)   # (C, 2m, n+2)
            al = rows[:, :, 1:n + 1]
            bl = rows[:, :, 2:n + 2]
            out = interleave(al, (al + bl) * 0.5, axis=2)  # (C, 2m, 2n)
        else:
            # Odd-centered: fine 2k+1 = coarse k; fine 2k = mean(k-1, k)
            # (coarse ghost −1 reads 0 — the fine boundary line itself).
            a = p[:, 0:m, :]
            b = p[:, 1:m + 1, :]
            rows = interleave((a + b) * 0.5, b, axis=1)   # (C, 2m, n+2)
            al = rows[:, :, 0:n]
            bl = rows[:, :, 1:n + 1]
            out = interleave((al + bl) * 0.5, bl, axis=2)  # (C, 2m, 2n)
        if needs_mask:
            out = out * _valid_mask(valid_hw, block_hw).astype(out.dtype)
        return out.astype(c.dtype)

    return prolong


# -- rank 3 (round 23): the same operators, one more axis ------------------
# Full weighting stays the separable [1/4, 1/2, 1/4] tensor product and
# trilinear prolongation its adjoint; the centering/extent contract is
# UNCHANGED per axis (odd-centered zero, even-centered periodic).  The
# depth axis is RESIDENT (volumes/halo3), so its coarsening needs no
# shard_map uniformity: blocks carry depth/2 coarse planes with the
# beyond-``coarse_extent`` tail masked to zero, exactly the rule the
# sharded H/W axes follow via the global-coordinate mask.

_FW_TAPS = (0.25, 0.5, 0.25)


def _check_even_block3(depth: int, block_hw, op: str) -> None:
    _check_even_block(block_hw, op)
    if int(depth) % 2:
        raise ValueError(
            f"{op} needs an even depth (coarse-aligned planes), got "
            f"D={depth}")


def _interleave(a, b, axis: int):
    """Alternate a/b along ``axis``: out[2i] = a[i], out[2i+1] = b[i]."""
    stacked = jnp.stack([a, b], axis=axis + 1)
    shape = list(a.shape)
    shape[axis] *= 2
    return stacked.reshape(shape)


def _fw_axis(p, axis: int):
    """One [1/4, 1/2, 1/4] smoothing pass along ``axis`` of a padded
    (F, ...) array — consumes that axis's depth-1 ghost."""
    n = p.shape[axis]
    lo = [slice(None)] * p.ndim
    cc = [slice(None)] * p.ndim
    hi = [slice(None)] * p.ndim
    lo[axis], cc[axis], hi[axis] = (
        slice(0, n - 2), slice(1, n - 1), slice(2, n))
    return (_FW_TAPS[0] * p[tuple(lo)] + _FW_TAPS[1] * p[tuple(cc)]
            + _FW_TAPS[2] * p[tuple(hi)])


def build_restrict_fw3(grid, depth: int, valid_hw, block_hw,
                       boundary: str = "zero"):
    """Per-block rank-3 full weighting ``(F, D, bh, bw) → (F, D/2,
    bh/2, bw/2)`` for use inside ``shard_map`` on the fine level's mesh.

    One depth-1 6-face exchange (``volumes.halo3``), three separable FW
    passes (the 3×3×3 tensor-product stencil), the centering subsample
    per axis, then the masks: the coarse (H, W) validity mask rank 2
    uses, plus a LOCAL depth mask zeroing coarse planes beyond
    ``coarse_extent(D)`` (the resident axis has no pad-to-multiple rim,
    but the odd-centered zero coarsening still drops the last plane of
    an even depth one fine cell inside the boundary).
    """
    from parallel_convolution_tpu.volumes import halo3
    from parallel_convolution_tpu.volumes.forms import _valid_mask3

    _check_even_block3(depth, block_hw, "restrict_fw(rank 3)")
    periodic = boundary == "periodic"
    cvalid = (coarse_extent(valid_hw[0], boundary),
              coarse_extent(valid_hw[1], boundary))
    cblock = (block_hw[0] // 2, block_hw[1] // 2)
    cdepth, cvalid_d = int(depth) // 2, coarse_extent(depth, boundary)
    needs_mask = not periodic and (
        cvalid[0] != cblock[0] * grid[0] or cvalid[1] != cblock[1] * grid[1])
    off = 0 if periodic else 1

    def restrict(v):
        p = halo3.volume_halo_exchange(v, 1, grid, boundary)
        for axis in (1, 2, 3):
            p = _fw_axis(p, axis)
        c = p[:, off::2, off::2, off::2]
        if needs_mask:
            c = c * _valid_mask3(cvalid, cblock).astype(c.dtype)
        if cvalid_d < cdepth:
            dmask = (jnp.arange(cdepth) < cvalid_d).astype(c.dtype)
            c = c * dmask[None, :, None, None]
        return c.astype(v.dtype)

    return restrict


def build_prolong_trilinear(grid, depth: int, valid_hw, block_hw,
                            boundary: str = "zero"):
    """Per-block trilinear prolongation ``(F, D/2, bh/2, bw/2) → (F, D,
    bh, bw)`` on the FINE level's mesh — three interleave passes over
    the depth-1 6-face-padded coarse block, one per axis, each the
    rank-2 centering rule verbatim (odd-centered zero reads the coarse
    ghost as 0 — the fine boundary line; even-centered periodic
    wraps)."""
    from parallel_convolution_tpu.volumes import halo3
    from parallel_convolution_tpu.volumes.forms import _valid_mask3

    _check_even_block3(depth, block_hw, "prolong_trilinear")
    periodic = boundary == "periodic"
    m = (int(depth) // 2, block_hw[0] // 2, block_hw[1] // 2)
    needs_mask = not periodic and (
        valid_hw[0] != block_hw[0] * grid[0]
        or valid_hw[1] != block_hw[1] * grid[1])

    def prolong(c):
        p = halo3.volume_halo_exchange(c, 1, grid, boundary)
        for axis in (1, 2, 3):
            n = m[axis - 1]
            sl_a = [slice(None)] * 4
            sl_b = [slice(None)] * 4
            if periodic:
                # Even-centered: fine 2k = coarse k; 2k+1 = mean(k, k+1).
                sl_a[axis], sl_b[axis] = slice(1, n + 1), slice(2, n + 2)
                a, b = p[tuple(sl_a)], p[tuple(sl_b)]
                p = _interleave(a, (a + b) * 0.5, axis)
            else:
                # Odd-centered: fine 2k+1 = coarse k; 2k = mean(k-1, k).
                sl_a[axis], sl_b[axis] = slice(0, n), slice(1, n + 1)
                a, b = p[tuple(sl_a)], p[tuple(sl_b)]
                p = _interleave((a + b) * 0.5, b, axis)
        if needs_mask:
            p = p * _valid_mask3(valid_hw, block_hw).astype(p.dtype)
        return p.astype(c.dtype)

    return prolong


def _register_transfer_forms() -> None:
    from parallel_convolution_tpu.utils.config import BOUNDARIES

    kernel_forms.register(kernel_forms.KernelForm(
        name="restrict_fw", rank=2, stencil_form="restrict",
        boundaries=tuple(BOUNDARIES), overlap_capable=False,
        build=build_restrict_fw))
    kernel_forms.register(kernel_forms.KernelForm(
        name="prolong_bilinear", rank=2, stencil_form="prolong",
        boundaries=tuple(BOUNDARIES), overlap_capable=False,
        build=build_prolong_bilinear))
    kernel_forms.register(kernel_forms.KernelForm(
        name="restrict_fw", rank=3, stencil_form="restrict",
        boundaries=tuple(BOUNDARIES), overlap_capable=False,
        build=build_restrict_fw3))
    kernel_forms.register(kernel_forms.KernelForm(
        name="prolong_trilinear", rank=3, stencil_form="prolong",
        boundaries=tuple(BOUNDARIES), overlap_capable=False,
        build=build_prolong_trilinear))


_register_transfer_forms()
