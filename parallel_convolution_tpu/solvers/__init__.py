"""Iterative solvers over the sharded stencil machinery.

``solvers.multigrid`` is the geometric multigrid V-cycle (round 15):
restriction and prolongation are themselves stencil forms registered in
the kernel-form registry (``parallel.kernels``), smoothing rides the
exact per-backend iterate programs ``parallel.step`` compiles, and
coarse levels collapse onto sub-grid meshes through the round-10
reshard machinery.  The solver registry (``SOLVERS``) lives in the
jax-free ``utils.config`` next to BACKENDS/STORAGES.
"""

from parallel_convolution_tpu.solvers import multigrid, transfer  # noqa: F401

__all__ = ["multigrid", "transfer"]
