"""Geometric multigrid V-cycle on the sharded halo machinery.

Plain Jacobi smoothing kills high-frequency error fast and low-frequency
error at ``1 - O(1/N²)`` per sweep — the reference's run-to-convergence
config needs O(N²) sweeps.  A V-cycle makes every frequency band
high-frequency on SOME grid: pre-smooth → restrict the residual to a
half-resolution grid → recursively solve the error equation there →
prolong the correction back → post-smooth.  Work per cycle is a
geometric series (each level is 4× cheaper), so the whole cycle costs a
few fine-grid sweeps while contracting error at a rate independent of N
— the orders-of-magnitude convergence win ROADMAP item 4 names.

Everything rides the existing machinery rather than re-implementing it:

* **Smoothing is the iterate path.**  Fine-level pre-smoothing is
  ``step._build_iterate`` and the post-smooth + convergence diff is
  ``step._build_converge_chunk`` — the same per-backend compiled
  programs (any registered smoother form, Pallas/RDMA included; the
  fine smoother inherits overlap legality from the kernel registry's
  capability bit).  Coarse levels smooth the error equation
  ``e ← mask(S e) + r`` — the SAME registry-built step plus the
  restricted residual, compiled per level.
* **Transfer operators are registered stencil forms.**
  ``restrict_fw`` / ``prolong_bilinear`` (solvers.transfer) resolve
  through ``parallel.kernels`` exactly like a backend does and run
  inside ``shard_map`` on the level's mesh over depth-1 halo exchanges.
* **Coarse levels collapse onto sub-grids.**  When a level's per-device
  block falls below the tile floor (``MG_BLOCK_FLOOR``), the level
  planner walks the r10 shrink ladder (halve the larger mesh axis) and
  the level state moves via the round-10 reshard rule (crop to valid,
  re-pad, re-shard — ``step.reshard_prepared``'s in-memory math) — a
  64-device mesh does not ppermute 4×4 blocks at the bottom of the
  cycle.

The equation solved is the one the Jacobi path already iterates:
``u = mask(S u)`` (S = the filter stencil, mask = the zero ghost-ring /
pad-rim invariant), i.e. ``A u = 0`` with ``A = I - mask·S``.  The
convergence measure is UNCHANGED from ``sharded_converge``: the max-abs
change of one fine-grid sweep (= the residual norm of A, up to sign),
read back per cycle — so multigrid's stopping rule, its progressive
stream rows, and its oracle comparisons all speak the same unit as the
Jacobi solver, and correctness never depends on coarse-level exactness
(coarse sloppiness only costs cycles, the fine-grid residual is the
judge).

Work accounting: a **fine-grid work unit** is one fine-level sweep's
worth of pixel updates.  Each level-ℓ sweep costs ``pxℓ/px0`` units;
restriction+prolongation together are charged one sweep at their fine
level.  ``work_units_to_tol`` is the number every convergence row
stamps and the ``--mg-smoke`` gate compares (multigrid must reach tol
in ≥10× fewer units than plain Jacobi on the same problem).
"""

from __future__ import annotations

import dataclasses
import time
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from parallel_convolution_tpu.obs import metrics as obs_metrics
from parallel_convolution_tpu.ops.filters import Filter
from parallel_convolution_tpu.parallel import kernels as kernel_forms
from parallel_convolution_tpu.parallel import step as step_lib
from parallel_convolution_tpu.parallel.mesh import (
    AXES, block_sharding, grid_shape, make_grid_mesh, padded_extent,
)
from parallel_convolution_tpu.resilience.faults import fault_point
from parallel_convolution_tpu.solvers.transfer import coarse_extent
from parallel_convolution_tpu.utils.jax_compat import shard_map

__all__ = ["MG_BLOCK_FLOOR", "MGResult", "Level", "cycle_work_units",
           "mg_converge", "mg_converge_stream", "plan_levels",
           "level_channel_keys", "warm_level_channels"]

# The tile floor: a level whose per-device block would dip below this on
# the inherited mesh collapses onto a smaller grid instead (sub-tile
# blocks are all rim — pure exchange latency, no compute to amortize it).
MG_BLOCK_FLOOR = 8
# Stop coarsening once the global extent is this small: the coarsest
# level is solved by smoothing alone, which is exact enough at 8x8.
MG_MIN_EXTENT = 8
MG_MAX_LEVELS = 12

# Default smoothing schedule: a V(2,2) cycle with a 16-sweep coarsest
# solve — the standard workhorse schedule (pre/post must stay small for
# the work-unit win; the coarsest grid is tiny so its sweeps are ~free).
NU_PRE = 2
NU_POST = 2
NU_COARSE = 16
# Damped-Jacobi relaxation weight, the standard 2D smoothing optimum.
# NOT optional: the undamped sweep leaves the checkerboard mode
# (eigenvalue −1) at full amplitude where full-weighting restriction
# cannot see it — measured as a dead stall at ~5e-2 residual on every
# grid depth ≥ 2 — while ω=4/5 contracts every high-frequency mode by
# ≥ 3/5 per sweep, which is what the coarse-grid correction needs.
OMEGA = 0.8


@dataclasses.dataclass(frozen=True)
class Level:
    """One grid level: its mesh, valid extent, and per-device block.

    ``block_hw * grid`` is the level's padded extent.  Every non-coarsest
    level has EVEN blocks (the planner pads to ``2*grid`` multiples) so
    restriction and prolongation stay device-local.
    """

    mesh: Mesh
    valid_hw: tuple[int, int]
    block_hw: tuple[int, int]

    @property
    def grid(self) -> tuple[int, int]:
        return grid_shape(self.mesh)

    @property
    def padded_hw(self) -> tuple[int, int]:
        g = self.grid
        return (self.block_hw[0] * g[0], self.block_hw[1] * g[1])


@dataclasses.dataclass
class MGResult:
    """The non-stream summary of one multigrid solve."""

    cycles: int
    work_units: float
    residual: float
    converged: bool
    levels: int
    level_grids: list[str]
    level_shapes: list[str]
    backend: str
    overlap: bool
    wall_s: float
    predicted_s_per_cycle: float | None = None
    col_mode: str = "strided"   # resolved column-slab transport of the
    #                            smoother programs (round 16)


def _level_block(valid_hw, grid, mult: int) -> tuple[int, int]:
    """Per-device block for ``valid_hw`` on ``grid`` padded to
    ``mult*grid`` multiples (mult=2 = the even-block rule)."""
    R, C = grid
    return (padded_extent(valid_hw[0], mult * R) // R,
            padded_extent(valid_hw[1], mult * C) // C)


def _fits(valid_hw, grid, mult: int, periodic: bool, floor: int) -> bool:
    bh, bw = _level_block(valid_hw, grid, mult)
    if periodic and (valid_hw[0] % (mult * grid[0])
                     or valid_hw[1] % (mult * grid[1])):
        # A torus level must keep valid == padded (halo wrap alignment).
        return False
    return min(bh, bw) >= floor


def _collapse(valid_hw, grid, mult: int, periodic: bool,
              floor: int) -> tuple[int, int] | None:
    """First rung of the shrink ladder (halve the larger axis — the r10
    ``grid_ladder`` walk) whose block clears the tile floor; None when
    even 1x1 cannot host the level (periodic misalignment)."""
    g = tuple(grid)
    while True:
        if _fits(valid_hw, g, mult, periodic, floor):
            return g
        if g == (1, 1):
            return None
        r, c = g
        g = (r, c // 2) if c >= r and c > 1 else (r // 2, c)


def plan_levels(mesh: Mesh, valid_hw, radius: int, boundary: str = "zero",
                mg_levels: int | None = None,
                floor: int = MG_BLOCK_FLOOR) -> list[Level]:
    """The level schedule: finest (the caller's mesh, as-is) down to the
    coarsest grid this problem/boundary supports.

    Rules, in order:

    * level 0 keeps the caller's mesh (the fine field lives there);
    * coarsening continues while ``mg_levels`` (when given) allows it,
      the global extent stays above ``MG_MIN_EXTENT``, and — for
      periodic boundaries — halving keeps torus alignment (even,
      grid-divisible extents);
    * every non-coarsest level pads its blocks EVEN (transfer locality);
    * a coarse level lands on the first shrink-ladder rung whose block
      clears ``floor`` (the coarse-grid reshard rule: state moves via
      crop-to-valid → re-pad → re-shard).
    """
    valid_hw = (int(valid_hw[0]), int(valid_hw[1]))
    periodic = boundary == "periodic"
    devices = list(mesh.devices.flat)
    cap = min(MG_MAX_LEVELS,
              mg_levels if mg_levels is not None else MG_MAX_LEVELS)
    if cap < 1:
        raise ValueError(f"mg_levels must be >= 1, got {mg_levels}")
    levels: list[Level] = []
    cur_valid, cur_grid = valid_hw, grid_shape(mesh)
    for idx in range(cap):
        more = (idx + 1 < cap and min(cur_valid) > MG_MIN_EXTENT
                and min(cur_valid) >= 2 * max(1, radius))
        if idx == 0:
            g = cur_grid  # the caller's mesh, never collapsed
            if more and periodic:
                # Even-block padding is always possible on the fine mesh
                # for zero boundaries; only a periodic misalignment
                # (torus levels must keep valid == padded) can veto
                # coarsening here.
                more = (cur_valid[0] % (2 * g[0]) == 0
                        and cur_valid[1] % (2 * g[1]) == 0)
        else:
            g = _collapse(cur_valid, cur_grid, 2 if more else 1,
                          periodic, floor)
            if more and g is None:
                more, g = False, _collapse(cur_valid, cur_grid, 1,
                                           periodic, floor)
            if g is None:
                break  # periodic level with no host at any rung: stop
        block = _level_block(cur_valid, g, 2 if more else 1)
        sub = (mesh if g == grid_shape(mesh)
               else make_grid_mesh(devices[: g[0] * g[1]], g))
        # Reuse the previous level's mesh object when the grid repeats,
        # so step/solver build caches key on ONE mesh per grid.
        if levels and levels[-1].grid == g:
            sub = levels[-1].mesh
        levels.append(Level(sub, cur_valid, block))
        if not more:
            break
        cur_valid = (coarse_extent(cur_valid[0], boundary),
                     coarse_extent(cur_valid[1], boundary))
        cur_grid = g
    return levels


def level_weights(levels) -> list[float]:
    """Fine-grid work units of ONE sweep at each level (pixel ratio)."""
    H0, W0 = levels[0].valid_hw
    return [(lv.valid_hw[0] * lv.valid_hw[1]) / float(H0 * W0)
            for lv in levels]


def cycle_work_units(levels, nu_pre: int = NU_PRE, nu_post: int = NU_POST,
                     nu_coarse: int = NU_COARSE) -> float:
    """Fine-grid work units of one V-cycle under the documented charge:
    every level-ℓ sweep costs its pixel ratio, the residual application
    is one sweep, restriction+prolongation together one more."""
    w = level_weights(levels)
    if len(levels) == 1:
        return (nu_pre + nu_post) * w[0]
    total = 0.0
    for i, wi in enumerate(w):
        if i == len(levels) - 1:
            total += nu_coarse * wi
        else:
            total += (nu_pre + nu_post + 1 + 1) * wi
    return total


def _level_sweeps(levels, nu_pre, nu_post, nu_coarse) -> list[int]:
    """Stencil applications per level per cycle (the obs attribution)."""
    if len(levels) == 1:
        return [nu_pre + nu_post]
    return [(nu_coarse if i == len(levels) - 1 else nu_pre + nu_post + 1)
            for i in range(len(levels))]


def level_channel_keys(levels, radius: int, boundary: str,
                       col_mode: str, channels: int = 1,
                       storage: str = "f32"):
    """The per-level persistent-channel identities of one V-cycle
    schedule (round 16): each level's exchange identity
    ``(grid, block, radius, fuse=1, dtype, boundary, kernel_form,
    col_mode)``, computed ONCE on the schedule and warmed into the
    channel-plan cache — every cycle's smoother kernels then BIND the
    same cached plans, so ``channels.stats()['builds']`` equals the
    number of distinct level identities however many cycles run
    (asserted in tests/test_channels.py)."""
    from parallel_convolution_tpu.parallel import channels as chan
    from parallel_convolution_tpu.tuning import costmodel

    dtype = {"f32": "float32", "bf16": "bfloat16", "u8": "uint8"}[storage]
    keys = []
    for lv in levels:
        tiled = costmodel.rdma_is_tiled(
            (channels, *lv.padded_hw), lv.block_hw, int(radius), 1,
            storage, col_mode=col_mode, grid=lv.grid)
        keys.append(chan.ChannelKey(
            grid=lv.grid, block_hw=lv.block_hw, radius=int(radius),
            fuse=1, dtype=dtype, boundary=boundary,
            kernel="tiled" if tiled else "monolithic",
            col_mode=col_mode))
    return tuple(keys)


def warm_level_channels(levels, radius: int, boundary: str, col_mode: str,
                        channels: int = 1, storage: str = "f32"):
    """Bind every level's channel plan up front (idempotent — repeat
    calls hit the cache); returns the identity tuple."""
    from parallel_convolution_tpu.parallel import channels as chan

    keys = level_channel_keys(levels, radius, boundary, col_mode,
                              channels, storage)
    for k in keys:
        chan.plan_for(k)
    return keys


# -- compiled level programs (lru-cached like step's builders) -------------

_SPEC = P(None, *AXES)


@lru_cache(maxsize=128)
def _build_smooth_rhs(mesh: Mesh, filt: Filter, n: int, valid_hw, block_hw,
                      backend: str, boundary: str,
                      tile: tuple[int, int] | None,
                      col_mode: str = "strided"):
    """``n`` damped error-equation sweeps:
    ``e ← (1−ω)·e + ω·(mask(S e) + r)``.

    The step is the registry-built smoother form (the SAME program the
    iterate path compiles, fuse=1, float carry); the restricted residual
    ``r`` is masked, so the convex combination keeps the masking
    invariant.  ω is :data:`OMEGA` — see its definition note for why the
    undamped sweep cannot serve as a multigrid smoother.
    """
    fault_point("backend_compile")  # lru_cache miss == a fresh compile
    grid = grid_shape(mesh)
    step_lib._check_block_size(filt, block_hw)
    step_lib._note_compile("mg_smooth", backend, grid, n, 1, boundary,
                           block_hw)
    step = step_lib._make_block_step(
        filt, grid, valid_hw, block_hw, False, backend, 1, boundary, tile,
        step_lib._mesh_interpret(mesh), False, False, col_mode)

    def body(e, r):
        def sweep(_, v):
            return ((1.0 - OMEGA) * v + OMEGA * (step(v) + r)).astype(
                e.dtype)

        return lax.fori_loop(0, n, sweep, e)

    sharded = shard_map(body, mesh=mesh, in_specs=(_SPEC, _SPEC),
                        out_specs=_SPEC, check_vma=False)
    return jax.jit(sharded, donate_argnums=0)


@lru_cache(maxsize=128)
def _build_fine_smooth(mesh: Mesh, filt: Filter, n: int, valid_hw, block_hw,
                       backend: str, boundary: str,
                       tile: tuple[int, int] | None, overlap: bool,
                       with_diff: bool, col_mode: str = "strided"):
    """``n`` damped fine-grid sweeps of the homogeneous equation:
    ``u ← (1−ω)·u + ω·mask(S u)``.

    The step is the registry-resolved smoother form — the identical
    per-block program ``step._build_iterate`` compiles (fuse=1), RDMA
    overlap included when the fine level's resolved knob says so, so the
    fine smoother inherits every backend lever the iterate path has.

    ``with_diff=True`` additionally returns the max-abs UNDAMPED sweep
    change ``max|S u − u|`` observed at the last sweep — exactly the
    convergence measure ``sharded_converge`` stops on (for undamped
    Jacobi the sweep change IS that residual), so multigrid's stopping
    rule, its stream rows, and its oracle comparisons all speak the same
    unit as the plain solver.  Computed from the last sweep's own
    stencil application: the measure costs nothing extra.
    """
    fault_point("backend_compile")
    grid = grid_shape(mesh)
    step_lib._check_block_size(filt, block_hw)
    step_lib._note_compile("mg_fine", backend, grid, n, 1, boundary,
                           block_hw)
    step = step_lib._make_block_step(
        filt, grid, valid_hw, block_hw, False, backend, 1, boundary, tile,
        step_lib._mesh_interpret(mesh), False, overlap, col_mode)

    def damped(v, s):
        return ((1.0 - OMEGA) * v + OMEGA * s).astype(v.dtype)

    if with_diff:
        def body(u):
            u = lax.fori_loop(0, max(0, n - 1),
                              lambda _, v: damped(v, step(v)), u)
            s = step(u)
            delta = jnp.abs(s.astype(jnp.float32) - u.astype(jnp.float32))
            diff = lax.pmax(jnp.max(delta), AXES)
            return damped(u, s), diff

        out_specs = (_SPEC, P())
    else:
        def body(u):
            return lax.fori_loop(0, n, lambda _, v: damped(v, step(v)), u)

        out_specs = _SPEC
    sharded = shard_map(body, mesh=mesh, in_specs=_SPEC,
                        out_specs=out_specs, check_vma=False)
    return jax.jit(sharded, donate_argnums=0)


@lru_cache(maxsize=128)
def _build_residual_restrict(mesh: Mesh, filt: Filter, valid_hw, block_hw,
                             backend: str, boundary: str,
                             tile: tuple[int, int] | None, fine: bool,
                             col_mode: str = "strided"):
    """Residual + full-weighting restriction in ONE compiled program.

    ``fine=True``  : ``u → 4·restrict(S u − u)``  (the homogeneous fine
    equation ``A u = 0``: rhs is zero).
    ``fine=False`` : ``(e, r) → 4·restrict(S e + r − e)`` (a coarse
    level's error equation ``A e = r``).

    The ×4 is the coarse-grid operator scaling: ``A = I − S`` is the
    UNDIVIDED second-order operator (``(h²/4)·Δ`` for the 5-point
    ``jacobi3``), so halving the resolution quadruples the coarse
    ``A_2h`` on smooth modes — the restricted residual must carry the
    same factor or every coarse correction lands 4× too weak and the
    cycle degenerates to barely-better-than-smoothing (measured: 3913
    cycles vs ~15 on a 96² seeded problem).

    The restriction operator resolves through the kernel-form registry
    (``restrict_fw``) — the transfer stencil is dispatched exactly like
    a backend.
    """
    fault_point("backend_compile")
    grid = grid_shape(mesh)
    step_lib._check_block_size(filt, block_hw)
    step_lib._note_compile("mg_restrict", backend, grid, 1, 1, boundary,
                           block_hw)
    step = step_lib._make_block_step(
        filt, grid, valid_hw, block_hw, False, backend, 1, boundary, tile,
        step_lib._mesh_interpret(mesh), False, False, col_mode)
    restrict = kernel_forms.resolve(2, "restrict_fw", boundary).build(
        grid, valid_hw, block_hw, boundary)

    if fine:
        def body(u):
            return 4.0 * restrict((step(u) - u).astype(jnp.float32))

        in_specs = _SPEC
    else:
        def body(e, r):
            return 4.0 * restrict((step(e) + r - e).astype(jnp.float32))

        in_specs = (_SPEC, _SPEC)
    sharded = shard_map(body, mesh=mesh, in_specs=in_specs,
                        out_specs=_SPEC, check_vma=False)
    return jax.jit(sharded)  # no donation: the caller still needs u/e


@lru_cache(maxsize=128)
def _build_prolong_correct(mesh: Mesh, valid_hw, block_hw, boundary: str):
    """``(u, e_c) → u + prolong(e_c)`` — bilinear prolongation (the
    registry's ``prolong_bilinear`` form) fused with the correction
    add on the FINE level's mesh."""
    fault_point("backend_compile")
    grid = grid_shape(mesh)
    step_lib._note_compile("mg_prolong", "prolong_bilinear", grid, 1, 1,
                           boundary, block_hw)
    prolong = kernel_forms.resolve(2, "prolong_bilinear", boundary).build(
        grid, valid_hw, block_hw, boundary)

    def body(u, ec):
        return (u + prolong(ec).astype(u.dtype)).astype(u.dtype)

    sharded = shard_map(body, mesh=mesh, in_specs=(_SPEC, _SPEC),
                        out_specs=_SPEC, check_vma=False)
    return jax.jit(sharded, donate_argnums=(0, 1))


def _fit_to(xs, valid_hw, mesh: Mesh, block_hw, src_mesh: Mesh):
    """Move a level state onto ``(mesh, block_hw)`` — the coarse-grid
    reshard rule (r10 machinery): crop to the valid extent, re-pad to
    the target blocks, re-shard.  Identity (no copy) when the state is
    already there; otherwise one small host round-trip — coarse levels
    are tiny by construction."""
    H, W = (int(v) for v in valid_hw)
    R, C = grid_shape(mesh)
    target = (block_hw[0] * R, block_hw[1] * C)
    if src_mesh is mesh and (xs.shape[1], xs.shape[2]) == target:
        return xs
    x = np.asarray(xs)[:, :H, :W]
    if (target[0], target[1]) != (H, W):
        x = np.pad(x, ((0, 0), (0, target[0] - H), (0, target[1] - W)))
    return jax.device_put(x, block_sharding(mesh))


# -- the solver ------------------------------------------------------------


def _mg_obs(levels, sweeps, filt, backend: str, channels: int,
            boundary: str, overlap: bool, cycle_wall: float) -> None:
    """Per-cycle telemetry: one exchange/compute attribution per LEVEL
    (``pctpu_mg_level``-labeled sweep counter + the exchange event with
    the level stamped), plus the cycle-wall histogram."""
    if not obs_metrics.enabled():
        return
    from parallel_convolution_tpu.obs import attribution

    for i, (lv, n) in enumerate(zip(levels, sweeps)):
        dev0 = lv.mesh.devices.flat[0]
        attribution.record_step(
            backend=backend, grid=lv.grid, block_hw=lv.block_hw,
            radius=filt.radius, fuse=1, iters=n, channels=channels,
            storage="f32", boundary=boundary, wall_s=None,
            shape=(channels, *lv.padded_hw), quantize=False, tile=None,
            platform=dev0.platform,
            device_kind=getattr(dev0, "device_kind", "") or "",
            source="multigrid", overlap=overlap and i == 0,
            mg_level=i)
    obs_metrics.histogram(
        "pctpu_mg_cycle_seconds", "wall of one multigrid V-cycle",
        ("backend",)).observe(cycle_wall, backend=backend)


def _predict_cycle_seconds(levels, sweeps, filt, backend: str,
                           channels: int, quantize: bool,
                           tile) -> float | None:
    """Cost-model price of one V-cycle: the SUM of its per-level sweep
    costs (``costmodel.predict_vcycle_seconds``) — coarse levels are
    cheaper, never free, so ``backend="auto"`` comparisons against a
    single-level solver stay honest."""
    try:
        from parallel_convolution_tpu.tuning import costmodel

        terms = []
        for lv, n in zip(levels, sweeps):
            dev0 = lv.mesh.devices.flat[0]
            hw = costmodel.hardware_for(
                dev0.platform, getattr(dev0, "device_kind", "") or "")
            spp = costmodel.predict_seconds_per_px_iter(
                backend, "f32", 1, tile, (channels, *lv.valid_hw),
                lv.block_hw, lv.grid, filt.size,
                backend in ("separable", "pallas_sep"), quantize, hw)
            terms.append((spp, channels * lv.valid_hw[0] * lv.valid_hw[1],
                          n))
        return costmodel.predict_vcycle_seconds(terms)
    except Exception:  # noqa: BLE001 — pricing must never kill a solve
        return None


def mg_converge_stream(x, filt: Filter, tol: float, max_iters: int,
                       mesh: Mesh | None = None, quantize: bool = False,
                       backend: str = "shifted", storage: str = "f32",
                       boundary: str = "zero",
                       fuse: int | None = 1,
                       tile: tuple[int, int] | None = None,
                       fallback: bool = False,
                       overlap: bool | None = None,
                       mg_levels: int | None = None,
                       col_mode: str | None = None,
                       nu_pre: int = NU_PRE, nu_post: int = NU_POST,
                       nu_coarse: int = NU_COARSE):
    """Progressive multigrid solve: a generator over V-cycle snapshots.

    Yields ``(image_f32, cycles_done, residual, work_units)`` after every
    V-cycle — ``residual`` is the max-abs change of one fine-grid sweep,
    the SAME measure ``sharded_converge`` stops on, read back per cycle
    (the readback is the fence).  The stream ends when ``residual <
    tol`` or the fine-grid work-unit budget ``max_iters`` is exhausted.

    ``quantize`` must be False and ``storage`` f32: multigrid corrections
    are signed float fields — a u8 store-back would clamp the error
    equation to garbage (typed ValueError, the serving layer's
    ``invalid``).  ``fuse`` is accepted for signature parity and ignored
    (smoothing sweeps are fuse=1; the V-cycle itself is the
    exchange-amortization lever here).  ``backend`` names the smoother
    form (``auto`` resolves through the tuning subsystem); transfer
    operators always run their registered stencils.
    """
    if quantize:
        raise ValueError(
            "solver='multigrid' requires quantize=False: corrections are "
            "signed float fields (u8 store-back would clamp the error "
            "equation)")
    if storage != "f32":
        raise ValueError(
            f"solver='multigrid' requires storage='f32', got {storage!r} "
            "(residual/correction fields need full float carries)")
    if mesh is None:
        mesh = make_grid_mesh()
    x = np.asarray(x, np.float32)
    channels, H, W = x.shape
    valid_hw = (int(H), int(W))
    backend, _, tile, overlap, col_mode, _ = step_lib._resolve_auto(
        mesh, filt, backend, fuse, tile, storage, quantize, boundary,
        valid_hw, channels, overlap=overlap, col_mode=col_mode)
    overlap = step_lib.resolve_overlap(overlap, backend, mesh)
    tile = step_lib._norm_tile(tile)
    levels = plan_levels(mesh, valid_hw, filt.radius, boundary, mg_levels)
    fine = levels[0]
    col_mode = step_lib.resolve_col_mode(
        col_mode, backend, mesh, fine.block_hw, filt.radius, 1, storage)
    if fallback:
        # Probe on the REAL fine-level block (plan_levels pads even only
        # when a coarser level follows) — kernel-family selection keys on
        # block_hw, so a mult=2 guess could pass a probe the mult=1
        # launch then fails.
        backend = step_lib._resolve_fallback(
            mesh, filt, backend, quantize, 1, boundary, tile, False,
            storage=storage, block_hw=fine.block_hw, overlap=overlap,
            col_mode=col_mode)
        overlap = kernel_forms.clamp_overlap(overlap, backend)
        col_mode = step_lib.clamp_col_mode(col_mode, backend)
    if kernel_forms.persistent_capable(backend):
        # Cache each level's exchange identity on the schedule up front:
        # every cycle's smoother kernels bind these SAME plans.
        warm_level_channels(levels, filt.radius, boundary, col_mode,
                            channels, storage)
    sweeps = _level_sweeps(levels, nu_pre, nu_post, nu_coarse)
    wu_cycle = cycle_work_units(levels, nu_pre, nu_post, nu_coarse)
    u = _fit_to(x, valid_hw, fine.mesh, fine.block_hw, src_mesh=None)

    def coarse_cycle(i: int, r):
        """Solve ``A e = r`` on level ``i`` (one recursive V leg)."""
        lv = levels[i]
        e = jnp.zeros_like(r)
        if i == len(levels) - 1:
            return _build_smooth_rhs(
                lv.mesh, filt, nu_coarse, lv.valid_hw, lv.block_hw,
                backend, boundary, tile, col_mode)(e, r)
        e = _build_smooth_rhs(lv.mesh, filt, nu_pre, lv.valid_hw,
                              lv.block_hw, backend, boundary, tile,
                              col_mode)(e, r)
        rc = _build_residual_restrict(
            lv.mesh, filt, lv.valid_hw, lv.block_hw, backend, boundary,
            tile, False, col_mode)(e, r)
        nxt = levels[i + 1]
        rc = _fit_to(rc, nxt.valid_hw, nxt.mesh, nxt.block_hw,
                     src_mesh=lv.mesh)
        ec = coarse_cycle(i + 1, rc)
        ec = _fit_to(ec, nxt.valid_hw, lv.mesh,
                     (lv.block_hw[0] // 2, lv.block_hw[1] // 2),
                     src_mesh=nxt.mesh)
        e = _build_prolong_correct(lv.mesh, lv.valid_hw, lv.block_hw,
                                   boundary)(e, ec)
        return _build_smooth_rhs(lv.mesh, filt, nu_post, lv.valid_hw,
                                 lv.block_hw, backend, boundary, tile,
                                 col_mode)(e, r)

    cycles, wu, diff = 0, 0.0, float("inf")
    max_wu = float(max_iters)
    while wu < max_wu and diff >= tol:
        t0 = time.perf_counter()
        if len(levels) == 1:
            # Degenerate single-level schedule: the cycle is pure damped
            # smoothing (plan_levels refused to coarsen — tiny image or
            # periodic misalignment).
            u, d = _build_fine_smooth(
                fine.mesh, filt, nu_pre + nu_post, fine.valid_hw,
                fine.block_hw, backend, boundary, tile, overlap, True,
                col_mode)(u)
        else:
            u = _build_fine_smooth(
                fine.mesh, filt, nu_pre, fine.valid_hw, fine.block_hw,
                backend, boundary, tile, overlap, False, col_mode)(u)
            rc = _build_residual_restrict(
                fine.mesh, filt, fine.valid_hw, fine.block_hw, backend,
                boundary, tile, True, col_mode)(u)
            nxt = levels[1]
            rc = _fit_to(rc, nxt.valid_hw, nxt.mesh, nxt.block_hw,
                         src_mesh=fine.mesh)
            ec = coarse_cycle(1, rc)
            ec = _fit_to(ec, nxt.valid_hw, fine.mesh,
                         (fine.block_hw[0] // 2, fine.block_hw[1] // 2),
                         src_mesh=nxt.mesh)
            u = _build_prolong_correct(
                fine.mesh, fine.valid_hw, fine.block_hw, boundary)(u, ec)
            # Post-smooth + the residual readout in one compiled program
            # — the last sweep's undamped change ``max|S u − u|`` is the
            # residual norm the stream reports and the stopping rule
            # reads (the same measure sharded_converge stops on).
            u, d = _build_fine_smooth(
                fine.mesh, filt, nu_post, fine.valid_hw, fine.block_hw,
                backend, boundary, tile, overlap, True, col_mode)(u)
        diff = float(d)   # the readback fences the cycle
        cycles += 1
        wu += wu_cycle
        _mg_obs(levels, sweeps, filt, backend, channels, boundary, overlap,
                time.perf_counter() - t0)
        yield (np.asarray(u[:, :H, :W].astype(jnp.float32)), cycles,
               diff, round(wu, 3))


def mg_converge(x, filt: Filter, tol: float, max_iters: int,
                mesh: Mesh | None = None, quantize: bool = False,
                backend: str = "shifted", storage: str = "f32",
                boundary: str = "zero", fuse: int | None = 1,
                tile: tuple[int, int] | None = None,
                fallback: bool = False, overlap: bool | None = None,
                mg_levels: int | None = None,
                col_mode: str | None = None,
                nu_pre: int = NU_PRE, nu_post: int = NU_POST,
                nu_coarse: int = NU_COARSE) -> tuple[np.ndarray, MGResult]:
    """Run the V-cycle to convergence; returns ``(field_f32, MGResult)``.

    ``max_iters`` bounds FINE-GRID WORK UNITS (the same budget a plain
    Jacobi run would spend as iterations), so the two solvers are
    comparable under one cap.
    """
    if mesh is None:
        mesh = make_grid_mesh()
    x = np.asarray(x, np.float32)
    channels = x.shape[0]
    levels = plan_levels(mesh, x.shape[1:], filt.radius, boundary,
                         mg_levels)
    sweeps = _level_sweeps(levels, nu_pre, nu_post, nu_coarse)
    t0 = time.perf_counter()
    out, cycles, diff, wu = x, 0, float("inf"), 0.0
    stream = mg_converge_stream(
        x, filt, tol, max_iters, mesh=mesh, quantize=quantize,
        backend=backend, storage=storage, boundary=boundary, fuse=fuse,
        tile=tile, fallback=fallback, overlap=overlap, mg_levels=mg_levels,
        col_mode=col_mode, nu_pre=nu_pre, nu_post=nu_post,
        nu_coarse=nu_coarse)
    for out, cycles, diff, wu in stream:
        pass
    # Post-resolution stamps: re-derive what the stream compiled with
    # (same resolution path, idempotent) so the result row can never
    # disagree with the program that produced it.
    b, _, tl, ov, cm, _ = step_lib._resolve_auto(
        mesh, filt, backend, fuse, tile, storage, quantize, boundary,
        tuple(int(v) for v in x.shape[1:]), channels, overlap=overlap,
        col_mode=col_mode)
    ov = step_lib.resolve_overlap(ov, b, mesh)
    cm = step_lib.resolve_col_mode(cm, b, mesh, levels[0].block_hw,
                                   filt.radius, 1, storage)
    if fallback:
        from parallel_convolution_tpu.resilience import degrade

        b = degrade.effective_for(b) or b
        ov = kernel_forms.clamp_overlap(ov, b)
        cm = step_lib.clamp_col_mode(cm, b)
    eff_backend, eff_overlap = b, ov
    res = MGResult(
        cycles=cycles, work_units=round(wu, 3), residual=diff,
        converged=diff < tol, levels=len(levels),
        level_grids=[f"{lv.grid[0]}x{lv.grid[1]}" for lv in levels],
        level_shapes=[f"{lv.valid_hw[0]}x{lv.valid_hw[1]}" for lv in levels],
        backend=eff_backend, overlap=eff_overlap, col_mode=cm,
        wall_s=round(time.perf_counter() - t0, 4),
        predicted_s_per_cycle=_predict_cycle_seconds(
            levels, sweeps, filt, eff_backend, channels, False,
            step_lib._norm_tile(tile)))
    return out, res
