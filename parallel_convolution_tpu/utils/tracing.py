"""Tracing / profiling hooks (SURVEY.md §5 aux subsystems).

The reference's only observability is hand-placed ``MPI_Wtime`` segments
printed at the end.  Here:

* :class:`PhaseTimer` — named wall-clock phases (load / compile / iterate /
  write) with a structured report, the upgrade over printf timings.  Device
  work is fenced with ``jax.block_until_ready`` so a phase means what it
  says under async dispatch.  Phases NEST (``queue`` around
  ``compile``/``device``/``copy`` is the serving layer's per-request
  breakdown); nested walls are recorded under slash-joined paths and a
  flat :meth:`PhaseTimer.to_row` export merges them into bench-row dicts.
* :func:`device_trace` — context manager around ``jax.profiler.trace``;
  writes a TensorBoard/Perfetto trace of the XLA execution (the real
  per-op timeline the reference never had).
"""

from __future__ import annotations

import contextlib
import json
import threading
import time

import jax


class PhaseTimer:
    """Accumulate named wall-clock phases.

    >>> t = PhaseTimer()
    >>> with t.phase("iterate"):
    ...     out = run()          # doctest: +SKIP
    >>> t.report()               # doctest: +SKIP

    Phases nest: entering ``phase("device")`` inside ``phase("serve")``
    accumulates under the path ``"serve/device"`` while ``"serve"`` keeps
    the enclosing wall — so a report's top-level walls stay additive and
    nested ones attribute where the time inside them went.

    Thread-safe (round 11): the nesting stack is **thread-local** — each
    thread nests against its own enclosing phases, never another
    thread's — and the accumulated walls/counts are lock-protected.  A
    timer shared between the batcher worker and HTTP handler threads
    therefore records correct per-thread paths instead of silently
    corrupting one shared stack (the pre-round-11 failure mode, pinned
    by ``tests/test_obs.py::test_phase_timer_thread_safety``).
    """

    def __init__(self) -> None:
        self.walls: dict[str, float] = {}
        self.counts: dict[str, int] = {}
        self._lock = threading.Lock()
        self._tls = threading.local()

    def _stack(self) -> list[str]:
        s = getattr(self._tls, "stack", None)
        if s is None:
            s = self._tls.stack = []
        return s

    @contextlib.contextmanager
    def phase(self, name: str, fence=None):
        """Time a phase; ``fence`` (a jax value/tree) is block_until_ready'd
        before the clock stops so async device work is charged here."""
        stack = self._stack()
        stack.append(name)
        path = "/".join(stack)
        t0 = time.perf_counter()
        try:
            yield
        finally:
            try:
                if fence is not None:
                    jax.block_until_ready(fence)
            finally:
                # Record + pop even when the body OR the fence raised:
                # a failing phase must not corrupt the nesting stack (the
                # fault/retry paths re-enter the same timer afterwards).
                dt = time.perf_counter() - t0
                stack.pop()
                with self._lock:
                    self.walls[path] = self.walls.get(path, 0.0) + dt
                    self.counts[path] = self.counts.get(path, 0) + 1

    def report(self) -> dict:
        with self._lock:
            walls = dict(self.walls)
            counts = dict(self.counts)
        # Total sums only TOP-LEVEL phases: a nested wall is already inside
        # its parent's, so summing every path would double-count it.
        total = sum(v for k, v in walls.items() if "/" not in k)
        return {
            "total_s": round(total, 4),
            "phases": {
                k: {"wall_s": round(v, 4), "calls": counts[k],
                    "share": round(v / total, 3) if total else 0.0}
                for k, v in sorted(walls.items(), key=lambda kv: -kv[1])
            },
        }

    def to_row(self, prefix: str = "phase_", scale: float = 1.0,
               digits: int = 6) -> dict:
        """Flat ``{prefix<path>_s: wall}`` dict for merging into bench rows.

        Nested paths flatten with underscores (``serve/device`` →
        ``phase_serve_device_s``).  ``scale`` converts units (1e3 = ms, with
        the key suffix left to the caller's prefix convention); the serving
        latency breakdown merges this straight into its per-request and
        loadgen rows.

        Flattening can collide: the nested path ``a/b`` and a top-level
        phase literally named ``a_b`` map to the same row key.  Colliding
        walls are SUMMED — a collision may blur attribution between two
        sources but can never silently drop one of them (pinned in
        tests/test_obs.py).
        """
        with self._lock:
            walls = dict(self.walls)
        out: dict[str, float] = {}
        for k, v in walls.items():
            key = f"{prefix}{k.replace('/', '_')}_s"
            out[key] = out.get(key, 0.0) + v * scale
        return {k: round(v, digits) for k, v in out.items()}

    def wall(self, name: str) -> float:
        """Accumulated seconds for one phase path (0.0 if never entered)."""
        with self._lock:
            return self.walls.get(name, 0.0)

    def dump(self, path) -> None:
        with open(path, "w") as f:  # diskio: exempt — exit-time report
            json.dump(self.report(), f, indent=2)


@contextlib.contextmanager
def device_trace(logdir: str):
    """Capture an XLA device trace viewable in TensorBoard / Perfetto."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
