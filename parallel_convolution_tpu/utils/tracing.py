"""Tracing / profiling hooks (SURVEY.md §5 aux subsystems).

The reference's only observability is hand-placed ``MPI_Wtime`` segments
printed at the end.  Here:

* :class:`PhaseTimer` — named wall-clock phases (load / compile / iterate /
  write) with a structured report, the upgrade over printf timings.  Device
  work is fenced with ``jax.block_until_ready`` so a phase means what it
  says under async dispatch.
* :func:`device_trace` — context manager around ``jax.profiler.trace``;
  writes a TensorBoard/Perfetto trace of the XLA execution (the real
  per-op timeline the reference never had).
"""

from __future__ import annotations

import contextlib
import json
import time

import jax


class PhaseTimer:
    """Accumulate named wall-clock phases.

    >>> t = PhaseTimer()
    >>> with t.phase("iterate"):
    ...     out = run()          # doctest: +SKIP
    >>> t.report()               # doctest: +SKIP
    """

    def __init__(self) -> None:
        self.walls: dict[str, float] = {}
        self.counts: dict[str, int] = {}

    @contextlib.contextmanager
    def phase(self, name: str, fence=None):
        """Time a phase; ``fence`` (a jax value/tree) is block_until_ready'd
        before the clock stops so async device work is charged here."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            if fence is not None:
                jax.block_until_ready(fence)
            dt = time.perf_counter() - t0
            self.walls[name] = self.walls.get(name, 0.0) + dt
            self.counts[name] = self.counts.get(name, 0) + 1

    def report(self) -> dict:
        total = sum(self.walls.values())
        return {
            "total_s": round(total, 4),
            "phases": {
                k: {"wall_s": round(v, 4), "calls": self.counts[k],
                    "share": round(v / total, 3) if total else 0.0}
                for k, v in sorted(self.walls.items(), key=lambda kv: -kv[1])
            },
        }

    def dump(self, path) -> None:
        with open(path, "w") as f:
            json.dump(self.report(), f, indent=2)


@contextlib.contextmanager
def device_trace(logdir: str):
    """Capture an XLA device trace viewable in TensorBoard / Perfetto."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
