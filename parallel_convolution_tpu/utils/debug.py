"""Numeric-safety tooling (SURVEY.md §5 'race detection / sanitizers').

Races are impossible by construction in this framework (pure functional
JAX; the reference's OpenMP loop needed its no-shared-writes discipline),
so the sanitizer tier here guards the remaining failure class: numeric
corruption — NaN/Inf escaping a kernel, or u8-mode values leaving
[0, 255].

* :func:`checked_correlate` — ``checkify``-wrapped stencil step that turns
  NaN/Inf into a Python-level error instead of silent propagation.
* :func:`assert_u8_range` / :func:`find_nonfinite` — host-side validators
  used by tests and debugging sessions.
* For Pallas-kernel debugging, run with ``interpret=True`` (exact same
  kernel code on CPU) — see ops/pallas_stencil.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import checkify

from parallel_convolution_tpu.ops import conv
from parallel_convolution_tpu.ops.filters import Filter


def checked_correlate(x: jnp.ndarray, filt: Filter):
    """One stencil step with NaN/Inf checking compiled in.

    Returns the output; raises ``checkify.JaxRuntimeError`` describing the
    first non-finite value if the input (or filter) produced one.
    """

    def f(v):
        out = conv.correlate_shifted(v, filt)
        checkify.check(
            jnp.isfinite(out).all(), "non-finite value in stencil output"
        )
        return out

    err, out = checkify.checkify(jax.jit(f))(x)
    err.throw()
    return out


def assert_u8_range(arr) -> None:
    """Validate the u8-mode invariant: exact integers in [0, 255]."""
    a = np.asarray(arr)
    if a.size == 0:
        return
    bad = ~((a >= 0) & (a <= 255) & (a == np.rint(a)))
    if bad.any():
        idx = tuple(int(i) for i in np.argwhere(bad)[0])
        raise AssertionError(
            f"u8-mode invariant violated at {idx}: value {a[bad][0]!r}"
        )


def find_nonfinite(arr) -> list[tuple]:
    """Indices (up to 10) of NaN/Inf values, for post-mortem debugging."""
    a = np.asarray(arr)
    return [tuple(int(i) for i in ix) for ix in np.argwhere(~np.isfinite(a))[:10]]
