"""Functional halo-p50 proxy on a forced multi-device CPU mesh.

One real TPU chip is a 1×1 mesh, where the halo exchange compiles to no
collective at all — the BASELINE halo-p50 metric is unmeasurable there
(``bench_halo_p50`` refuses with a sentinel).  This module is the honest
stand-in the driver can still record: run the *same compiled two-phase
ppermute exchange* on an 8-virtual-device CPU mesh in a fresh process and
report its p50, clearly labeled as a CPU functional proxy (it validates
the mechanism and gives a magnitude, not ICI latency).

Run as ``python -m parallel_convolution_tpu.utils.halo_proxy`` with a clean
environment; prints ONE JSON line (or one line per config under
``--sweep``, the block-size/radius scaling record — note
``run_in_subprocess`` parses only the LAST line and never passes
--sweep).  A subprocess is required because the parent's jax is already
initialized on the TPU platform.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys


def main() -> int:
    from parallel_convolution_tpu.utils.platform import force_platform

    force_platform("cpu")

    import jax

    from parallel_convolution_tpu.parallel.mesh import make_grid_mesh
    from parallel_convolution_tpu.utils import bench

    devs = jax.devices()
    if len(devs) < 2 or devs[0].platform != "cpu":
        print(json.dumps({"error": f"need >=2 cpu devices, have "
                          f"{len(devs)} {devs[0].platform if devs else '-'}"}))
        return 1
    mesh = make_grid_mesh(devs)

    def one(block, r, trials=12):
        # Each trial is already a DIFFERENCED amortized 256-round span —
        # live ghost-consuming exchange rounds minus local-roll control
        # rounds (final round-5 bench_halo_p50 definition; the first
        # revision's un-differenced chained round was elided by XLA to
        # zero collectives and is void) — so a dozen trials replace the
        # old 60-deep median over single dispatches whose p50 swung 10×
        # across identical-code driver runs.
        row = bench.bench_halo_p50(block, r=r, mesh=mesh, trials=trials)
        row["proxy"] = "cpu-mesh"
        row["devices"] = len(devs)
        return row

    if "--sweep" in sys.argv:
        # Scaling record: latency vs per-device block size and radius
        # (the reference's small-block latency-bound regime, SURVEY §3.2).
        # >= 11 trials so p90 (times[int(n*0.9)]) is a percentile, not the
        # max sample wearing a percentile's name.
        for block, r in (((64, 64), 1), ((256, 256), 1), ((512, 512), 1),
                         ((1024, 1024), 1), ((512, 512), 2)):
            print(json.dumps(one(block, r)), flush=True)
        return 0
    print(json.dumps(one((512, 512), 1)))
    return 0


def run_in_subprocess(n_devices: int = 8, timeout: float = 600.0) -> dict:
    """Launch the proxy in a clean child process and parse its JSON row.

    Returns ``{"error": ...}`` instead of raising so benchmark drivers can
    record the failure without dying.
    """
    from parallel_convolution_tpu.utils.platform import child_env_cpu

    env = child_env_cpu(n_devices)
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "parallel_convolution_tpu.utils.halo_proxy"],
            env=env, capture_output=True, text=True, timeout=timeout,
        )
        return json.loads(proc.stdout.strip().splitlines()[-1])
    except Exception as e:
        return {"error": repr(e)}


if __name__ == "__main__":
    sys.exit(main())
