"""Sharded raw-image load/save: no host buffer ever holds the full image.

SURVEY.md §7 hard parts: the 65536×65536 RGB config is a 12.9 GB uint8
file — the reference reads per-rank blocks via MPI-IO offsets; here
:func:`jax.make_array_from_callback` asks for exactly each addressable
device's block, which we serve straight from the file with
``utils.imageio.read_block`` (NumPy memmap windows; the native C++ reader
when built).  The result is born with the padded P(None,'x','y') layout the
sharded step wants — zero-filled in the pad rim, planar float32.

Saving walks ``arr.addressable_shards`` and writes each block's valid
intersection at its file offset (``MPI_File_write_at``).  On a multi-host
deployment every host does this for its own shards only.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

from parallel_convolution_tpu.parallel.mesh import (
    block_sharding, grid_shape, padded_extent,
)
from parallel_convolution_tpu.resilience.faults import fault_point
from parallel_convolution_tpu.utils import imageio


def _read_block_np(path, rows, cols, mode, r0, r1, c0, c1) -> np.ndarray:
    try:
        from parallel_convolution_tpu.native import is_built, serial_native

        if is_built():
            return serial_native.read_block(path, rows, cols, mode, r0, r1, c0, c1)
    except Exception:
        pass
    return imageio.read_block(path, rows, cols, mode, r0, r1, c0, c1)


def load_sharded(
    path, rows: int, cols: int, mode: str, mesh: Mesh,
    dtype=np.float32,
) -> jax.Array:
    """Load a raw image directly into a sharded (C, Hp, Wp) planar array.

    Hp/Wp are the padded-to-block-multiple extents for ``mesh``; the pad rim
    arrives zero-filled, matching the sharded step's masking invariant.
    """
    C = 3 if mode == "rgb" else 1
    R, Cc = grid_shape(mesh)
    Hp, Wp = padded_extent(rows, R), padded_extent(cols, Cc)
    sharding = block_sharding(mesh)

    def cb(index):
        rs, cs = index[1], index[2]
        bh = (rs.stop or Hp) - (rs.start or 0)
        bw = (cs.stop or Wp) - (cs.start or 0)
        r0, c0 = rs.start or 0, cs.start or 0
        r1, c1 = min(rs.stop or Hp, rows), min(cs.stop or Wp, cols)
        out = np.zeros((C, bh, bw), dtype)
        if r1 > r0 and c1 > c0:
            fault_point("io_read")  # one consult per device-block read
            blk = _read_block_np(path, rows, cols, mode, r0, r1, c0, c1)
            out[:, : r1 - r0, : c1 - c0] = imageio.interleaved_to_planar(blk)
        return out

    return jax.make_array_from_callback((C, Hp, Wp), sharding, cb)


def save_sharded(
    path, arr: jax.Array, rows: int, cols: int, mode: str,
    allocate: bool = True,
) -> None:
    """Write a sharded padded (C, Hp, Wp) array back to a raw file.

    Each addressable shard writes only its valid (non-pad) intersection at
    the right file offset; u8 conversion happens per block.
    """
    if allocate:
        imageio.allocate_raw(path, rows, cols, mode)
    for shard in arr.addressable_shards:
        rs, cs = shard.index[1], shard.index[2]
        r0, c0 = rs.start or 0, cs.start or 0
        r1 = min(rs.stop or rows, rows)
        c1 = min(cs.stop or cols, cols)
        if r1 <= r0 or c1 <= c0:
            continue  # shard lies entirely in the pad rim
        block = np.asarray(shard.data)[:, : r1 - r0, : c1 - c0]
        block_u8 = imageio.planar_to_interleaved(
            np.clip(block, 0, 255).astype(np.uint8)
        )
        imageio.write_block(path, rows, cols, mode, r0, c0, block_u8)
