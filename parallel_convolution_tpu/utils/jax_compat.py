"""Version-adaptive aliases for jax APIs that moved after 0.4.x.

The framework is written against current jax — ``jax.shard_map``,
varying-mesh-axes (``vma``) out-types, ``pltpu.CompilerParams`` /
``InterpretParams`` / ``MemorySpace.HBM`` — but must also run on a stock
jax 0.4.x install (no tunnel, no site hooks), where those APIs either
live under older names (``jax.experimental.shard_map``,
``TPUCompilerParams``, ``TPUMemorySpace.ANY``) or do not exist at all
(the DMA-faithful TPU interpreter with ``dma_execution_mode`` /
``detect_races``).  Every alias here resolves the NEW api first, so on
current jax this module is a pure pass-through and behavior is
byte-identical; on old jax it degrades to the nearest equivalent.

The one capability that cannot be bridged is the faithful TPU
interpreter: 0.4.x's generic Pallas interpreter has no lowering for
barrier semaphores or remote DMA, so the RDMA kernels (and their
CPU-mesh protocol tests) need either current jax or real silicon.
``HAS_TPU_INTERPRET`` gates those paths: tests skip with an explicit
reason instead of failing on a missing lowering.
"""

from __future__ import annotations

import inspect

import jax
from jax.experimental.pallas import tpu as pltpu

# True when the DMA-faithful TPU interpreter (semaphores, remote copies,
# race detector on the virtual CPU mesh) exists in this jax.
HAS_TPU_INTERPRET = hasattr(pltpu, "InterpretParams")

# True on current jax (top-level ``jax.shard_map``).  A few tests pin
# behaviors of the CURRENT stack that old jax/jaxlib genuinely lack —
# the shard_map lowering's exact collective-permute shapes, XLA:CPU FMA
# contraction discipline, CPU multiprocess collectives — and skip (not
# fail) where those capabilities are absent.
IS_MODERN_JAX = hasattr(jax, "shard_map")


if hasattr(jax, "shard_map"):

    def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)

else:
    from jax.experimental.shard_map import shard_map as _shard_map_old

    def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
        # 0.4.x keyword: check_rep (replication checker, the vma
        # checker's ancestor) — same "off" escape hatch semantics.
        return _shard_map_old(f, mesh, in_specs, out_specs,
                              check_rep=check_vma)


def vma_of(x):
    """Varying-mesh-axes of ``x``'s type, or None where jax predates vma.

    Callers thread the result straight into :func:`shape_struct`; None
    means "don't declare vma" (old jax has no checker to satisfy).
    """
    typeof = getattr(jax, "typeof", None)
    if typeof is None:
        return None
    return getattr(typeof(x), "vma", frozenset())


def shape_struct(shape, dtype, vma=None):
    """``jax.ShapeDtypeStruct`` with ``vma`` only where supported."""
    if vma is None:
        return jax.ShapeDtypeStruct(shape, dtype)
    return jax.ShapeDtypeStruct(shape, dtype, vma=vma)


def tpu_compiler_params(**kwargs):
    """``pltpu.CompilerParams(**kwargs)`` under either API generation.

    Old jax calls the class ``TPUCompilerParams`` and lacks some fields
    (e.g. ``has_side_effects``); unsupported kwargs are dropped — they
    only matter to Mosaic compiles, which old-jax environments (no
    faithful interpreter, CPU-only) never reach.
    """
    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
        allowed = inspect.signature(cls).parameters
        kwargs = {k: v for k, v in kwargs.items() if k in allowed}
    return cls(**kwargs)


def tpu_interpret_params(**kwargs):
    """The DMA-faithful interpreter config, or plain ``True`` without it.

    Current jax: ``pltpu.InterpretParams(**kwargs)`` (simulated remote
    DMAs, semaphores, optional race detector).  Old jax: the generic
    interpreter bool — enough for single-device windowed-DMA kernels,
    NOT for the RDMA protocol (see ``HAS_TPU_INTERPRET``).
    """
    cls = getattr(pltpu, "InterpretParams", None)
    if cls is None:
        return True
    return cls(**kwargs)


def hbm_scratch(shape, dtype):
    """An HBM scratch entry: ``MemorySpace.HBM`` or old ``ANY`` space."""
    ms = getattr(pltpu, "MemorySpace", None)
    if ms is not None and hasattr(ms, "HBM"):
        return ms.HBM(shape, dtype)
    return pltpu.TPUMemorySpace.ANY(shape, dtype)
