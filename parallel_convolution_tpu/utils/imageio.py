"""Headerless raw image I/O (reference component C7, SURVEY.md §2).

The reference's image format is a headerless ``.raw`` byte stream, row-major:

* grayscale — 1 byte per pixel, shape ``(rows, cols)``;
* RGB       — 3 bytes per pixel, interleaved ``R,G,B``, shape
  ``(rows, cols, 3)``.

Dimensions are not stored in the file — the caller supplies ``rows``/``cols``
exactly as the reference's CLI does (``image path, rows, cols, loops,
grey|rgb``).  The reference reads per-rank blocks via MPI-IO offsets or a
rank-0 scatter; the TPU equivalent here is (a) a plain whole-image load for
host-sized images and (b) a *sharded* loader that reads only each device's
block (plus nothing else) via ``np.memmap`` windows, so a 65536² RGB image
(12.9 GB) never materializes in one host buffer (SURVEY.md §7 hard parts).

A faster C++ reader/writer with the same semantics lives in ``native/`` and
is used automatically when its shared library has been built; these NumPy
paths are the always-available fallback and the semantics spec.
"""

from __future__ import annotations

import os
from collections.abc import Sequence

import numpy as np

Mode = str  # "grey" | "rgb"


def _channels(mode: Mode) -> int:
    if mode == "grey":
        return 1
    if mode == "rgb":
        return 3
    raise ValueError(f"mode must be 'grey' or 'rgb', got {mode!r}")


def image_shape(rows: int, cols: int, mode: Mode) -> tuple[int, ...]:
    c = _channels(mode)
    return (rows, cols) if c == 1 else (rows, cols, c)


def read_raw(path: str | os.PathLike, rows: int, cols: int, mode: Mode) -> np.ndarray:
    """Read a whole raw image into a uint8 array of :func:`image_shape`."""
    c = _channels(mode)
    expected = rows * cols * c
    data = np.fromfile(path, dtype=np.uint8)
    if data.size != expected:
        raise ValueError(
            f"{os.fspath(path)}: file has {data.size} bytes, expected "
            f"{expected} for {rows}x{cols} {mode}"
        )
    return data.reshape(image_shape(rows, cols, mode))


def write_raw(path: str | os.PathLike, img: np.ndarray) -> None:
    """Write a uint8 image back to a headerless raw file."""
    np.ascontiguousarray(img, dtype=np.uint8).tofile(path)


def open_raw_mmap(
    path: str | os.PathLike, rows: int, cols: int, mode: Mode
) -> np.memmap:
    """Memory-map a raw image read-only (no bytes touched until sliced)."""
    c = _channels(mode)
    return np.memmap(
        path, dtype=np.uint8, mode="r", shape=image_shape(rows, cols, mode)
    )


def read_block(
    path: str | os.PathLike,
    rows: int,
    cols: int,
    mode: Mode,
    row_start: int,
    row_stop: int,
    col_start: int,
    col_stop: int,
) -> np.ndarray:
    """Read one rectangular block of a raw image without loading the rest.

    This is the MPI-IO ``MPI_File_read_at`` analog: each device's block of a
    huge image is pulled straight from disk.  Row slices of the memmap are
    contiguous file ranges; the column slice copies only the block.
    """
    mm = open_raw_mmap(path, rows, cols, mode)
    block = np.array(mm[row_start:row_stop, col_start:col_stop])
    del mm
    return block


def write_block(
    path: str | os.PathLike,
    rows: int,
    cols: int,
    mode: Mode,
    row_start: int,
    col_start: int,
    block: np.ndarray,
) -> None:
    """Write one rectangular block into a (pre-sized) raw file in place.

    The MPI-IO ``MPI_File_write_at`` analog.  The file must already exist
    with the full image size (see :func:`allocate_raw`).
    """
    mm = np.memmap(
        path, dtype=np.uint8, mode="r+", shape=image_shape(rows, cols, mode)
    )
    mm[
        row_start : row_start + block.shape[0],
        col_start : col_start + block.shape[1],
    ] = block
    mm.flush()
    del mm


def allocate_raw(path: str | os.PathLike, rows: int, cols: int, mode: Mode) -> None:
    """Create (or truncate) a raw file of the full image size, zero-filled."""
    c = _channels(mode)
    with open(path, "wb") as f:  # diskio: exempt — image scaffolding
        f.truncate(rows * cols * c)


def generate_test_image(
    rows: int, cols: int, mode: Mode, seed: int = 0
) -> np.ndarray:
    """Deterministic pseudo-image fixture (the survey's waterfall stand-in).

    A mix of smooth gradients and seeded noise so blur/edge filters have
    visible, non-trivial structure to act on.
    """
    rng = np.random.default_rng(seed)
    y = np.linspace(0.0, 4.0 * np.pi, rows, dtype=np.float64)[:, None]
    x = np.linspace(0.0, 4.0 * np.pi, cols, dtype=np.float64)[None, :]
    base = 127.5 + 80.0 * np.sin(y) * np.cos(x) + 40.0 * np.sin(0.5 * (x + y))
    c = _channels(mode)
    if c == 1:
        img = base + rng.normal(0.0, 12.0, size=(rows, cols))
    else:
        phases = np.array([0.0, 2.0, 4.0])[None, None, :]
        img = (
            base[:, :, None]
            + 30.0 * np.sin(0.25 * (x[:, :, None] + phases))
            + rng.normal(0.0, 12.0, size=(rows, cols, c))
        )
    return np.clip(np.rint(img), 0, 255).astype(np.uint8)


def block_bounds(total: int, parts: int, index: int) -> tuple[int, int]:
    """Start/stop of ``index``'th of ``parts`` near-equal contiguous blocks.

    The reference requires divisible dimensions; this framework does not —
    remainders are spread over the leading blocks (sizes differ by ≤ 1).
    """
    if not 0 <= index < parts:
        raise IndexError(f"block {index} of {parts}")
    base, rem = divmod(total, parts)
    start = index * base + min(index, rem)
    stop = start + base + (1 if index < rem else 0)
    return start, stop


def interleaved_to_planar(img: np.ndarray) -> np.ndarray:
    """(H, W, C) interleaved → (C, H, W) planar (kernel-friendly layout)."""
    if img.ndim == 2:
        return img[None]
    return np.ascontiguousarray(np.moveaxis(img, -1, 0))


def planar_to_interleaved(img: np.ndarray) -> np.ndarray:
    """(C, H, W) planar → (H, W, C) interleaved (or (H, W) when C == 1)."""
    if img.shape[0] == 1:
        return np.ascontiguousarray(img[0])
    return np.ascontiguousarray(np.moveaxis(img, 0, -1))
