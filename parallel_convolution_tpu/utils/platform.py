"""Platform detection and backend-selection shims, in ONE place.

This environment (and Cloud TPU images generally) has two quirks every
entrypoint must survive, previously handled by four drifting copies in
cli.py / bench.py / __graft_entry__.py / tests/conftest.py:

1. A site hook may pre-import jax with the launch-time environment
   snapshotted, so ``JAX_PLATFORMS`` set by the caller never reaches
   backend selection — it must be re-applied through ``jax.config``
   (which still works until a backend initializes).
2. Experimental PJRT proxy platforms (e.g. "axon") tunnel to a real TPU:
   ``device.platform`` says "tpu" but ``client.platform_version`` names
   the proxy, and ``jax.block_until_ready`` returns before the stream
   drains — benchmarking needs a device→host readback fence there.

Everything is import-light: jax is imported inside functions so the CLI
can parse ``--help`` without paying backend startup.
"""

from __future__ import annotations

import os
import sys

_READBACK_FENCE: bool | None = None


def child_env_cpu(n_devices: int, env: dict | None = None) -> dict:
    """Environment for a clean child process on an n-device CPU platform.

    The one shared recipe for spawning multi-virtual-device CPU helpers
    (halo proxy, multi-host workers): pins JAX_PLATFORMS=cpu and REPLACES
    any inherited --xla_force_host_platform_device_count with ``n_devices``.
    """
    import re

    env = dict(os.environ if env is None else env)
    env["JAX_PLATFORMS"] = "cpu"
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   env.get("XLA_FLAGS", ""))
    env["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n_devices}"
    ).strip()
    return env


def force_platform(name: str, warn: bool = False) -> bool:
    """Point jax at platform ``name`` before its backend initializes.

    Best-effort: a no-op once any backend exists (jax raises then), and it
    overrides a site hook's programmatic ``jax_platforms`` pin, which the
    env var alone cannot.  Returns whether the pin took; ``warn=True``
    additionally prints the failure to stderr.
    """
    try:
        import jax

        jax.config.update("jax_platforms", name)
        return True
    except Exception as e:
        if warn:
            print(
                f"pconv-tpu: warning: platform pin {name!r} could not be "
                f"applied (backend already initialized?): {e}",
                file=sys.stderr,
            )
        return False


def apply_platform_env() -> None:
    """Honor ``JAX_PLATFORMS`` even when a site hook pre-imported jax."""
    want = os.environ.get("JAX_PLATFORMS")
    if want:
        force_platform(want, warn=True)


# The probe child re-applies JAX_PLATFORMS through jax.config exactly like
# apply_platform_env (inlined: the child may not have the package on its
# path), so it probes the SAME backend the parent would select — not
# blindly the ambient tunnel when the caller explicitly asked for cpu.
_PROBE_SRC = """
import os, jax
w = os.environ.get("JAX_PLATFORMS")
if w:
    try:
        jax.config.update("jax_platforms", w)
    except Exception:
        pass
jax.devices()
"""


def ensure_live_backend(timeout: float = 120.0) -> str | None:
    """Guard a benchmark entrypoint against a dead accelerator tunnel.

    The ambient platform here is a network tunnel that dies transiently;
    when it does, the first ``jax.devices()`` blocks FOREVER (observed: a
    6-hour outage mid-round-4), which would hang the driver.  Probes
    backend init in a child process (inheriting env + site hook, so it
    reproduces the parent's selection); on success applies the env pin
    and returns None.  On hang/failure it pins cpu and returns a note
    string for the result row — or raises if the cpu pin cannot take
    (proceeding would hit the same infinite hang the probe exists to
    prevent).
    """
    import subprocess

    from parallel_convolution_tpu.resilience.faults import fault_point

    # The 'device_probe' site models this very guard failing (OOM on
    # probe, tunnel flaps): callers that want bounded retries wrap
    # ensure_live_backend in resilience.retry.with_retry.
    fault_point("device_probe")
    try:
        p = subprocess.run(
            [sys.executable, "-c", _PROBE_SRC],
            timeout=timeout, capture_output=True, text=True,
        )
        if p.returncode != 0:
            # A FAST failure is a real error (typo'd JAX_PLATFORMS,
            # broken install), not the hang this guard exists for —
            # surface the child's stderr and let the parent reproduce
            # the error in-process instead of mislabeling it "tunnel
            # down" and silently benchmarking the CPU.
            print(f"pconv-tpu: backend probe failed (rc={p.returncode}); "
                  f"proceeding to reproduce the error in-process:\n"
                  f"{p.stderr.strip()[-500:]}", file=sys.stderr)
        apply_platform_env()
        return None
    except subprocess.TimeoutExpired:
        pass  # the hang case: fall through to the cpu fallback
    if not force_platform("cpu", warn=True):
        raise RuntimeError(
            "accelerator backend unresponsive AND the cpu fallback pin "
            "could not be applied (a backend already initialized) — "
            "refusing to proceed into an indefinite hang"
        )
    return ("ambient accelerator backend unresponsive (tunnel down?); "
            "fell back to CPU so this row is a CPU measurement, NOT the "
            "chip record")


# Simulation override for the device-health probe: elastic-recovery
# drills on a CPU host can't actually lose a device, so
# PCTPU_SIM_DEVICES=N makes the probe report N live devices without
# spawning a child (documented in DESIGN.md "Elastic recovery").
SIM_DEVICES_ENV = "PCTPU_SIM_DEVICES"

# Child source for the health probe: re-applies JAX_PLATFORMS like
# _PROBE_SRC, then reports the live-device count on the last line.
_COUNT_SRC = _PROBE_SRC + """
print(len(jax.devices()))
"""


def probe_device_count(timeout: float = 60.0) -> int | None:
    """How many devices the backend can actually enumerate right now.

    The elastic-recovery health probe: run in a CHILD process (the same
    dead-tunnel discipline as :func:`ensure_live_backend` — a flapping
    accelerator tunnel makes the first in-process ``jax.devices()`` hang
    forever), inheriting env + site hook so the child reproduces the
    parent's backend selection.  Returns the live count, or ``None``
    when the probe hangs/fails (callers treat None as "health unknown"
    and keep their current mesh).  ``PCTPU_SIM_DEVICES=N`` short-circuits
    to N — the simulation knob reshape drills use on CPU hosts, where a
    device cannot really disappear.

    Consults the ``device_probe`` fault site (resilience.faults), like
    :func:`ensure_live_backend`.
    """
    sim = os.environ.get(SIM_DEVICES_ENV)
    if sim:
        try:
            return max(0, int(sim))
        except ValueError:
            print(f"pconv-tpu: ignoring non-integer {SIM_DEVICES_ENV}="
                  f"{sim!r}", file=sys.stderr)
    import subprocess

    from parallel_convolution_tpu.resilience.faults import fault_point

    fault_point("device_probe")
    try:
        p = subprocess.run(
            [sys.executable, "-c", _COUNT_SRC],
            timeout=timeout, capture_output=True, text=True,
        )
    except subprocess.TimeoutExpired:
        return None
    if p.returncode != 0:
        return None
    try:
        return int(p.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return None


def device_on_tpu(d) -> bool:
    """True when ``d`` is real TPU silicon.

    Checks device_kind too: experimental PJRT proxies (e.g. platform
    'axon') report a platform name != 'tpu' while still being TPUs — the
    Mosaic path must be used there, not the Pallas interpreter.
    """
    kind = (getattr(d, "device_kind", "") or "").lower()
    return "tpu" in d.platform.lower() or "tpu" in kind


def on_tpu() -> bool:
    """True when the DEFAULT backend drives real TPU silicon.

    One process can hold both a TPU default backend and a forced-CPU
    mesh (``cpu_devices``); code compiling for a specific mesh must ask
    ``device_on_tpu(mesh.devices.flat[0])``, not this global.
    """
    import jax

    try:
        d = jax.devices()[0]
    except Exception:
        return False
    return device_on_tpu(d)


def topology(mesh=None) -> dict:
    """``{"hosts": N, "slice_topology": "SxD:kind"}`` of this process's
    accelerator layout — the ROADMAP-item-1 row-keying identity, pulled
    forward (r17) so perf evidence is stamped BEFORE multi-host meshes
    exist and future multi-host rows never share a perf_gate baseline
    with single-host ones.

    ``hosts`` is the process count of the distributed runtime (1 for
    every single-controller run).  ``slice_topology`` is
    ``<slices>x<devices-per-slice>:<device_kind>`` derived from the mesh
    devices' ``slice_index`` (0/absent on CPU and single-slice TPU).
    Never raises: an uninitialized backend reports the 1-host unknown
    topology rather than killing a bench row.
    """
    try:
        import jax

        hosts = int(jax.process_count())
        devs = (list(mesh.devices.flat) if mesh is not None
                else list(jax.devices()))
    except Exception:  # noqa: BLE001 — row stamping must never fail
        return {"hosts": 1, "slice_topology": "1x0:unknown"}
    if not devs:
        return {"hosts": hosts, "slice_topology": "1x0:unknown"}
    slices: dict[int, int] = {}
    for d in devs:
        idx = int(getattr(d, "slice_index", 0) or 0)
        slices[idx] = slices.get(idx, 0) + 1
    per_slice = max(slices.values())
    kind = (getattr(devs[0], "device_kind", "") or devs[0].platform
            or "unknown").replace(" ", "_")
    return {"hosts": max(1, hosts),
            "slice_topology": f"{len(slices)}x{per_slice}:{kind}"}


def cpu_devices(n: int | None = None) -> list:
    """CPU devices, forcing the platform when nothing initialized yet.

    A programmatic ``jax_platforms`` pin from a site hook beats the env
    var, so first try flipping the config; once any backend exists,
    ``jax.devices("cpu")`` still works and still honors
    ``--xla_force_host_platform_device_count``.
    """
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    devs = jax.devices()
    if devs and devs[0].platform == "cpu" and (n is None or len(devs) >= n):
        return devs
    try:
        return jax.devices("cpu")
    except Exception:
        return devs


def needs_readback_fence() -> bool:
    """True on experimental proxy platforms where block_until_ready lies.

    Standard backends (cpu/tpu/gpu) really block; tunnel proxies dispatch
    asynchronously and return "ready" while the stream is still executing —
    there only a device→host read fences.  Detection is two-layer because
    the proxy can report platform == 'tpu' (measured: axon's
    ``platform_version`` says "axon ..." while ``device.platform`` says
    "tpu" and block_until_ready returns ~70 ms early on a ~240 ms program):

    1. name check: platform not a standard backend, or "axon" in the
       client's platform_version;
    2. empirical calibration (cached): fence a ~100 ms compiled loop with
       block_until_ready, then read one element — if the readback takes
       over 30% of the blocked wall, the "fence" returned early.  Best of
       three trials, so one transient stall on a busy accelerator cannot
       silently switch every subsequent bench into readback mode.
    """
    global _READBACK_FENCE
    if _READBACK_FENCE is not None:
        return _READBACK_FENCE
    import jax

    try:
        d = jax.devices()[0]
    except Exception:
        _READBACK_FENCE = False
        return False
    version = (getattr(d.client, "platform_version", "") or "").lower()
    if d.platform.lower() not in ("cpu", "tpu", "gpu", "cuda", "rocm") or (
            "axon" in version):
        _READBACK_FENCE = True
        return True
    # CPU's block_until_ready is synchronous by construction, and the
    # calibration spin would take minutes there — only accelerators both
    # need the check and finish it in ~tens of ms.
    _READBACK_FENCE = False if d.platform.lower() == "cpu" else _fence_lies()
    return _READBACK_FENCE


def _fence_lies(trials: int = 5) -> bool:
    """Calibrate: does block_until_ready actually wait for completion?

    The verdict is the MEDIAN readback excess over ``trials``: a platform
    is declared lying only when the majority of trials show a slow
    post-block readback.  Median beats both extremes — min let ONE lucky
    fast readback declare a lying platform honest (and then every bench
    in the process trusts a fence that returns early); max would let one
    transient stall do the opposite.
    """
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    try:
        @jax.jit
        def spin(v):
            return jax.lax.fori_loop(0, 64, lambda _, a: a @ a, v)

        x = jnp.eye(2048, dtype=jnp.float32) * 0.999
        r = spin(x)
        jax.block_until_ready(r)
        np.asarray(r[0, 0])  # warm compile + transfer path
        excess = []
        for _ in range(trials):
            t0 = time.perf_counter()
            r = spin(x)
            jax.block_until_ready(r)
            t_block = time.perf_counter() - t0
            t0 = time.perf_counter()
            np.asarray(r[0, 0])
            t_read = time.perf_counter() - t0
            excess.append(t_read - (0.3 * t_block + 5e-3))
        excess.sort()
        return excess[len(excess) // 2] > 0
    except Exception:
        return False


def enable_compile_cache(cache_dir: str | None = None) -> None:
    """Turn on JAX's persistent compilation cache (works over the tunnel).

    Mosaic compiles of the deep-fused kernels take minutes on the proxy
    platform (measured: 66 s → 8 s process-total for the fuse=16 bench
    once cached); benchmark drivers call this first so repeat runs pay
    compile once per config ever, not once per process.
    """
    import jax

    path = cache_dir or os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), ".jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass  # older jax or read-only fs: compiles still work, just slower


def timing_mode() -> str:
    """Which wall-timing scheme benches on this platform use (for rows)."""
    return "slope" if needs_readback_fence() else "fence"
