"""Runtime-support layer: raw image I/O, benchmarking, tracing, config."""
