"""Benchmark module (reference component C10, SURVEY.md §2 and §6).

The reference wraps its hot loop in ``MPI_Wtime`` and reduces the max
elapsed across ranks; numbers land in hand-made README tables.  Here the
walls are ``jax.block_until_ready`` fences around compiled runners and the
output is structured rows (dict/JSON) feeding BASELINE.md and the driver's
``bench.py``:

* **Gpixels/sec/chip** — pixels iterated per second per device
  (``H*W*iters / wall / n_devices``), the BASELINE.json headline metric.
* **halo-exchange p50 latency** — median wall of one compiled halo-pad
  round trip over the mesh, the latency-bound tail the reference measures
  implicitly at small block sizes.
"""

from __future__ import annotations

import statistics
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from parallel_convolution_tpu.ops.filters import Filter
from parallel_convolution_tpu.parallel import halo, step as step_lib
from parallel_convolution_tpu.parallel.mesh import (
    AXES, block_sharding, grid_shape, make_grid_mesh,
)
from parallel_convolution_tpu.utils.jax_compat import shard_map
from parallel_convolution_tpu.utils.platform import (
    needs_readback_fence as _needs_readback_fence,
    timing_mode, topology,
)


def fence(x):
    """Force completion of everything ``x`` depends on; returns ``x``.

    ``jax.block_until_ready`` alone is NOT a fence on experimental proxy
    platforms (measured on 'axon': 0.1 ms "wall" for a 100-iteration
    8192² stencil).  There, additionally read ONE element per addressable
    shard (a few bytes over the tunnel, vs. seconds for a full-array
    fetch).  On standard backends block_until_ready is a true fence and
    the readback is skipped so microsecond-scale latency benches (halo
    p50) stay undistorted.
    """
    leaves = [l for l in jax.tree.leaves(x) if hasattr(l, "ndim")]
    jax.block_until_ready(leaves)
    if not _needs_readback_fence():
        return x
    for leaf in leaves:
        shards = getattr(leaf, "addressable_shards", None)
        if shards:
            for s in shards:
                d = s.data
                np.asarray(d[(0,) * d.ndim])
        else:
            np.asarray(leaf[(0,) * leaf.ndim])
    return x


def wall(fn, *args, warmup: int = 1, reps: int = 3) -> float:
    """Median wall-clock seconds of ``fn(*args)`` fully materialized."""
    for _ in range(warmup):
        fence(fn(*args))
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fence(fn(*args))
        times.append(time.perf_counter() - t0)
    return statistics.median(times)


def slope_wall(fn, x, reps: int = 3, chain: int = 4) -> float:
    """Wall seconds of one ``fn`` call with the fence constant cancelled.

    For chainable runners (``fn: Array -> Array``, same shape/dtype): on
    lying-fence proxy platforms a single fenced wall carries a ~140 ms
    device→host constant; this times 1-call vs ``chain``-call spans, each
    ending in one fence, and returns the slope (utils/bench.bench_iterate's
    scheme, reusable for ad-hoc candidates).  On standard backends it is a
    plain min-of-reps fenced wall.
    """
    out = fence(fn(x))  # compile + warm
    if not _needs_readback_fence():
        walls = []
        for _ in range(reps):
            t0 = time.perf_counter()
            out = fence(fn(out))
            walls.append(time.perf_counter() - t0)
        return min(walls)
    singles, chains = [], []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fence(fn(out))
        singles.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        for _ in range(chain):
            out = fn(out)
        fence(out)
        chains.append(time.perf_counter() - t0)
    secs = (statistics.median(chains) - statistics.median(singles)) / (
        chain - 1)
    if secs <= 0:  # jitter swamped the chain: upper-bound fallback
        secs = max(statistics.median(chains) / chain, 1e-9)
    return secs


def bench_iterate(
    shape: tuple[int, int],
    filt: Filter,
    iters: int,
    mesh=None,
    channels: int = 1,
    backend: str = "shifted",
    quantize: bool = True,
    storage: str = "f32",
    fuse: int | None = 1,
    boundary: str = "zero",
    reps: int = 3,
    tile: tuple[int, int] | None = None,
    interior_split: bool = False,
    fallback: bool = False,
    overlap: bool | None = None,
    col_mode: str | None = None,
) -> dict:
    """Gpixels/sec/chip for the standard fixed-iteration workload.

    ``tile`` overrides the Pallas output-tile shape (None = per-kernel
    default) — passed explicitly because it is a static jit argument;
    monkeypatching the module defaults does NOT reach already-traced
    kernels.  ``interior_split`` benches the unmasked-interior launch
    split (fused Pallas backends; any grid since round 5).

    Every row is stamped with ``platform`` (the mesh devices' platform —
    a CPU row can never read as a chip record again, the BENCH_r04/r05
    failure mode) and ``effective_backend``.  ``fallback=True``
    additionally walks the degradation chain (resilience.degrade) on a
    transient compile/launch failure, and the row then records the
    backend that ACTUALLY produced the number, with the requested one
    still under ``backend``.

    ``backend="auto"`` (optionally with ``fuse=None``/``tile=None``)
    resolves through the tuning subsystem BEFORE the degrade walk; the
    row's ``plan_source`` records where the plan came from
    (measured|interpolated|predicted — 'explicit' for named configs),
    and ``fuse``/``tile`` always record the values the executable was
    ACTUALLY compiled with (post-resolution, post-clamp), never the
    caller-passed ones — an evidence row can no longer disagree with
    the program it timed.  ``predicted_gpx_per_chip`` is the cost
    model's figure for the same config, so a silent mistune shows as a
    measured-vs-predicted gap in every row."""
    if mesh is None:
        mesh = make_grid_mesh()
    reps = max(1, reps)  # reps=0 would leave the slope path's median empty
    H, W = shape
    rng = np.random.default_rng(0)
    x = rng.integers(0, 256, size=(channels, H, W)).astype(np.float32)

    # Time ONLY the on-device iteration: host->device transfer happens once
    # (over a tunnel it would otherwise dominate), and because the runner
    # donates its input, repetitions chain output->input — padded shape,
    # dtype and sharding are invariant, exactly the double-buffer reuse the
    # real pipeline gets.
    xs, valid_hw, block_hw = step_lib._prepare(x, mesh, filt.radius, storage)
    effective, fuse, tile, overlap, col_mode, plan_source = (
        step_lib._resolve_auto(
            mesh, filt, backend, fuse, tile, storage, quantize, boundary,
            valid_hw, channels, overlap=overlap, col_mode=col_mode))
    plan_source = plan_source or "explicit"
    # The overlap/col_mode knobs the executable will ACTUALLY be
    # compiled with — stamped below exactly like tile/fuse (post-auto-
    # resolution, post-clamp), so a row can never disagree with the
    # compiled program.
    overlap = step_lib.resolve_overlap(overlap, effective, mesh)
    col_mode = step_lib.resolve_col_mode(col_mode, effective, mesh,
                                         block_hw, filt.radius, fuse,
                                         storage)
    if fallback:
        from parallel_convolution_tpu.resilience import degrade

        # Probe on the REAL block geometry + storage: kernel selection
        # (e.g. pallas_rdma tiled-vs-monolithic) depends on both.
        effective = degrade.resolve_backend(
            mesh, filt, effective, quantize=quantize, fuse=fuse,
            boundary=boundary, tile=tile, interior_split=interior_split,
            storage=storage, block_hw=block_hw, overlap=overlap,
            col_mode=col_mode)
        overlap = overlap and effective == "pallas_rdma"
        col_mode = step_lib.clamp_col_mode(col_mode, effective)
    fn = step_lib._build_iterate(mesh, filt, iters, quantize, valid_hw,
                                 block_hw, effective, fuse, boundary,
                                 tile, interior_split, overlap, col_mode)
    out = fence(fn(xs))  # compile + warmup

    # The fence itself can cost a large constant on tunnel platforms
    # (~134 ms device→host round trip measured on axon) — time spans of 1
    # and of ``chain`` chained calls, each ending in ONE fence, and take
    # the slope: the constant cancels, leaving pure per-call device time.
    chain = 4 if _needs_readback_fence() else 1

    def span(n):
        nonlocal out
        t0 = time.perf_counter()
        for _ in range(n):
            out = fn(out)
        fence(out)
        return time.perf_counter() - t0

    # Pure fence cost (everything is already drained after warmup): the
    # constant the slope must cancel, and the floor for fallbacks.
    t0 = time.perf_counter()
    fence(out)
    floor = time.perf_counter() - t0
    first = span(1)
    # When one call already dwarfs the fence constant (~0.15 s), chaining
    # only multiplies runtime for <5% accuracy — use plain spans.
    mode = "fence"
    if chain > 1 and first < 3.0:
        mode = "slope"
        # Size the chain so the chained span carries ~1 s of device work:
        # for millisecond workloads a chain of 4 leaves the slope signal
        # under the ±40 ms fence jitter, and the old single-span fallback
        # then reported the fence floor as the "wall" (observed: a 3 ms
        # job measured as 150 ms → 50× underreported throughput).
        per_est = max(first - floor, 1e-4)
        chain = max(chain, min(int(round(1.0 / per_est)) or 1, 256))
        singles, chains = [first], []
        for i in range(reps):
            chains.append(span(chain))
            if i + 1 < reps:
                singles.append(span(1))
        secs = (statistics.median(chains) - statistics.median(singles)) / (
            chain - 1)
        if secs <= 0:
            # Jitter swamped even the long chain: floor-subtracted chained
            # span is a conservative upper bound on the per-call time.
            # Flagged in the row — an upper bound is not a slope
            # measurement and must not be read as one.
            secs = max((statistics.median(chains) - floor) / chain, 1e-6)
            mode = "slope-fallback-upper-bound"
    else:
        secs = statistics.median(
            [first] + [span(1) for _ in range(reps - 1)])
    n_dev = mesh.size
    gpx = H * W * channels * iters / secs / 1e9
    dev0 = mesh.devices.flat[0]
    # Stamp what was COMPILED, not what was passed: the same clamp
    # _build_iterate applies, and the kernel tile the launch actually
    # used (explicit/auto-resolved value, else the per-kernel module
    # default for Pallas tiers; None for backends with no tile).
    from parallel_convolution_tpu.tuning import costmodel, search
    from parallel_convolution_tpu.tuning.plans import Workload

    compiled_fuse = max(1, min(fuse, iters or 1))
    compiled_tile = costmodel.effective_tile(effective, tile)
    if effective == "pallas_rdma" and not costmodel.rdma_is_tiled(
            (channels, H, W), block_hw, filt.radius, compiled_fuse, storage,
            col_mode=col_mode, grid=grid_shape(mesh)):
        compiled_tile = None  # monolithic kernel: no output tile exists
    w = Workload.from_mesh(mesh, filt, (channels, H, W), storage=storage,
                           quantize=quantize, boundary=boundary)
    predicted = costmodel.predict_gpx_per_chip(search.predict(
        w, search.Candidate(effective, compiled_fuse, compiled_tile,
                            overlap, col_mode)))
    # Exchange/overlap attribution (obs.attribution): the analytic
    # per-direction ghost-band bytes of this decomposition and the
    # roofline model's exchange share — the per-phase instrumentation
    # the overlapped-halo and topology roadmap items are judged against.
    grid = grid_shape(mesh)
    from parallel_convolution_tpu.obs import attribution

    # record_step feeds the metric series AND returns the attribution
    # this row stamps; with obs disabled it returns None and the row's
    # analytic fields are computed directly (pure math, always on).
    att = attribution.record_step(
        backend=effective, grid=grid, block_hw=block_hw,
        radius=filt.radius, fuse=compiled_fuse, iters=iters,
        channels=channels, storage=storage, boundary=boundary,
        wall_s=secs, shape=(channels, H, W), quantize=quantize,
        tile=compiled_tile, platform=dev0.platform,
        device_kind=getattr(dev0, "device_kind", "") or "",
        source="bench", overlap=overlap, col_mode=col_mode)
    if att is None:
        split = attribution.predicted_exchange_split(
            grid, block_hw, filt.radius, compiled_fuse,
            backend=effective, storage=storage,
            shape=(channels, H, W), tile=compiled_tile,
            quantize=quantize,
            separable=effective in ("separable", "pallas_sep"),
            platform=dev0.platform,
            device_kind=getattr(dev0, "device_kind", "") or "",
            overlap=overlap)
        att = {
            "halo_bytes": attribution.halo_bytes_total(
                grid, block_hw, filt.radius, compiled_fuse, iters,
                channels, storage, boundary),
            "exchange_fraction": split["exchange_fraction"],
            "exchange_hidden_fraction": split["exchange_hidden_fraction"],
        }
    # The drift series (ROADMAP 5a's recalibration input): the bench
    # measurement against the model's figure, per plan key.
    attribution.record_drift(w.key(), effective, predicted, gpx / n_dev)
    return {
        "workload": f"{filt.name} {H}x{W}x{channels} {iters} iters",
        "backend": backend,
        # The backend that ACTUALLY produced this number (differs from
        # 'backend' only when fallback degraded it, or when 'auto' was
        # resolved) and the hardware it ran on — a silent CPU fallback
        # or tier downgrade can no longer masquerade as the requested
        # configuration in published rows.
        "effective_backend": effective,
        "platform": dev0.platform,
        "device_kind": getattr(dev0, "device_kind", "") or "",
        "storage": storage,
        "fuse": compiled_fuse,
        "tile": (f"{compiled_tile[0]}x{compiled_tile[1]}"
                 if compiled_tile else None),
        # The RESOLVED overlap knob (post-auto-resolution, post-clamp,
        # post-degrade) — the program this row timed either was or was
        # not the interior-first pipeline; the row says which.
        "overlap": bool(overlap),
        # The RESOLVED column-slab transport, same stamping rule
        # ("packed" is the canonical inert label off the RDMA tier).
        "col_mode": col_mode,
        "plan_source": plan_source,
        # The canonical tuning identity of the timed config — the
        # drift-series label and perf_gate.py's history key.
        "plan_key": w.key(),
        "predicted_gpx_per_chip": round(predicted, 3),
        "mesh": "x".join(str(s) for s in grid),
        "devices": n_dev,
        # Topology identity (ROADMAP item 1's keying, pulled forward in
        # r17): perf_gate.row_key keys multi-host rows separately so
        # they are never judged against single-host baselines.
        **topology(mesh),
        "wall_s": round(secs, 4),
        "gpixels_per_s": round(gpx, 3),
        "gpixels_per_s_per_chip": round(gpx / n_dev, 3),
        # Exchange attribution: the model's exchange share of one
        # iteration and the analytic ghost-band bytes this run moved
        # (whole mesh, all rounds, per direction) — obs.attribution.
        "exchange_fraction": round(att["exchange_fraction"], 4),
        # Overlap-adjusted split: the share of exchange time the
        # interior-first pipeline hides under compute (0.0 serialized).
        "exchange_hidden_fraction": round(
            att.get("exchange_hidden_fraction", 0.0), 4),
        "halo_bytes": att["halo_bytes"],
        # Which wall scheme ACTUALLY produced this row ('slope' = chained
        # spans with the fence constant cancelled; 'fence' = plain fenced
        # spans, used on standard backends and for multi-second walls where
        # the fence constant is <5%) — keeps results auditable.
        "timing": mode,
    }


def bench_converge(
    shape: tuple[int, int],
    filt: Filter,
    tol: float,
    max_iters: int,
    mesh=None,
    channels: int = 1,
    backend: str = "shifted",
    storage: str = "f32",
    boundary: str = "zero",
    check_every: int = 10,
    fuse: int | None = 1,
    tile: tuple[int, int] | None = None,
    solver: str = "jacobi",
    mg_levels: int | None = None,
    overlap: bool | None = None,
    col_mode: str | None = None,
    seed: int = 0,
) -> dict:
    """One run-to-convergence row, solver-comparable by construction.

    The row's ``work_units_to_tol`` is the fine-grid work spent reaching
    ``tol`` — iterations for jacobi, the pixel-weighted per-level sum for
    multigrid — so a multigrid row and a jacobi row on the same problem
    divide into the convergence speedup directly.  ``solver`` and
    ``mg_levels`` are stamped POST-RESOLUTION like tile/fuse: the level
    count is what the planner actually scheduled (never the requested
    cap), and ``plan_key`` carries a ``solver=`` suffix for non-jacobi
    rows so ``scripts/perf_gate.py`` never judges a multigrid row
    against a jacobi baseline.
    """
    if mesh is None:
        mesh = make_grid_mesh()
    from parallel_convolution_tpu.tuning.plans import Workload

    H, W = shape
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((channels, H, W)).astype(np.float32)
    # Post-resolution stamping, same rule as bench_iterate: resolve
    # backend="auto"/fuse=None/tile=None through the tuning subsystem
    # FIRST so the row records the program that actually ran.
    backend, fuse, tile, overlap, col_mode, _ = step_lib._resolve_auto(
        mesh, filt, backend, fuse, tile, storage, False, boundary,
        (H, W), channels, check_every=int(check_every), overlap=overlap,
        col_mode=col_mode)
    w = Workload.from_mesh(mesh, filt, (channels, H, W), storage=storage,
                           quantize=False, boundary=boundary)
    dev0 = mesh.devices.flat[0]
    grid = grid_shape(mesh)
    row = {
        "workload": f"converge {filt.name} {H}x{W}x{channels} tol={tol}",
        "backend": backend,
        "solver": solver,
        "storage": storage,
        "boundary": boundary,
        "platform": dev0.platform,
        "device_kind": getattr(dev0, "device_kind", "") or "",
        "mesh": "x".join(str(s) for s in grid),
        "devices": mesh.size,
        "tol": float(tol),
        # Topology identity — same r17 keying rule as bench_iterate.
        **topology(mesh),
    }
    t0 = time.perf_counter()
    if solver == "multigrid":
        from parallel_convolution_tpu.solvers import multigrid

        out, res = multigrid.mg_converge(
            x, filt, tol=tol, max_iters=max_iters, mesh=mesh,
            quantize=False, backend=backend, storage=storage,
            boundary=boundary, fuse=fuse, tile=tile, overlap=overlap,
            mg_levels=mg_levels, col_mode=col_mode)
        row.update({
            "effective_backend": res.backend,
            "overlap": res.overlap,
            "col_mode": res.col_mode,
            "converged": res.converged,
            "residual": float(res.residual),
            "cycles": res.cycles,
            # Post-resolution stamps: what the planner actually
            # scheduled, not the requested cap.
            "mg_levels": res.levels,
            "mg_level_shapes": res.level_shapes,
            "work_units_to_tol": res.work_units,
            "predicted_s_per_cycle": res.predicted_s_per_cycle,
            # The solver is part of the history identity: a V-cycle's
            # work trajectory must never be judged against sweep counts.
            "plan_key": f"{w.key()}|solver=multigrid",
        })
    else:
        # The host-driven stream (byte-identical final image to
        # sharded_converge, same chunk math) reads the diff back per
        # chunk, so convergence is judged on diff < tol itself — the
        # iters < max_iters proxy misreports a run that reaches tol
        # exactly on the final permitted chunk.
        out, iters, diff = x, 0, None
        for out, iters, diff in step_lib.sharded_converge_stream(
                x, filt, tol=tol, max_iters=max_iters,
                check_every=check_every, mesh=mesh, quantize=False,
                backend=backend, storage=storage, boundary=boundary,
                fuse=fuse, tile=tile, overlap=overlap,
                col_mode=col_mode):
            pass
        row.update({
            "effective_backend": backend,
            "col_mode": step_lib.resolve_col_mode(
                col_mode, backend, mesh,
                (-(-H // grid[0]), -(-W // grid[1])), filt.radius,
                int(fuse), storage),
            "converged": diff is not None and diff < tol,
            "residual": diff,
            "iters": iters,
            "mg_levels": None,
            "work_units_to_tol": float(iters),
            "plan_key": w.key(),
        })
    secs = max(time.perf_counter() - t0, 1e-9)
    row["wall_s"] = round(secs, 4)
    # Fine-grid pixel updates per second — the gateable throughput of a
    # convergence run (work-unit-weighted, so a V-cycle's coarse sweeps
    # are charged at their pixel ratio; perf_gate's history separates
    # solvers by key, this number tracks each solver's own trajectory).
    row["gpixels_per_s"] = round(
        row["work_units_to_tol"] * H * W * channels / secs / 1e9, 5)
    row["checksum"] = float(np.abs(np.asarray(out)).max())
    return row


def halo_bench_rounds(mesh, grid, r: int, n: int, exchange: bool):
    """The halo benchmark's chained round runner, at module scope so the
    HLO regression test (`test_bench_halo_rounds_keep_collectives`)
    compiles the SAME code `bench_halo_p50` times — not a private copy
    that could drift while the real round regresses to an elided graph.

    The exchange round carries forward the window STARTING at the ghost
    corner — it consumes the ppermuted ghosts and rotates the data
    across devices, which is what keeps the collective alive in the
    compiled loop (see `bench_halo_p50`'s definition note).  The control
    round moves the same bytes with a local roll and has no collective.
    """

    def body(v):
        def one(_, b):
            if exchange:
                p = halo.halo_exchange(b, r, grid)
                return p[:, : b.shape[1], : b.shape[2]]
            return jnp.roll(b, (r, r), axis=(1, 2))

        return jax.lax.fori_loop(0, n, one, v)

    return jax.jit(shard_map(
        body, mesh=mesh, in_specs=P(None, *AXES), out_specs=P(None, *AXES),
    ))


def bench_halo_p50(
    block_shape: tuple[int, int],
    r: int = 1,
    mesh=None,
    trials: int = 20,
    chain_rounds: int | None = None,
) -> dict:
    """p50 amortized latency of one compiled halo exchange over the mesh.

    ``block_shape`` is the per-device block (the reference's per-rank tile);
    latency is what bounds small-block scaling (SURVEY.md §3.2).

    DEFINITION (round 5, one procedure for every consumer): each trial
    times ONE jitted span of ``chain_rounds`` chained LIVE exchange
    rounds and one span of equal-shape local-control rounds, and reports
    their difference divided by the count; p50/p90 are over trials.

    Two failure modes of earlier procedures, both caught this round:

    * A single fenced round (pre-round-5) is dominated by per-dispatch
      host noise — the proxy's p50 swung 1.4 → 16 ms, 10×, across
      identical driver runs.
    * Worse, a chained round built as ``slice(exchange(b))`` back to
      ``b``'s own window is the IDENTITY: XLA cancels slice-of-concat
      and emits ZERO collective-permutes (verified in HLO), so every
      earlier proxy number — ms-scale and µs-scale alike — timed an
      empty graph.  The fuse-delta cross-check
      (``scripts/halo_cross_check.py``) exposed this: its derived
      saving was 44× the "measured" cost.

    The live round therefore consumes the ghosts: it carries forward the
    (bh, bw) window that STARTS at the ghost corner, so the data rotates
    across devices and neither slice-of-concat cancellation nor
    loop-invariant hoisting can elide the ppermutes (asserted in HLO by
    ``test_bench_halo_rounds_keep_collectives``).  The control round is
    a local ``jnp.roll`` by the same shift — same consumer bytes, no
    collective — so the differenced number isolates exchange cost (pad,
    two-phase ppermute, stitch) from the consumer copy.
    On lying-fence tunnel platforms each leg additionally uses the slope
    scheme (k-round chain minus a 1-round span) to cancel the fence
    constant.
    """
    if mesh is None:
        mesh = make_grid_mesh()
    grid = grid_shape(mesh)
    bh, bw = block_shape
    if mesh.size == 1:
        # On a 1×1 mesh halo_exchange._shift short-circuits to zeros_like —
        # there is NO collective, so any number "measured" here would be
        # the latency of nothing.  Refuse with an explicit sentinel rather
        # than record a vacuous 0.0 (round-1 BENCH did exactly that).
        return {
            "block": f"{bh}x{bw}", "radius": r,
            "mesh": "1x1", "p50_us": None, "p90_us": None,
            "unmeasurable": "1x1 mesh has no collective to time",
        }
    H, W = bh * grid[0], bw * grid[1]
    x = jax.device_put(
        np.random.default_rng(0).random((1, H, W)).astype(np.float32),
        block_sharding(mesh),
    )

    def rounds(n, exchange):
        return halo_bench_rounds(mesh, grid, r, n, exchange)

    # On tunnel platforms a single fenced call is dominated by the ~140 ms
    # (±40 ms jitter) device→host fence; a ~20 µs halo round is invisible
    # unless thousands are chained on-device so the aggregate signal beats
    # the jitter — then slope out the constant (same trick as
    # bench_iterate).  Slopes are clamped at 0: a negative slope is pure
    # jitter, and falling back to the fenced wall would report the tunnel,
    # not the halo.
    lying_fence = _needs_readback_fence()
    k = chain_rounds or (4096 if lying_fence else 256)
    if lying_fence:
        k = max(2, k)  # the slope below divides by k - 1
    fnx, fnc = rounds(k, True), rounds(k, False)
    fence(fnx(x)), fence(fnc(x))  # compile
    times = []
    clamped = 0

    def span(fn):
        t0 = time.perf_counter()
        fence(fn(x))
        return time.perf_counter() - t0

    if not lying_fence:
        # Differenced amortized cost: per trial, one fenced span of k
        # live-exchange rounds minus one span of k local-control rounds,
        # over k.  Dispatch + fence cost cancels in the difference AND is
        # amortized (<1% at k=256); pairing the legs inside one trial
        # also cancels slow host-load drift.
        for _ in range(trials):
            d = (span(fnx) - span(fnc)) / k
            if d <= 0:
                clamped += 1  # noise swamped the exchange; never emit <0
                d = 0.0
            times.append(d)
    else:
        fnx1, fnc1 = rounds(1, True), rounds(1, False)
        fence(fnx1(x)), fence(fnc1(x))  # compile
        for _ in range(trials):
            slope_x = (span(fnx) - span(fnx1)) / (k - 1)
            slope_c = (span(fnc) - span(fnc1)) / (k - 1)
            d = slope_x - slope_c
            if d <= 0:
                # Negative = fence jitter swamped the chained rounds;
                # count it instead of recording an impossible <= 0 µs
                # latency as if it were a measurement.
                clamped += 1
                d = 0.0
            times.append(d)
    times.sort()
    p50 = 1e6 * times[len(times) // 2]
    p90 = 1e6 * times[int(len(times) * 0.9)]
    row = {
        "block": f"{bh}x{bw}", "radius": r,
        "mesh": "x".join(str(s) for s in grid),
        "p50_us": round(p50, 1),
        "p90_us": round(p90, 1),
        "trials": trials,
        "rounds_per_trial": k,
        "timing": (timing_mode() if lying_fence
                   else f"amortized-diff-{k}"),
    }
    if clamped:
        row["clamped_trials"] = clamped
    if p50 <= 0.0 and clamped:
        # The median itself sits on the clamp: the signal never rose above
        # the noise floor, so there is no measurement — null, flagged.
        # Same for a clamped p90: 0.0 µs is impossible, not a tail latency.
        row["p50_us"] = None
        row["noise_floor"] = True
        if p90 <= 0.0:
            row["p90_us"] = None
    return row


def bench_oracle_proxy(shape=(1920, 2520), iters: int = 2,
                       reps: int = 5) -> dict:
    """Serial CPU proxy (BASELINE config 1) via the NumPy oracle.

    The reference's own published numbers were unreadable (empty mount —
    BASELINE.md provenance note), so the honest single-process baseline is
    measured here, not copied.  Prefers the native C++ serial binary when
    built (a truer stand-in for the reference's C), else NumPy.

    This number is the denominator of every headline speedup claim, so it
    is the median of ``reps`` trials with the min→max spread recorded —
    a single 2-iteration trial swung ±20% between otherwise identical
    rounds (0.059–0.070 Gpx/s, BENCH_r01–r03) and dragged vs_baseline
    with it.
    """
    from parallel_convolution_tpu.ops import oracle
    from parallel_convolution_tpu.ops.filters import get_filter

    H, W = shape
    img = np.random.default_rng(0).integers(0, 256, size=(H, W)).astype(np.uint8)
    filt = get_filter("blur3")
    run = oracle.run_serial_u8
    impl = "numpy-oracle"
    try:
        from parallel_convolution_tpu.native import serial_native

        # Warm-up call outside the timed span so a first-use C++ build (or
        # page-in) doesn't pollute the measurement.
        serial_native.run_serial_u8(img[:8, :8], filt, 1)
        # threads=1: this row is the strict serial C1 baseline, not the
        # OpenMP hybrid tier (threads=0 default).
        run = lambda *a: serial_native.run_serial_u8(*a, threads=1)
        impl = "cpp-serial"
    except Exception:
        pass
    walls = []
    for _ in range(max(1, reps)):
        t0 = time.perf_counter()
        run(img, filt, iters)
        walls.append(max(time.perf_counter() - t0, 1e-9))
    secs = statistics.median(walls)
    return {
        "workload": f"serial blur3 {H}x{W} {iters} iters",
        "impl": impl,
        "wall_s": round(secs, 4),
        "gpixels_per_s": float(f"{H * W * iters / secs / 1e9:.5g}"),
        "reps": len(walls),
        "spread_pct": round(100.0 * (max(walls) - min(walls)) / secs, 1),
    }
