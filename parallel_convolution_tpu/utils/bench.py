"""Benchmark module (reference component C10, SURVEY.md §2 and §6).

The reference wraps its hot loop in ``MPI_Wtime`` and reduces the max
elapsed across ranks; numbers land in hand-made README tables.  Here the
walls are ``jax.block_until_ready`` fences around compiled runners and the
output is structured rows (dict/JSON) feeding BASELINE.md and the driver's
``bench.py``:

* **Gpixels/sec/chip** — pixels iterated per second per device
  (``H*W*iters / wall / n_devices``), the BASELINE.json headline metric.
* **halo-exchange p50 latency** — median wall of one compiled halo-pad
  round trip over the mesh, the latency-bound tail the reference measures
  implicitly at small block sizes.
"""

from __future__ import annotations

import statistics
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from parallel_convolution_tpu.ops.filters import Filter
from parallel_convolution_tpu.parallel import halo, step as step_lib
from parallel_convolution_tpu.parallel.mesh import (
    AXES, block_sharding, grid_shape, make_grid_mesh,
)


def _needs_readback_fence() -> bool:
    """True on experimental proxy platforms where block_until_ready lies.

    Standard backends (cpu/tpu/gpu) really block; proxies (e.g. 'axon')
    dispatch asynchronously and return "ready" while the stream is still
    executing — there only a device→host read fences.
    """
    try:
        return jax.devices()[0].platform.lower() not in (
            "cpu", "tpu", "gpu", "cuda", "rocm")
    except Exception:
        return False


def fence(x):
    """Force completion of everything ``x`` depends on; returns ``x``.

    ``jax.block_until_ready`` alone is NOT a fence on experimental proxy
    platforms (measured on 'axon': 0.1 ms "wall" for a 100-iteration
    8192² stencil).  There, additionally read ONE element per addressable
    shard (a few bytes over the tunnel, vs. seconds for a full-array
    fetch).  On standard backends block_until_ready is a true fence and
    the readback is skipped so microsecond-scale latency benches (halo
    p50) stay undistorted.
    """
    leaves = [l for l in jax.tree.leaves(x) if hasattr(l, "ndim")]
    jax.block_until_ready(leaves)
    if not _needs_readback_fence():
        return x
    for leaf in leaves:
        shards = getattr(leaf, "addressable_shards", None)
        if shards:
            for s in shards:
                d = s.data
                np.asarray(d[(0,) * d.ndim])
        else:
            np.asarray(leaf[(0,) * leaf.ndim])
    return x


def wall(fn, *args, warmup: int = 1, reps: int = 3) -> float:
    """Median wall-clock seconds of ``fn(*args)`` fully materialized."""
    for _ in range(warmup):
        fence(fn(*args))
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fence(fn(*args))
        times.append(time.perf_counter() - t0)
    return statistics.median(times)


def bench_iterate(
    shape: tuple[int, int],
    filt: Filter,
    iters: int,
    mesh=None,
    channels: int = 1,
    backend: str = "shifted",
    quantize: bool = True,
    storage: str = "f32",
    fuse: int = 1,
    reps: int = 3,
) -> dict:
    """Gpixels/sec/chip for the standard fixed-iteration workload."""
    if mesh is None:
        mesh = make_grid_mesh()
    H, W = shape
    rng = np.random.default_rng(0)
    x = rng.integers(0, 256, size=(channels, H, W)).astype(np.float32)

    # Time ONLY the on-device iteration: host->device transfer happens once
    # (over a tunnel it would otherwise dominate), and because the runner
    # donates its input, repetitions chain output->input — padded shape,
    # dtype and sharding are invariant, exactly the double-buffer reuse the
    # real pipeline gets.
    xs, valid_hw, block_hw = step_lib._prepare(x, mesh, filt.radius, storage)
    fn = step_lib._build_iterate(mesh, filt, iters, quantize, valid_hw,
                                 block_hw, backend, fuse)
    out = fence(fn(xs))  # compile + warmup
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fence(fn(out))
        times.append(time.perf_counter() - t0)
    secs = statistics.median(times)
    n_dev = mesh.size
    gpx = H * W * channels * iters / secs / 1e9
    return {
        "workload": f"{filt.name} {H}x{W}x{channels} {iters} iters",
        "backend": backend,
        "storage": storage,
        "fuse": fuse,
        "mesh": "x".join(str(s) for s in grid_shape(mesh)),
        "devices": n_dev,
        "wall_s": round(secs, 4),
        "gpixels_per_s": round(gpx, 3),
        "gpixels_per_s_per_chip": round(gpx / n_dev, 3),
    }


def bench_halo_p50(
    block_shape: tuple[int, int],
    r: int = 1,
    mesh=None,
    trials: int = 20,
) -> dict:
    """p50 latency of one compiled two-phase halo exchange over the mesh.

    ``block_shape`` is the per-device block (the reference's per-rank tile);
    latency is what bounds small-block scaling (SURVEY.md §3.2).
    """
    if mesh is None:
        mesh = make_grid_mesh()
    grid = grid_shape(mesh)
    bh, bw = block_shape
    H, W = bh * grid[0], bw * grid[1]
    x = jax.device_put(
        np.random.default_rng(0).random((1, H, W)).astype(np.float32),
        block_sharding(mesh),
    )

    fn = jax.jit(
        jax.shard_map(
            lambda v: halo.halo_exchange(v, r, grid),
            mesh=mesh, in_specs=P(None, *AXES), out_specs=P(None, *AXES),
        )
    )
    fence(fn(x))  # compile
    times = []
    for _ in range(trials):
        t0 = time.perf_counter()
        fence(fn(x))
        times.append(time.perf_counter() - t0)
    times.sort()
    return {
        "block": f"{bh}x{bw}", "radius": r,
        "mesh": "x".join(str(s) for s in grid),
        "p50_us": round(1e6 * times[len(times) // 2], 1),
        "p90_us": round(1e6 * times[int(len(times) * 0.9)], 1),
    }


def bench_oracle_proxy(shape=(1920, 2520), iters: int = 2) -> dict:
    """Serial CPU proxy (BASELINE config 1) via the NumPy oracle.

    The reference's own published numbers were unreadable (empty mount —
    BASELINE.md provenance note), so the honest single-process baseline is
    measured here, not copied.  Prefers the native C++ serial binary when
    built (a truer stand-in for the reference's C), else NumPy.
    """
    from parallel_convolution_tpu.ops import oracle
    from parallel_convolution_tpu.ops.filters import get_filter

    H, W = shape
    img = np.random.default_rng(0).integers(0, 256, size=(H, W)).astype(np.uint8)
    filt = get_filter("blur3")
    run = oracle.run_serial_u8
    impl = "numpy-oracle"
    try:
        from parallel_convolution_tpu.native import serial_native

        # Warm-up call outside the timed span so a first-use C++ build (or
        # page-in) doesn't pollute the measurement.
        serial_native.run_serial_u8(img[:8, :8], filt, 1)
        # threads=1: this row is the strict serial C1 baseline, not the
        # OpenMP hybrid tier (threads=0 default).
        run = lambda *a: serial_native.run_serial_u8(*a, threads=1)
        impl = "cpp-serial"
    except Exception:
        pass
    t0 = time.perf_counter()
    run(img, filt, iters)
    secs = max(time.perf_counter() - t0, 1e-9)
    return {
        "workload": f"serial blur3 {H}x{W} {iters} iters",
        "impl": impl,
        "wall_s": round(secs, 4),
        "gpixels_per_s": float(f"{H * W * iters / secs / 1e9:.5g}"),
    }
