"""Checkpoint / resume for long runs (SURVEY.md §5 aux subsystems).

The reference has none (runs are minutes long, output written once at the
end); this framework adds the natural TPU-native version: every K
iterations the sharded state is snapshotted **per addressable shard** (no
host gather — each device block becomes one ``.npy`` keyed by its grid
coordinates) together with a JSON sidecar recording progress and config.
A restarted run validates the sidecar against its own config and continues
from the saved iteration.

Chunked execution does not perturb semantics: in u8 mode every iteration
ends quantized to exact integers, and float-mode shards are saved as raw
float32, so save/restore is lossless and the checkpointed run remains
bit-identical to an uninterrupted one.

Hardening (resilience PR): snapshots are now *verifiable*, not just
ordered.  Each shard is written atomically (tmp + rename) and its CRC32 +
byte length are recorded in ``meta.json``; loading validates completeness
and checksums and raises :class:`CheckpointCorrupt` on a torn snapshot
(the multi-host prune race: ``meta.json`` present but shard files
missing/truncated).  ``load_state(..., fallback=True)`` — the default
inside :func:`run_checkpointed` — then walks back to the newest snapshot
that does validate instead of crashing or, worse, resuming from garbage.
Injection sites ``checkpoint_write_shard`` / ``checkpoint_write_meta``
(resilience.faults) let tests kill a save between any two writes and
prove the resumed run byte-identical (tests/test_resilience.py).
"""

from __future__ import annotations

import io
import json
import os
import warnings
import zlib
from pathlib import Path

import jax
import numpy as np
from jax.sharding import Mesh

from parallel_convolution_tpu.parallel.mesh import block_sharding, grid_shape
from parallel_convolution_tpu.resilience.faults import fault_point

META_NAME = "meta.json"
LATEST_NAME = "LATEST"
KEEP_SNAPSHOTS = 2


class CheckpointCorrupt(RuntimeError):
    """A snapshot's meta exists but its shard set is incomplete/damaged."""


class CheckpointWarning(UserWarning):
    """A corrupt snapshot was skipped in favor of an older (or fresh) state."""


def _coords(index, block_hw) -> tuple[int, int]:
    rs, cs = index[1], index[2]
    return (rs.start or 0) // block_hw[0], (cs.start or 0) // block_hw[1]


def _snap_dir(ckpt_dir, iters_done: int) -> Path:
    return Path(ckpt_dir) / f"it_{int(iters_done):08d}"


def _latest_snap(ckpt_dir) -> Path | None:
    p = Path(ckpt_dir) / LATEST_NAME
    if not p.exists():
        return None
    snap = Path(ckpt_dir) / p.read_text().strip()
    return snap if (snap / META_NAME).exists() else None


def _candidate_snaps(ckpt_dir) -> list[Path]:
    """Snapshots to try, newest-claim first: the LATEST pointer's target,
    then every other ``it_*`` dir with a meta, newest iteration first."""
    d = Path(ckpt_dir)
    first = _latest_snap(d)
    out = [first] if first is not None else []
    if d.exists():
        rest = sorted(
            (p for p in d.iterdir() if p.is_dir()
             and p.name.startswith("it_") and (p / META_NAME).exists()),
            key=lambda p: p.name, reverse=True,
        )
        out += [p for p in rest if first is None or p.name != first.name]
    return out


def _expected_shards(meta: dict) -> list[str]:
    g0, g1 = meta["grid"]
    return [f"shard_{r}_{c}.npy" for r in range(g0) for c in range(g1)]


def _validate_snapshot(snap: Path, meta: dict) -> None:
    """Raise :class:`CheckpointCorrupt` unless every expected shard file is
    present and matches its recorded CRC32/length.

    Shards without a CRC record (a legacy snapshot, or — multi-host —
    shards another host wrote under its own meta) degrade to a header
    parse: presence + a readable ``.npy`` is the best that host can check.
    """
    problems = []
    recorded = meta.get("shards", {})
    for name in _expected_shards(meta):
        p = snap / name
        if not p.exists():
            problems.append(f"missing {name}")
            continue
        rec = recorded.get(name)
        if rec is not None:
            # Stream the CRC in chunks: shards can be device-block-sized
            # (hundreds of MB at 65536² scale) — never a whole-file read.
            crc, n = 0, 0
            with open(p, "rb") as f:
                while chunk := f.read(1 << 20):
                    crc = zlib.crc32(chunk, crc)
                    n += len(chunk)
            if n != rec["bytes"]:
                problems.append(
                    f"truncated {name} ({n} != {rec['bytes']} bytes)")
            elif crc != rec["crc32"]:
                problems.append(f"checksum mismatch in {name}")
        else:
            try:
                np.load(p, mmap_mode="r")
            except Exception:
                problems.append(f"unreadable {name} (no CRC recorded)")
    if problems:
        raise CheckpointCorrupt(
            f"snapshot {snap.name} is torn: {'; '.join(problems[:8])}"
            + (f" (+{len(problems) - 8} more)" if len(problems) > 8 else "")
        )


def save_state(ckpt_dir, arr: jax.Array, meta: dict) -> None:
    """Snapshot a sharded padded (C, Hp, Wp) array + metadata.

    Crash-safe by construction: each snapshot is its own
    ``it_<NNNNNNNN>/`` directory, every shard is serialized in memory
    first and written atomically (tmp + rename) with its CRC32 recorded,
    meta is written last inside the directory, and the ``LATEST`` pointer
    flips atomically only after the snapshot is complete — a crash at any
    point leaves the previous snapshot intact AND leaves the torn one
    detectable (:func:`_validate_snapshot`).  Older snapshots beyond
    KEEP_SNAPSHOTS are pruned.

    Fault sites: ``checkpoint_write_shard`` before each shard write;
    ``checkpoint_write_meta`` twice — before the meta write and before the
    LATEST flip — so tests can kill the save at every boundary.
    """
    d = Path(ckpt_dir)
    d.mkdir(parents=True, exist_ok=True)
    snap = _snap_dir(d, meta["iters_done"])
    snap.mkdir(exist_ok=True)
    R_blocks = meta["grid"]
    block_hw = (arr.shape[1] // R_blocks[0], arr.shape[2] // R_blocks[1])
    shards: dict[str, dict] = {}
    for shard in arr.addressable_shards:
        r, c = _coords(shard.index, block_hw)
        name = f"shard_{r}_{c}.npy"
        buf = io.BytesIO()
        np.save(buf, np.asarray(shard.data))
        raw = buf.getvalue()
        fault_point("checkpoint_write_shard")
        tmp = snap / (name + ".tmp")
        tmp.write_bytes(raw)
        os.replace(tmp, snap / name)
        shards[name] = {"crc32": zlib.crc32(raw), "bytes": len(raw)}
    meta = dict(meta, shards=shards)
    fault_point("checkpoint_write_meta")
    tmp = snap / (META_NAME + ".tmp")
    tmp.write_text(json.dumps(meta))
    os.replace(tmp, snap / META_NAME)
    fault_point("checkpoint_write_meta")
    ptr_tmp = d / (LATEST_NAME + ".tmp")
    ptr_tmp.write_text(snap.name)
    os.replace(ptr_tmp, d / LATEST_NAME)
    # prune old snapshots (multi-host: every host holds its own shards, so
    # each prunes the same dirs; missing-file races are ignored)
    snaps = sorted(p for p in d.iterdir()
                   if p.is_dir() and p.name.startswith("it_"))
    for old in snaps[:-KEEP_SNAPSHOTS]:
        for f in old.iterdir():
            try:
                f.unlink()
            except OSError:
                pass
        try:
            old.rmdir()
        except OSError:
            pass


def load_meta(ckpt_dir) -> dict | None:
    """The LATEST snapshot's meta, unvalidated (shards may still be torn —
    use :func:`load_state` for validated loading)."""
    snap = _latest_snap(ckpt_dir)
    if snap is None:
        return None
    return json.loads((snap / META_NAME).read_text())


def load_state(ckpt_dir, mesh: Mesh,
               fallback: bool = False) -> tuple[jax.Array, dict]:
    """Restore the sharded array (each device reads only its own shard).

    Validates snapshot completeness + per-shard CRC32 before any device
    read; a torn snapshot raises :class:`CheckpointCorrupt` — unless
    ``fallback=True``, in which case the walk continues to the newest
    OLDER snapshot that validates (with a :class:`CheckpointWarning`
    naming what was skipped).  Returns ``(array, meta)`` of the snapshot
    actually loaded, so the caller resumes from its true iteration count.

    A grid mismatch is a config error, not corruption: it raises
    ``ValueError`` immediately, fallback or not.
    """
    candidates = _candidate_snaps(ckpt_dir)
    if not candidates:
        raise FileNotFoundError(f"no checkpoint at {ckpt_dir}")
    grid = grid_shape(mesh)
    last_err: CheckpointCorrupt | None = None
    for snap in candidates:
        meta = json.loads((snap / META_NAME).read_text())
        if tuple(meta["grid"]) != grid:
            raise ValueError(
                f"checkpoint grid {meta['grid']} != mesh grid {list(grid)}"
            )
        try:
            _validate_snapshot(snap, meta)
        except CheckpointCorrupt as e:
            if not fallback:
                raise
            warnings.warn(f"skipping torn snapshot: {e}", CheckpointWarning,
                          stacklevel=2)
            last_err = e
            continue
        shape = tuple(meta["shape"])
        block_hw = (shape[1] // grid[0], shape[2] // grid[1])

        def cb(index, snap=snap, block_hw=block_hw):
            r, c = _coords(index, block_hw)
            return np.load(snap / f"shard_{r}_{c}.npy")

        arr = jax.make_array_from_callback(shape, block_sharding(mesh), cb)
        return arr, meta
    raise CheckpointCorrupt(
        f"no valid snapshot in {ckpt_dir}: every candidate is torn "
        f"(last: {last_err})"
    )


def run_checkpointed(
    xs: jax.Array,
    filt,
    total_iters: int,
    mesh: Mesh,
    valid_hw,
    ckpt_dir,
    every: int,
    quantize: bool = True,
    backend: str = "shifted",
    fuse: int = 1,
    boundary: str = "zero",
    tile: tuple[int, int] | None = None,
    interior_split: bool = False,
    fallback: bool = False,
) -> jax.Array:
    """Iterate with a snapshot every ``every`` iterations; auto-resume.

    If ``ckpt_dir`` holds a compatible checkpoint, continues from its
    iteration count (``xs`` may then be None).  Returns the padded sharded
    result after ``total_iters`` total iterations.

    Resume is resilient by default: a torn LATEST snapshot falls back to
    the newest valid one (:func:`load_state` with ``fallback=True``), and
    if *no* snapshot validates the run restarts from ``xs`` with a
    :class:`CheckpointWarning` — never from corrupt bytes.  ``fallback``
    here is the *backend* degradation knob, threaded to
    ``step.iterate_prepared`` (resilience.degrade).
    """
    from parallel_convolution_tpu.parallel import step as step_lib

    grid = grid_shape(mesh)
    config = {
        "filter": filt.name,
        "quantize": quantize,
        "backend": backend,
        "fuse": fuse,
        "boundary": boundary,
        "valid_hw": list(valid_hw),
        "grid": list(grid),
    }
    # Gate on the config FIRST (one small JSON read): a mismatch must not
    # cost shard validation + a full device load before raising.  All
    # snapshots in a dir come from one run, so the latest meta speaks for
    # every fallback candidate too.
    meta0 = load_meta(ckpt_dir)
    if meta0 is not None:
        saved_cfg = {k: meta0.get(k) for k in config}
        if saved_cfg != config:
            raise ValueError(
                f"checkpoint config mismatch: {saved_cfg} != {config}"
            )
    meta = None
    try:
        loaded_xs, meta = load_state(ckpt_dir, mesh, fallback=True)
    except FileNotFoundError:
        pass
    except CheckpointCorrupt as e:
        warnings.warn(
            f"no usable checkpoint in {ckpt_dir} ({e}); starting fresh",
            CheckpointWarning, stacklevel=2)
    done = 0
    if meta is not None:
        # Re-check against the snapshot actually loaded: with no LATEST
        # pointer yet (a first-save crash) meta0 above was None and the
        # pre-gate never ran.
        saved_cfg = {k: meta.get(k) for k in config}
        if saved_cfg != config:
            raise ValueError(
                f"checkpoint config mismatch: {saved_cfg} != {config}"
            )
        xs = loaded_xs
        done = int(meta["iters_done"])
    if xs is None:
        raise ValueError("no checkpoint found and no initial state given")
    # Validate the quantize-range contract ONCE on the entry state; chunk
    # inputs below are prior chunk outputs, in contract by induction
    # (quantized values are always in [0, 255]).
    step_lib._check_quantize_contract(xs, filt, quantize)

    while done < total_iters:
        chunk = min(every, total_iters - done)
        # tile and interior_split are pure perf knobs (bit-identical for
        # any value in every mode), so they are deliberately NOT part of
        # the resume-compatibility config above.  fuse IS kept there: it
        # is only bit-identical under quantize=True — in float mode with a
        # narrow storage dtype the fused kernel keeps f32 intermediates
        # the unfused path would have rounded through storage every
        # iteration.
        xs = step_lib.iterate_prepared(
            xs, filt, chunk, mesh, valid_hw, interior_split=interior_split,
            quantize=quantize, backend=backend, fuse=min(fuse, chunk),
            boundary=boundary, tile=tile, check_contract=False,
            fallback=fallback,
        )
        done += chunk
        if done < total_iters:  # final state is the caller's to persist
            save_state(
                ckpt_dir, xs,
                {**config, "iters_done": done, "shape": list(xs.shape)},
            )
    return xs
