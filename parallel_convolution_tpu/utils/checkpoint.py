"""Checkpoint / resume for long runs (SURVEY.md §5 aux subsystems).

The reference has none (runs are minutes long, output written once at the
end); this framework adds the natural TPU-native version: every K
iterations the sharded state is snapshotted **per addressable shard** (no
host gather — each device block becomes one ``.npy`` keyed by its grid
coordinates) together with a JSON sidecar recording progress and config.
A restarted run validates the sidecar against its own config and continues
from the saved iteration.

Chunked execution does not perturb semantics: in u8 mode every iteration
ends quantized to exact integers, and float-mode shards are saved as raw
float32, so save/restore is lossless and the checkpointed run remains
bit-identical to an uninterrupted one.
"""

from __future__ import annotations

import json
import os
from pathlib import Path

import jax
import numpy as np
from jax.sharding import Mesh

from parallel_convolution_tpu.parallel.mesh import block_sharding, grid_shape

META_NAME = "meta.json"
LATEST_NAME = "LATEST"
KEEP_SNAPSHOTS = 2


def _coords(index, block_hw) -> tuple[int, int]:
    rs, cs = index[1], index[2]
    return (rs.start or 0) // block_hw[0], (cs.start or 0) // block_hw[1]


def _snap_dir(ckpt_dir, iters_done: int) -> Path:
    return Path(ckpt_dir) / f"it_{int(iters_done):08d}"


def _latest_snap(ckpt_dir) -> Path | None:
    p = Path(ckpt_dir) / LATEST_NAME
    if not p.exists():
        return None
    snap = Path(ckpt_dir) / p.read_text().strip()
    return snap if (snap / META_NAME).exists() else None


def save_state(ckpt_dir, arr: jax.Array, meta: dict) -> None:
    """Snapshot a sharded padded (C, Hp, Wp) array + metadata.

    Crash-safe by construction: each snapshot is its own
    ``it_<NNNNNNNN>/`` directory, meta is written last inside it, and the
    ``LATEST`` pointer flips atomically only after the snapshot is
    complete — a crash at any point leaves the previous snapshot intact.
    Older snapshots beyond KEEP_SNAPSHOTS are pruned.
    """
    d = Path(ckpt_dir)
    d.mkdir(parents=True, exist_ok=True)
    snap = _snap_dir(d, meta["iters_done"])
    snap.mkdir(exist_ok=True)
    R_blocks = meta["grid"]
    block_hw = (arr.shape[1] // R_blocks[0], arr.shape[2] // R_blocks[1])
    for shard in arr.addressable_shards:
        r, c = _coords(shard.index, block_hw)
        np.save(snap / f"shard_{r}_{c}.npy", np.asarray(shard.data))
    tmp = snap / (META_NAME + ".tmp")
    tmp.write_text(json.dumps(meta))
    os.replace(tmp, snap / META_NAME)
    ptr_tmp = d / (LATEST_NAME + ".tmp")
    ptr_tmp.write_text(snap.name)
    os.replace(ptr_tmp, d / LATEST_NAME)
    # prune old snapshots (multi-host: every host holds its own shards, so
    # each prunes the same dirs; missing-file races are ignored)
    snaps = sorted(p for p in d.iterdir()
                   if p.is_dir() and p.name.startswith("it_"))
    for old in snaps[:-KEEP_SNAPSHOTS]:
        for f in old.iterdir():
            try:
                f.unlink()
            except OSError:
                pass
        try:
            old.rmdir()
        except OSError:
            pass


def load_meta(ckpt_dir) -> dict | None:
    snap = _latest_snap(ckpt_dir)
    if snap is None:
        return None
    return json.loads((snap / META_NAME).read_text())


def load_state(ckpt_dir, mesh: Mesh) -> tuple[jax.Array, dict]:
    """Restore the sharded array (each device reads only its own shard)."""
    snap = _latest_snap(ckpt_dir)
    if snap is None:
        raise FileNotFoundError(f"no checkpoint at {ckpt_dir}")
    meta = json.loads((snap / META_NAME).read_text())
    shape = tuple(meta["shape"])
    grid = grid_shape(mesh)
    if tuple(meta["grid"]) != grid:
        raise ValueError(
            f"checkpoint grid {meta['grid']} != mesh grid {list(grid)}"
        )
    block_hw = (shape[1] // grid[0], shape[2] // grid[1])

    def cb(index):
        r, c = _coords(index, block_hw)
        return np.load(snap / f"shard_{r}_{c}.npy")

    arr = jax.make_array_from_callback(shape, block_sharding(mesh), cb)
    return arr, meta


def run_checkpointed(
    xs: jax.Array,
    filt,
    total_iters: int,
    mesh: Mesh,
    valid_hw,
    ckpt_dir,
    every: int,
    quantize: bool = True,
    backend: str = "shifted",
    fuse: int = 1,
    boundary: str = "zero",
    tile: tuple[int, int] | None = None,
    interior_split: bool = False,
) -> jax.Array:
    """Iterate with a snapshot every ``every`` iterations; auto-resume.

    If ``ckpt_dir`` holds a compatible checkpoint, continues from its
    iteration count (``xs`` may then be None).  Returns the padded sharded
    result after ``total_iters`` total iterations.
    """
    from parallel_convolution_tpu.parallel import step as step_lib

    grid = grid_shape(mesh)
    config = {
        "filter": filt.name,
        "quantize": quantize,
        "backend": backend,
        "fuse": fuse,
        "boundary": boundary,
        "valid_hw": list(valid_hw),
        "grid": list(grid),
    }
    meta = load_meta(ckpt_dir)
    done = 0
    if meta is not None:
        saved_cfg = {k: meta.get(k) for k in config}
        if saved_cfg != config:
            raise ValueError(
                f"checkpoint config mismatch: {saved_cfg} != {config}"
            )
        xs, _ = load_state(ckpt_dir, mesh)
        done = int(meta["iters_done"])
    if xs is None:
        raise ValueError("no checkpoint found and no initial state given")
    # Validate the quantize-range contract ONCE on the entry state; chunk
    # inputs below are prior chunk outputs, in contract by induction
    # (quantized values are always in [0, 255]).
    step_lib._check_quantize_contract(xs, filt, quantize)

    while done < total_iters:
        chunk = min(every, total_iters - done)
        # tile and interior_split are pure perf knobs (bit-identical for
        # any value in every mode), so they are deliberately NOT part of
        # the resume-compatibility config above.  fuse IS kept there: it
        # is only bit-identical under quantize=True — in float mode with a
        # narrow storage dtype the fused kernel keeps f32 intermediates
        # the unfused path would have rounded through storage every
        # iteration.
        xs = step_lib.iterate_prepared(
            xs, filt, chunk, mesh, valid_hw, interior_split=interior_split,
            quantize=quantize, backend=backend, fuse=min(fuse, chunk),
            boundary=boundary, tile=tile, check_contract=False,
        )
        done += chunk
        if done < total_iters:  # final state is the caller's to persist
            save_state(
                ckpt_dir, xs,
                {**config, "iters_done": done, "shape": list(xs.shape)},
            )
    return xs
