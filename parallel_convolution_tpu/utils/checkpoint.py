"""Checkpoint / resume for long runs (SURVEY.md §5 aux subsystems).

The reference has none (runs are minutes long, output written once at the
end); this framework adds the natural TPU-native version: every K
iterations the sharded state is snapshotted **per addressable shard** (no
host gather — each device block becomes one ``.npy`` keyed by its grid
coordinates) together with a JSON sidecar recording progress and config.
A restarted run validates the sidecar against its own config and continues
from the saved iteration.

Chunked execution does not perturb semantics: in u8 mode every iteration
ends quantized to exact integers, and float-mode shards are saved as raw
float32, so save/restore is lossless and the checkpointed run remains
bit-identical to an uninterrupted one.

Hardening (resilience PR): snapshots are now *verifiable*, not just
ordered.  Each shard is written atomically (tmp + rename) and its CRC32 +
byte length are recorded in ``meta.json``; loading validates completeness
and checksums and raises :class:`CheckpointCorrupt` on a torn snapshot
(the multi-host prune race: ``meta.json`` present but shard files
missing/truncated).  ``load_state(..., fallback=True)`` — the default
inside :func:`run_checkpointed` — then walks back to the newest snapshot
that does validate instead of crashing or, worse, resuming from garbage.
Injection sites ``checkpoint_write_shard`` / ``checkpoint_write_meta``
(resilience.faults) let tests kill a save between any two writes and
prove the resumed run byte-identical (tests/test_resilience.py).

Elastic recovery (round 10): snapshots are **grid-shape-agnostic**.  A
snapshot written on any ``(P, Q)`` mesh grid loads onto any ``(P', Q')``
grid: per-shard blocks are sliced and reassembled through the same
index maps that wrote them (``_coords`` / ``block_sharding``), the
pad-to-multiple rim is re-derived for the target grid via
``padded_extent``, and each target shard reads only the source shards it
overlaps — gather-free, no host buffer ever holds the full image.  Grid
therefore left the resume-compatibility config: losing a chip (or
getting handed a smaller slice) no longer strands every snapshot.
Validation failures are a **quarantine policy**: a corrupt shard marks
that snapshot degraded — the :class:`CheckpointWarning` names the
snapshot, the shard, and the cause (missing / truncated / checksum
mismatch / unreadable / torn meta) — and ``fallback=True`` reshards
around it from the newest snapshot that still validates.
"""

from __future__ import annotations

import io
import json
import os
import time
import warnings
import zlib
from pathlib import Path

import jax
import numpy as np
from jax.sharding import Mesh

from parallel_convolution_tpu.obs import events as obs_events, metrics as obs_metrics
from parallel_convolution_tpu.parallel.mesh import (
    block_sharding, grid_shape, padded_extent,
)
from parallel_convolution_tpu.resilience import diskio
from parallel_convolution_tpu.resilience.faults import (
    InjectedFault, fault_point,
)


def _note_ckpt(op: str, wall_s: float, nbytes: int, **fields) -> None:
    """One checkpoint op's telemetry: duration histogram, byte counter,
    and the typed timeline event.  One branch when obs is off."""
    if not obs_metrics.enabled():
        return
    obs_metrics.histogram(
        "pctpu_checkpoint_seconds", "checkpoint operation wall time",
        ("op",)).observe(wall_s, op=op)
    obs_metrics.counter(
        "pctpu_checkpoint_bytes_total", "checkpoint bytes written/read",
        ("op",)).inc(nbytes, op=op)
    obs_events.emit(f"checkpoint_{op}", wall_s=round(wall_s, 6),
                    bytes=int(nbytes), **fields)


def _note_quarantine(snap_name: str, problems) -> None:
    if not obs_metrics.enabled():
        return
    c = obs_metrics.counter(
        "pctpu_quarantines_total",
        "shard validation failures by cause (missing/truncated/...)",
        ("cause",))
    for cause, _shard in problems:
        c.inc(cause=cause)
    obs_events.emit("quarantine", snap=snap_name,
                    problems=[[c_, s] for c_, s in problems][:16])

META_NAME = "meta.json"
LATEST_NAME = "LATEST"
KEEP_SNAPSHOTS = 2


class CheckpointCorrupt(RuntimeError):
    """A snapshot's meta exists but its shard set is incomplete/damaged.

    ``snap`` names the snapshot directory; ``problems`` is the per-shard
    diagnosis — ``(cause, shard_name)`` pairs with cause one of
    ``missing | truncated | checksum | unreadable | torn-meta`` — so the
    quarantine warnings can say exactly what was wrong, not just "torn".
    """

    def __init__(self, msg: str, snap: str = "",
                 problems: tuple = ()):  # (cause, shard) pairs
        super().__init__(msg)
        self.snap = snap
        self.problems = tuple(problems)


class CheckpointWarning(UserWarning):
    """A corrupt snapshot was quarantined (skipped in favor of an older or
    fresh state), or a snapshot was resharded onto a different grid."""


def _coords(index, block_hw) -> tuple[int, int]:
    rs, cs = index[1], index[2]
    return (rs.start or 0) // block_hw[0], (cs.start or 0) // block_hw[1]


def _snap_dir(ckpt_dir, iters_done: int) -> Path:
    return Path(ckpt_dir) / f"it_{int(iters_done):08d}"


def _latest_snap(ckpt_dir) -> Path | None:
    p = Path(ckpt_dir) / LATEST_NAME
    if not p.exists():
        return None
    try:
        snap = Path(ckpt_dir) / p.read_text().strip()
    except OSError:  # pointer pruned/replaced mid-read by a sibling host
        return None
    return snap if (snap / META_NAME).exists() else None


def _candidate_snaps(ckpt_dir) -> list[Path]:
    """Snapshots to try, newest-claim first: the LATEST pointer's target,
    then every other ``it_*`` dir with a meta, newest iteration first.

    Robust against a concurrent writer/pruner: directory entries that
    vanish between the listing and the existence check simply drop out
    (the prune-vs-read race is benign by construction — a pruned
    snapshot was never the newest)."""
    d = Path(ckpt_dir)
    first = _latest_snap(d)
    out = [first] if first is not None else []
    rest: list[Path] = []
    try:
        for p in d.iterdir():
            try:
                if (p.is_dir() and p.name.startswith("it_")
                        and (p / META_NAME).exists()):
                    rest.append(p)
            except OSError:
                continue  # entry pruned mid-check
    except OSError:
        pass  # ckpt dir itself missing/unreadable: only LATEST's claim
    rest.sort(key=lambda p: p.name, reverse=True)
    out += [p for p in rest if first is None or p.name != first.name]
    return out


def _read_meta(snap: Path) -> dict:
    """Parse a snapshot's meta; unreadable/invalid JSON (a torn write or
    a dir pruned mid-read) raises :class:`CheckpointCorrupt` with cause
    ``torn-meta`` so the fallback walk can quarantine and continue."""
    try:
        return json.loads((snap / META_NAME).read_text())
    except (OSError, ValueError) as e:
        raise CheckpointCorrupt(
            f"snapshot {snap.name} is torn: unreadable meta ({e})",
            snap=snap.name, problems=(("torn-meta", META_NAME),),
        ) from e


def _expected_shards(meta: dict) -> list[str]:
    g0, g1 = meta["grid"]
    return [f"shard_{r}_{c}.npy" for r in range(g0) for c in range(g1)]


def _validate_snapshot(snap: Path, meta: dict) -> None:
    """Raise :class:`CheckpointCorrupt` unless every expected shard file is
    present and matches its recorded CRC32/length.

    Shards without a CRC record (a legacy snapshot, or — multi-host —
    shards another host wrote under its own meta) degrade to a header
    parse: presence + a readable ``.npy`` is the best that host can check.

    Each shard's verdict carries a cause — ``missing`` / ``truncated`` /
    ``checksum`` / ``unreadable`` — that the quarantine warnings surface
    verbatim; an I/O failure mid-read (``io_read`` fault site) counts as
    ``unreadable``, it quarantines the snapshot rather than killing the
    recovery walk.
    """
    problems: list[tuple[str, str]] = []   # (cause, shard)
    notes: list[str] = []
    recorded = meta.get("shards", {})
    for name in _expected_shards(meta):
        p = snap / name
        if not p.exists():
            problems.append(("missing", name))
            notes.append(f"missing {name}")
            continue
        rec = recorded.get(name)
        if rec is not None:
            # Stream the CRC in chunks: shards can be device-block-sized
            # (hundreds of MB at 65536² scale) — never a whole-file read.
            crc, n = 0, 0
            try:
                fault_point("io_read")  # one consult per shard validation
                with open(p, "rb") as f:
                    while chunk := f.read(1 << 20):
                        crc = zlib.crc32(chunk, crc)
                        n += len(chunk)
            except (OSError, InjectedFault) as e:
                problems.append(("unreadable", name))
                notes.append(f"unreadable {name} ({e})")
                continue
            if n != rec["bytes"]:
                problems.append(("truncated", name))
                notes.append(f"truncated {name} ({n} != {rec['bytes']} bytes)")
            elif crc != rec["crc32"]:
                problems.append(("checksum", name))
                notes.append(f"checksum mismatch in {name}")
        else:
            try:
                np.load(p, mmap_mode="r")
            except Exception:
                problems.append(("unreadable", name))
                notes.append(f"unreadable {name} (no CRC recorded)")
    if problems:
        raise CheckpointCorrupt(
            f"snapshot {snap.name} is torn: {'; '.join(notes[:8])}"
            + (f" (+{len(notes) - 8} more)" if len(notes) > 8 else ""),
            snap=snap.name, problems=problems,
        )


def save_state(ckpt_dir, arr: jax.Array, meta: dict) -> None:
    """Snapshot a sharded padded (C, Hp, Wp) array + metadata.

    Crash-safe by construction: each snapshot is its own
    ``it_<NNNNNNNN>/`` directory, every shard is serialized in memory
    first and written atomically (tmp + rename) with its CRC32 recorded,
    meta is written last inside the directory, and the ``LATEST`` pointer
    flips atomically only after the snapshot is complete — a crash at any
    point leaves the previous snapshot intact AND leaves the torn one
    detectable (:func:`_validate_snapshot`).  Older snapshots beyond
    KEEP_SNAPSHOTS are pruned.

    Fault sites: ``checkpoint_write_shard`` before each shard write;
    ``checkpoint_write_meta`` twice — before the meta write and before the
    LATEST flip — so tests can kill the save at every boundary.
    """
    t_save0 = time.perf_counter()
    d = Path(ckpt_dir)
    d.mkdir(parents=True, exist_ok=True)
    snap = _snap_dir(d, meta["iters_done"])
    snap.mkdir(exist_ok=True)
    R_blocks = meta["grid"]
    block_hw = (arr.shape[1] // R_blocks[0], arr.shape[2] // R_blocks[1])
    shards: dict[str, dict] = {}
    for shard in arr.addressable_shards:
        r, c = _coords(shard.index, block_hw)
        name = f"shard_{r}_{c}.npy"
        buf = io.BytesIO()
        np.save(buf, np.asarray(shard.data))
        raw = buf.getvalue()
        # Routed through diskio (round 24) so drills can shape the
        # failure (ENOSPC/EIO/slow); bare injections raise raw as ever.
        diskio.consult("checkpoint_write_shard")
        tmp = snap / (name + ".tmp")
        tmp.write_bytes(raw)
        os.replace(tmp, snap / name)
        shards[name] = {"crc32": zlib.crc32(raw), "bytes": len(raw)}
    meta = dict(meta, shards=shards)
    diskio.consult("checkpoint_write_meta")
    tmp = snap / (META_NAME + ".tmp")
    tmp.write_text(json.dumps(meta))
    os.replace(tmp, snap / META_NAME)
    diskio.consult("checkpoint_write_meta")
    ptr_tmp = d / (LATEST_NAME + ".tmp")
    ptr_tmp.write_text(snap.name)
    os.replace(ptr_tmp, d / LATEST_NAME)
    _note_ckpt("save", time.perf_counter() - t_save0,
               sum(s["bytes"] for s in shards.values()),
               snap=snap.name, iters_done=int(meta["iters_done"]),
               shards=len(shards))
    # prune old snapshots (multi-host: every host holds its own shards, so
    # each prunes the same dirs; missing-file AND missing-dir races are
    # ignored — a sibling host may have pruned the same dir already)
    try:
        snaps = sorted(p for p in d.iterdir()
                       if p.is_dir() and p.name.startswith("it_"))
    except OSError:
        snaps = []
    for old in snaps[:-KEEP_SNAPSHOTS]:
        try:
            entries = list(old.iterdir())
        except OSError:
            continue  # dir already pruned by a sibling
        for f in entries:
            try:
                f.unlink()
            except OSError:
                pass
        try:
            old.rmdir()
        except OSError:
            pass


def load_meta(ckpt_dir) -> dict | None:
    """The LATEST snapshot's meta, unvalidated (shards may still be torn —
    use :func:`load_state` for validated loading)."""
    snap = _latest_snap(ckpt_dir)
    if snap is None:
        return None
    return _read_meta(snap)


def _valid_hw_of(meta: dict) -> tuple[int, int]:
    """The snapshot's valid (unpadded) image extent.  ``valid_hw`` is in
    every meta :func:`run_checkpointed` writes; hand-rolled metas without
    it fall back to the saved padded extent (every pixel treated valid —
    exact when the source dims divided its grid)."""
    vh = meta.get("valid_hw")
    if vh:
        return int(vh[0]), int(vh[1])
    return int(meta["shape"][1]), int(meta["shape"][2])


def _reshard_callback(snap: Path, meta: dict, target_shape):
    """Per-target-shard assembly from a snapshot written on another grid.

    Gather-free: each target shard opens only the source ``.npy`` blocks
    it overlaps (memmap windows — never a full-file read) and fills its
    pad rim with zeros, re-deriving the target grid's pad-to-multiple
    extents.  The source's own pad rim is never read: positions outside
    the valid image are zero on BOTH grids by the masking invariant, so
    resharding preserves bytes exactly.
    """
    src_grid = tuple(meta["grid"])
    src_shape = tuple(meta["shape"])
    sbh = src_shape[1] // src_grid[0]
    sbw = src_shape[2] // src_grid[1]
    H, W = _valid_hw_of(meta)
    C, Hp, Wp = target_shape
    dtype = np.load(snap / "shard_0_0.npy", mmap_mode="r").dtype

    def cb(index):
        rs, cs = index[1], index[2]
        r0, r1 = rs.start or 0, rs.stop or Hp
        c0, c1 = cs.start or 0, cs.stop or Wp
        out = np.zeros((C, r1 - r0, c1 - c0), dtype)
        vr1, vc1 = min(r1, H), min(c1, W)  # only in-image pixels exist
        if vr1 <= r0 or vc1 <= c0:
            return out  # target shard lies entirely in the new pad rim
        for sr in range(r0 // sbh, (vr1 - 1) // sbh + 1):
            for sc in range(c0 // sbw, (vc1 - 1) // sbw + 1):
                blk = np.load(snap / f"shard_{sr}_{sc}.npy", mmap_mode="r")
                gr0, gr1 = max(r0, sr * sbh), min(vr1, (sr + 1) * sbh)
                gc0, gc1 = max(c0, sc * sbw), min(vc1, (sc + 1) * sbw)
                out[:, gr0 - r0:gr1 - r0, gc0 - c0:gc1 - c0] = (
                    blk[:, gr0 - sr * sbh:gr1 - sr * sbh,
                        gc0 - sc * sbw:gc1 - sc * sbw])
        return out

    return cb


def load_state(ckpt_dir, mesh: Mesh,
               fallback: bool = False) -> tuple[jax.Array, dict]:
    """Restore the sharded array onto ``mesh`` — ANY mesh grid.

    Validates snapshot completeness + per-shard CRC32 before any device
    read; a torn snapshot raises :class:`CheckpointCorrupt` — unless
    ``fallback=True``, in which case the snapshot is *quarantined* (a
    :class:`CheckpointWarning` naming the snapshot, the shard, and the
    cause) and the walk continues to the newest OLDER snapshot that
    validates.  Returns ``(array, meta)`` of the snapshot actually
    loaded, so the caller resumes from its true iteration count.

    Grid-shape-agnostic (round 10): when the snapshot's grid differs
    from ``mesh``'s, shards are sliced and reassembled per target shard
    (:func:`_reshard_callback`) with the pad rim re-derived for the new
    grid — ``meta['resharded_from']`` then records the source grid.
    When the grids match, each device reads exactly its own shard file,
    as before.
    """
    t_load0 = time.perf_counter()
    candidates = _candidate_snaps(ckpt_dir)
    if not candidates:
        raise FileNotFoundError(f"no checkpoint at {ckpt_dir}")
    grid = grid_shape(mesh)
    last_err: CheckpointCorrupt | None = None
    for snap in candidates:
        try:
            meta = _read_meta(snap)
            _validate_snapshot(snap, meta)
        except CheckpointCorrupt as e:
            _note_quarantine(e.snap or snap.name, e.problems)
            if not fallback:
                raise
            warnings.warn(
                f"quarantined torn snapshot {e.snap or snap.name}: {e}",
                CheckpointWarning, stacklevel=2)
            last_err = e
            continue
        src_grid = tuple(meta["grid"])
        if src_grid == grid:
            shape = tuple(meta["shape"])
            block_hw = (shape[1] // grid[0], shape[2] // grid[1])

            def cb(index, snap=snap, block_hw=block_hw):
                r, c = _coords(index, block_hw)
                return np.load(snap / f"shard_{r}_{c}.npy")

        else:
            H, W = _valid_hw_of(meta)
            shape = (int(meta["shape"][0]),
                     padded_extent(H, grid[0]), padded_extent(W, grid[1]))
            cb = _reshard_callback(snap, meta, shape)
            meta = dict(meta, resharded_from=list(src_grid),
                        grid=list(grid), shape=list(shape))
            warnings.warn(
                f"resharding snapshot {snap.name} from grid "
                f"{src_grid[0]}x{src_grid[1]} onto {grid[0]}x{grid[1]}",
                CheckpointWarning, stacklevel=2)
        arr = jax.make_array_from_callback(shape, block_sharding(mesh), cb)
        nbytes = sum(s.get("bytes", 0)
                     for s in meta.get("shards", {}).values())
        op = "load" if src_grid == grid else "reshard"
        _note_ckpt(op, time.perf_counter() - t_load0, nbytes,
                   snap=snap.name, iters_done=int(meta.get("iters_done", 0)),
                   grid=f"{grid[0]}x{grid[1]}",
                   **({"resharded_from":
                       f"{src_grid[0]}x{src_grid[1]}"}
                      if src_grid != grid else {}))
        return arr, meta
    raise CheckpointCorrupt(
        f"no valid snapshot in {ckpt_dir}: every candidate is torn "
        f"(last: {last_err})"
    )


def run_checkpointed(
    xs: jax.Array,
    filt,
    total_iters: int,
    mesh: Mesh,
    valid_hw,
    ckpt_dir,
    every: int,
    quantize: bool = True,
    backend: str = "shifted",
    fuse: int = 1,
    boundary: str = "zero",
    tile: tuple[int, int] | None = None,
    interior_split: bool = False,
    fallback: bool = False,
    overlap: bool | None = None,
    col_mode: str | None = None,
) -> jax.Array:
    """Iterate with a snapshot every ``every`` iterations; auto-resume.

    If ``ckpt_dir`` holds a compatible checkpoint, continues from its
    iteration count (``xs`` may then be None).  Returns the padded sharded
    result after ``total_iters`` total iterations.

    Resume is resilient by default: a torn LATEST snapshot falls back to
    the newest valid one (:func:`load_state` with ``fallback=True``), and
    if *no* snapshot validates the run restarts from ``xs`` with a
    :class:`CheckpointWarning` — never from corrupt bytes.  The mesh may
    have a DIFFERENT grid than the one that wrote the checkpoint
    (elastic recovery: resume a 2x4 run on whatever slice survives) —
    shards reshard transparently and bytes stay identical.  ``fallback``
    here is the *backend* degradation knob, threaded to
    ``step.iterate_prepared`` (resilience.degrade).
    """
    from parallel_convolution_tpu.parallel import step as step_lib

    grid = grid_shape(mesh)
    # Resume-compatibility config.  Grid is deliberately NOT part of it:
    # the grid is a property of the hardware you resume on, not of the
    # run — snapshots reshard onto whatever mesh is alive.
    config = {
        "filter": filt.name,
        "quantize": quantize,
        "backend": backend,
        "fuse": fuse,
        "boundary": boundary,
        "valid_hw": list(valid_hw),
    }
    # Gate on the config FIRST (one small JSON read): a mismatch must not
    # cost shard validation + a full device load before raising.  All
    # snapshots in a dir come from one run, so the latest meta speaks for
    # every fallback candidate too.
    try:
        meta0 = load_meta(ckpt_dir)
    except CheckpointCorrupt:
        meta0 = None  # torn meta: the validated walk below handles it
    if meta0 is not None:
        saved_cfg = {k: meta0.get(k) for k in config}
        if saved_cfg != config:
            raise ValueError(
                f"checkpoint config mismatch: {saved_cfg} != {config}"
            )
    meta = None
    try:
        loaded_xs, meta = load_state(ckpt_dir, mesh, fallback=True)
    except FileNotFoundError:
        pass
    except CheckpointCorrupt as e:
        warnings.warn(
            f"no usable checkpoint in {ckpt_dir} ({e}); starting fresh",
            CheckpointWarning, stacklevel=2)
    done = 0
    if meta is not None:
        # Re-check against the snapshot actually loaded: with no LATEST
        # pointer yet (a first-save crash) meta0 above was None and the
        # pre-gate never ran.
        saved_cfg = {k: meta.get(k) for k in config}
        if saved_cfg != config:
            raise ValueError(
                f"checkpoint config mismatch: {saved_cfg} != {config}"
            )
        xs = loaded_xs
        done = int(meta["iters_done"])
    if xs is None:
        raise ValueError("no checkpoint found and no initial state given")
    # Validate the quantize-range contract ONCE on the entry state; chunk
    # inputs below are prior chunk outputs, in contract by induction
    # (quantized values are always in [0, 255]).
    step_lib._check_quantize_contract(xs, filt, quantize)

    while done < total_iters:
        chunk = min(every, total_iters - done)
        # tile, interior_split, and overlap are pure perf knobs
        # (bit-identical for any value in every mode), so they are
        # deliberately NOT part of
        # the resume-compatibility config above.  fuse IS kept there: it
        # is only bit-identical under quantize=True — in float mode with a
        # narrow storage dtype the fused kernel keeps f32 intermediates
        # the unfused path would have rounded through storage every
        # iteration.
        xs = step_lib.iterate_prepared(
            xs, filt, chunk, mesh, valid_hw, interior_split=interior_split,
            quantize=quantize, backend=backend, fuse=min(fuse, chunk),
            boundary=boundary, tile=tile, check_contract=False,
            fallback=fallback, overlap=overlap, col_mode=col_mode,
        )
        done += chunk
        if done < total_iters:  # final state is the caller's to persist
            save_state(
                ckpt_dir, xs,
                {**config, "grid": list(grid), "iters_done": done,
                 "shape": list(xs.shape)},
            )
    return xs
