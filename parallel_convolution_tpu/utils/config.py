"""Run configuration (SURVEY.md §5 'config / flag system').

The reference's configuration is argv/stdin plus compile-time constants.
Here a single dataclass captures a full run — image geometry, filter,
mesh, backend knobs — serializable to/from JSON so runs are reproducible
artifacts (the sidecar `utils.checkpoint` writes is a subset of this).
"""

from __future__ import annotations

import dataclasses
import json

# Canonical backend/storage registries.  Live here (jax-free module) so
# config validation stays dependency-light; parallel.step maps the names to
# implementations (and asserts it covers them), the CLI builds its choices
# from them.
BACKENDS = ("shifted", "xla_conv", "pallas", "separable", "pallas_sep",
            "pallas_rdma")
# The autotuning sentinel: not an implementation — entry points resolve it
# through parallel_convolution_tpu.tuning (plan cache, else cost model)
# BEFORE anything that needs a concrete backend name sees it.
AUTO = "auto"
BACKEND_CHOICES = BACKENDS + (AUTO,)
STORAGES = ("f32", "bf16", "u8")
BOUNDARIES = ("zero", "periodic")
# Convergence solver registry (round 15): how a run-to-convergence job
# reaches its fixed point.  "jacobi" is the reference's plain sweep loop
# (sharded_converge); "multigrid" is the geometric V-cycle
# (solvers.multigrid) — same stopping measure, orders of magnitude fewer
# fine-grid work units.  Jax-free here so CLI/serving validation and the
# wire schema share one source.
SOLVERS = ("jacobi", "multigrid")

# Rank-3 volumetric registries (round 23).  The kernel-form registry
# keys rank-3 programs by (3, name, boundary); these jax-free tuples are
# the canonical name sets the CLI, serving validation, and the pinned
# key-set test all read.  ``smooth`` forms are Jacobi relaxations a
# converge loop may drive; ``physics`` forms are time-dependent
# integrators (fixed-step only).  Every rank-3 form carries TWO fields
# stacked leading: (u, f) for the FD forms, (u, u_prev) for wave,
# (U, V) for Gray–Scott.
RANKS = (2, 3)
VOLUME_SMOOTH_FORMS = ("fd7", "fd7_stack", "fd25", "fd25_stack")
VOLUME_PHYSICS_FORMS = ("wave", "grayscott")
VOLUME_FORMS = VOLUME_SMOOTH_FORMS + VOLUME_PHYSICS_FORMS
VOLUME_FIELDS = 2
# Ghost radius per rank-3 form (fd25 is the 8th-order star).
VOLUME_RADII = {"fd7": 1, "fd7_stack": 1, "fd25": 4, "fd25_stack": 4,
                "wave": 1, "grayscott": 1}

# Column-slab transports of the RDMA kernels (round 16, the
# derived-datatypes A/B): "packed" stages the strided slab through a
# contiguous buffer and moves ONE dense RDMA; "strided" issues the
# direct strided copy; "auto" lets the cost model pick.  Jax-free here
# so CLI/serving validation, the plan schema, and the channel layer
# share one source.
COL_MODES = ("packed", "strided")
COL_MODE_CHOICES = COL_MODES + ("auto",)

# Env escape hatch: run the overlapped RDMA pipeline under interpreted
# Pallas anyway (CI byte proofs).  Lives here (jax-free) because BOTH
# the dispatch clamp (parallel/step.resolve_overlap) and the tuner's
# candidate enumeration (tuning/search._legal_overlaps) honor it — the
# two must read the same switch or auto could tune a form dispatch
# refuses to compile.
OVERLAP_INTERPRET_ENV = "PCTPU_OVERLAP_INTERPRET"


@dataclasses.dataclass
class RunConfig:
    """Everything needed to reproduce one filtering run."""

    rows: int
    cols: int
    rank: int = 2                  # 2 = planar (C, H, W); 3 = volume
    #                                (F, D, H, W) through volumes/
    depth: int | None = None       # D extent (rank 3 only)
    mode: str = "grey"            # grey | rgb
    filter_name: str = "blur3"
    iters: int = 100
    mesh_shape: tuple[int, int] | None = None   # None = all devices
    backend: str = "shifted"       # any of parallel.step.BACKENDS, or
    #                                "auto" (plan-cache/cost-model resolved)
    storage: str = "f32"           # f32 | bf16
    fuse: int | None = 1           # None = tune it (backend="auto" only)
    tile: tuple[int, int] | None = None   # Pallas kernel tile (TH, TW)
    overlap: bool | None = None    # interior-first overlapped halo
    #                                pipeline (RDMA kernels): None = off
    #                                for explicit backends, tuned for
    #                                "auto"; True/False = clamped request
    col_mode: str | None = None    # RDMA column-slab transport: None or
    #                                "auto" = cost-model pick; packed/
    #                                strided honored on the RDMA tier
    #                                (byte-identical either way)
    boundary: str = "zero"
    quantize: bool = True
    converge_tol: float | None = None
    solver: str = "jacobi"         # convergence strategy (SOLVERS) for
    #                                converge_tol runs; "multigrid"
    #                                requires quantize=False + f32
    mg_levels: int | None = None   # multigrid level-count cap
    check_every: int = 10
    sharded_io: bool = False
    checkpoint_dir: str | None = None
    checkpoint_every: int = 100
    fallback: bool = False  # graceful backend degradation on transient
    #                         compile/launch failure (resilience.degrade)

    def __post_init__(self) -> None:
        if self.rank not in RANKS:
            raise ValueError(f"rank must be one of {RANKS}, got {self.rank}")
        if self.rank == 3:
            if self.depth is None or int(self.depth) < 1:
                raise ValueError(
                    f"rank=3 needs a positive depth, got {self.depth}")
            if self.filter_name not in VOLUME_FORMS:
                raise ValueError(
                    f"rank-3 form must be one of {VOLUME_FORMS}, got "
                    f"{self.filter_name!r}")
            if self.quantize or self.storage != "f32":
                raise ValueError(
                    "rank=3 runs float carries: quantize=False, "
                    "storage='f32'")
        elif self.depth is not None:
            raise ValueError("depth is a rank-3 knob (set rank=3)")
        if self.mode not in ("grey", "rgb"):
            raise ValueError(f"mode must be grey|rgb, got {self.mode!r}")
        if self.storage not in STORAGES:
            raise ValueError(
                f"storage must be one of {STORAGES}, got {self.storage!r}")
        if self.backend not in BACKEND_CHOICES:
            raise ValueError(f"unknown backend {self.backend!r}")
        if self.boundary not in BOUNDARIES:
            raise ValueError(
                f"boundary must be one of {BOUNDARIES}, got {self.boundary!r}")
        if self.storage == "u8" and not self.quantize:
            # u8 carries can only hold the quantized integer states; a float
            # Jacobi iterate would be silently truncated every iteration.
            raise ValueError("storage='u8' requires quantize=True")
        if self.solver not in SOLVERS:
            raise ValueError(
                f"solver must be one of {SOLVERS}, got {self.solver!r}")
        if self.mg_levels is not None and int(self.mg_levels) < 1:
            raise ValueError(f"mg_levels must be >= 1, got {self.mg_levels}")
        if self.solver == "multigrid" and self.converge_tol is not None:
            # The V-cycle's residual/correction fields are signed floats:
            # fail the config here, not deep inside a traced program.
            if self.quantize:
                raise ValueError(
                    "solver='multigrid' requires quantize=False")
            if self.storage != "f32":
                raise ValueError(
                    "solver='multigrid' requires storage='f32'")
        if (self.rows <= 0 or self.cols <= 0 or self.iters < 0
                or (self.fuse is not None and self.fuse < 1)):
            raise ValueError("rows/cols must be positive, iters >= 0, fuse >= 1")
        if self.fuse is None and self.backend != AUTO:
            raise ValueError(
                "fuse=None means 'tune it' and needs backend='auto'")
        if self.overlap is not None:
            self.overlap = bool(self.overlap)
        if (self.col_mode is not None
                and self.col_mode not in COL_MODE_CHOICES):
            raise ValueError(
                f"col_mode must be one of {COL_MODE_CHOICES}, got "
                f"{self.col_mode!r}")
        if self.mesh_shape is not None:
            self.mesh_shape = tuple(self.mesh_shape)
        if self.tile is not None:
            self.tile = tuple(int(v) for v in self.tile)
            if len(self.tile) != 2 or min(self.tile) <= 0:
                raise ValueError(
                    f"tile must be two positive ints (TH, TW), got {self.tile}")

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2)

    @classmethod
    def from_json(cls, text: str) -> "RunConfig":
        return cls(**json.loads(text))

    def build_model(self):
        """Instantiate the ConvolutionModel this config describes."""
        from parallel_convolution_tpu.models import ConvolutionModel
        from parallel_convolution_tpu.parallel.mesh import make_grid_mesh

        mesh = None
        if self.mesh_shape is not None:
            import jax

            r, c = self.mesh_shape
            mesh = make_grid_mesh(jax.devices()[: r * c], (r, c))
        return ConvolutionModel(
            filt=self.filter_name, mesh=mesh, backend=self.backend,
            quantize=self.quantize, storage=self.storage, fuse=self.fuse,
            boundary=self.boundary, tile=self.tile, overlap=self.overlap,
            col_mode=self.col_mode, fallback=self.fallback,
        )
