"""Shared-evidence JSONL rewriting that preserves foreign lanes.

Several smoke legs co-own one curve file (``evidence/scale_curve.jsonl``
holds scale_smoke's un-laned rows AND shard_smoke's ``router_scale``
lane; the cache smoke adds a ``cache_skew`` lane).  Each writer must
rewrite ONLY its own rows and keep every other lane's lines byte-for-
byte — round 21 proved this inline in two scripts with two slightly
different copies; this module is the one shared implementation, and
``scripts/static_check.py`` forbids any other open-for-write of a
shared curve file so the next smoke script cannot silently clobber a
foreign lane.

Ownership is declared by ``lane``:

* ``lane=None`` — the caller owns the UN-LANED rows (scale_smoke's
  contract): lines whose JSON carries a truthy ``"lane"`` are foreign
  and preserved.
* ``lane="router_scale"`` — the caller owns exactly that lane: lines
  with any OTHER lane (including none) are preserved.

Unparseable lines are dropped (same tolerance both inline copies had:
a torn line is not evidence).  The rewrite is atomic (temp +
``os.replace``) so a crashed smoke can never leave a half-written
curve for the next leg's gate to misread.
"""

from __future__ import annotations

import json
import os
import tempfile
from pathlib import Path

from parallel_convolution_tpu.resilience import diskio

__all__ = ["rewrite_shared_jsonl"]


def rewrite_shared_jsonl(path, rows, *, lane: str | None = None) -> int:
    """Rewrite ``path`` with ``rows`` (this writer's lane), preserving
    every foreign line.  ``rows`` that do not already carry the owned
    ``lane`` are stamped with it.  Returns the number of foreign lines
    preserved.
    """
    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    foreign: list[str] = []
    if p.exists():
        for line in p.read_text().splitlines():
            if not line.strip():
                continue
            try:
                row_lane = json.loads(line).get("lane")
            except (ValueError, AttributeError):
                continue
            if (row_lane if lane is None else row_lane != lane):
                foreign.append(line)
    out_rows = []
    for r in rows:
        r = dict(r)
        if lane is not None:
            r.setdefault("lane", lane)
        out_rows.append(r)
    # evidence_write guard (round 24): a full/dying disk surfaces HERE,
    # typed, before any byte moves — the temp+replace discipline below
    # means a fault can never tear the shared curve itself.
    diskio.consult("evidence_write")
    fd, tmp = tempfile.mkstemp(dir=str(p.parent), prefix=f".{p.name}.",
                               suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            if lane is None:
                # The un-laned owner leads (scale_smoke's established
                # file shape: own rows first, foreign lanes after).
                for r in out_rows:
                    f.write(json.dumps(r) + "\n")
                for line in foreign:
                    f.write(line + "\n")
            else:
                # Lane owners append after the foreign lines they kept.
                for line in foreign:
                    f.write(line + "\n")
                for r in out_rows:
                    f.write(json.dumps(r) + "\n")
        os.replace(tmp, p)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return len(foreign)
