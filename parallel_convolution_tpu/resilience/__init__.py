"""Resilience layer: fault injection, classified retry, backend degradation.

The project's own operational history is the motivation (BASELINE.md,
ROUND4/5 notes): four multi-hour tunnel outages, a tiled-RDMA compile
crash on silicon, two driver rounds whose headline bench row was a silent
CPU fallback, and per-round shell scripts re-encoding retry/terminal
logic nobody could test.  This package turns each of those observed
outage modes into a first-class, deterministic, replayable mechanism:

* :mod:`~parallel_convolution_tpu.resilience.faults` — a seeded,
  process-global fault plan with named injection sites that library code
  consults via the zero-overhead-when-disabled :func:`fault_point` hook.
* :mod:`~parallel_convolution_tpu.resilience.retry` — the
  transient/terminal error taxonomy (:func:`classify`) and
  :func:`with_retry`, capped exponential backoff with deterministic
  jitter — the one tested implementation of the loop that previously
  lived, divergently, in ``tunnel_watch.sh`` and ``chip_session_r5*.sh``.
* :mod:`~parallel_convolution_tpu.resilience.degrade` — graceful backend
  degradation: probe a backend once per (mesh, config) per process and
  walk ``pallas_rdma → pallas → shifted`` on classified-transient
  compile/launch failure, so a fallback can never silently masquerade as
  the requested tier (the effective backend is stamped into bench rows).
* :mod:`~parallel_convolution_tpu.resilience.supervisor` — the leg-queue
  runner behind ``scripts/run_supervised.py``: per-leg completion
  predicates, terminal-failure sentinel file, JSON status ledger, and
  (round 10) reshape-aware legs that walk a mesh ladder when an attempt
  dies with a device-loss signature.
* :mod:`~parallel_convolution_tpu.resilience.elastic` — elastic mesh
  recovery: device-set change detection (child-process health probe),
  the shrink ladder, and new-mesh construction — the glue between
  grid-agnostic checkpoints, the supervisor's reshape legs, and the
  serving engine's mid-process ``reshape()``.
* :mod:`~parallel_convolution_tpu.resilience.breaker` — the per-replica
  circuit breaker (closed → open → half-open) the serving router's
  passive health signal rides; failure counting reuses
  :func:`~parallel_convolution_tpu.resilience.retry.classify` so a
  request's own contract bug never opens a replica's circuit.

Everything here except ``degrade``'s probe is jax-free and import-light,
so hooks can live in modules (``utils.platform``) that must parse
``--help`` without paying backend startup.
"""

from parallel_convolution_tpu.resilience import elastic  # noqa: F401
from parallel_convolution_tpu.resilience.breaker import (  # noqa: F401
    CircuitBreaker,
)
from parallel_convolution_tpu.resilience.faults import (  # noqa: F401
    InjectedFault,
    KNOWN_SITES,
    fault_point,
    injected,
    install_plan,
    plan_from_env,
    plan_from_spec,
    uninstall_plan,
)
from parallel_convolution_tpu.resilience.retry import (  # noqa: F401
    RetryExhausted,
    RetryPolicy,
    classify,
    with_retry,
)

__all__ = [
    "CircuitBreaker", "InjectedFault", "KNOWN_SITES", "elastic",
    "fault_point", "injected", "install_plan", "plan_from_env",
    "plan_from_spec", "uninstall_plan",
    "RetryExhausted", "RetryPolicy", "classify", "with_retry",
]
