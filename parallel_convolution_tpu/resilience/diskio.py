"""Guarded disk IO: storage-shaped failure injection for every durable
surface (round 24).

Rounds 18–21 drilled PROCESS death (chaos transport, fenced WAL
takeover); every disk-backed subsystem still assumed the filesystem
beneath it was healthy and fast.  This module closes that gap the same
way ``serving.chaos`` closed the network one: the proven, seeded
``PCTPU_FAULTS`` machinery decides WHEN a disk surface fails (hit
counters / ranges / probabilities — replayable bit-for-bit), and a
per-site **disk mode** map decides WHAT the failure looks like — the
ways real disks actually fail:

* ``enospc``     — ``OSError(ENOSPC)`` before any byte lands (full disk);
* ``eio``        — ``OSError(EIO)`` (dying device / dead file handle);
* ``torn_write`` — a PREFIX of the payload lands, then ``EIO`` (power
  loss mid-write: the bytes on disk are garbage a reader must detect);
* ``slow_write`` — the operation succeeds after a seeded stall (a
  saturated device: latency, not loss).

With no mode installed for a triggered site the raw
:class:`~.faults.InjectedFault` re-raises untranslated — every drill
written before this module behaves exactly as it did.  With no fault
plan installed at all, each guard is one global load + ``is None`` test
plus a plain write — safe on hot paths.

Owners route their write-mode IO through the guards
(:func:`guarded_write` / :func:`guarded_fsync` / :func:`guarded_open` /
:func:`guarded_replace`), or consult :func:`consult` around IO they must
shape themselves; ``scripts/static_check.py`` check 6 pins the
convention (any write-mode ``open``/``os.replace`` under ``serving/``,
``obs/``, ``utils/`` lives in an allowlisted guarded owner).

stdlib-only, import-light, jax-free.
"""

from __future__ import annotations

import errno
import os
import threading
import time

from parallel_convolution_tpu.resilience.faults import (
    InjectedFault, fault_point,
)

__all__ = ["DISK_SITES", "consult", "deferred_consult", "guarded_fsync",
           "guarded_open", "guarded_replace", "guarded_write",
           "injected_counts", "install_modes", "installed_modes",
           "modes_from_env", "modes_from_spec", "uninstall_modes"]

# site -> the disk failure shapes it can take.  Every site here is a
# KNOWN_SITES member (faults.SITE_TABLE is the one registry); the mode
# list bounds what a spec may ask for, so a typo'd mode can't silently
# never fire.  torn_write is only offered where a partial payload can
# actually land (buffered writes), not on fsync barriers.
DISK_SITES = {
    "wal_write": ("enospc", "eio", "torn_write", "slow_write"),
    "wal_fsync": ("enospc", "eio", "slow_write"),
    "checkpoint_write_shard": ("enospc", "eio", "torn_write",
                               "slow_write"),
    "checkpoint_write_meta": ("enospc", "eio", "torn_write",
                              "slow_write"),
    "cache_spill": ("enospc", "eio", "torn_write", "slow_write"),
    "cache_promote": ("eio", "slow_write"),
    "events_emit": ("enospc", "eio", "slow_write"),
    "evidence_write": ("enospc", "eio", "torn_write", "slow_write"),
}

# Literal consults per site — the fault-site drift guard
# (tests/test_chaos.py) greps the tree for literal site-name consults,
# so the documented registry can never silently lose a consult hidden
# behind a variable.  The four NEW round-24 sites live only here; their
# owners (cache, events, evidence_io) consult through this table.
_CONSULTS = {
    "wal_write": lambda: fault_point("wal_write"),
    "wal_fsync": lambda: fault_point("wal_fsync"),
    "checkpoint_write_shard":
        lambda: fault_point("checkpoint_write_shard"),
    "checkpoint_write_meta":
        lambda: fault_point("checkpoint_write_meta"),
    "cache_spill": lambda: fault_point("cache_spill"),
    "cache_promote": lambda: fault_point("cache_promote"),
    "events_emit": lambda: fault_point("events_emit"),
    "evidence_write": lambda: fault_point("evidence_write"),
}

# Mean injected stall for slow_write (the actual sleep is deterministic
# per hit — storage drills assert wall-clock floors, not jitter shapes).
SLOW_WRITE_S = 0.05

# The process-global mode map, installed next to the fault plan (specs
# ride PCTPU_DISK_MODES in the env, "site=mode,..." from drills).  Read
# without a lock: installed before the workload starts, and a torn read
# can only see a fully constructed dict (CPython attribute store is
# atomic) — the faults._PLAN rule.
_MODES: dict[str, str] = {}
_COUNTS: dict[tuple[str, str], int] = {}   # (site, mode) -> injections
_COUNTS_LOCK = threading.Lock()


def modes_from_spec(spec: str) -> dict[str, str]:
    """Parse ``site=mode,site=mode``; raises ValueError on unknown
    sites/modes so a typo can't silently noop (the chaos-mode rule)."""
    out: dict[str, str] = {}
    for part in filter(None, (p.strip() for p in spec.split(","))):
        if "=" not in part:
            raise ValueError(
                f"bad disk mode {part!r}: expected site=mode")
        site, mode = (s.strip() for s in part.split("=", 1))
        if site not in DISK_SITES:
            raise ValueError(
                f"unknown disk site {site!r}; known: "
                f"{sorted(DISK_SITES)}")
        if mode not in DISK_SITES[site]:
            raise ValueError(
                f"unknown disk mode {mode!r} for {site}; known: "
                f"{DISK_SITES[site]}")
        out[site] = mode
    return out


def install_modes(modes: dict[str, str] | str | None) -> None:
    """Install the process-global disk-mode map (validated); None or an
    empty spec clears it."""
    global _MODES
    if isinstance(modes, str):
        modes = modes_from_spec(modes)
    if modes:
        # Re-validate dict input the same way a spec is validated.
        bad = [(s, m) for s, m in modes.items()
               if s not in DISK_SITES or m not in DISK_SITES.get(s, ())]
        if bad:
            raise ValueError(f"unknown disk site/mode pair(s) {bad}")
    _MODES = dict(modes or {})


def uninstall_modes() -> None:
    global _MODES
    _MODES = {}


def installed_modes() -> dict[str, str]:
    return dict(_MODES)


def modes_from_env(env: dict | None = None) -> dict[str, str]:
    """``PCTPU_DISK_MODES`` → a validated mode map (empty when unset)."""
    env = os.environ if env is None else env
    spec = (env.get("PCTPU_DISK_MODES") or "").strip()
    return modes_from_spec(spec) if spec else {}


def install_from_env(env: dict | None = None) -> dict[str, str]:
    """Install ``PCTPU_DISK_MODES`` (scripts call this at boot);
    returns what was installed."""
    modes = modes_from_env(env)
    install_modes(modes)
    return modes


def injected_counts() -> dict[str, int]:
    """``"site=mode" -> count`` of injections actually translated here
    (drill asserts; the raw trigger counts live on the fault plan)."""
    with _COUNTS_LOCK:
        return {f"{s}={m}": n for (s, m), n in sorted(_COUNTS.items())}


def _note(site: str, mode: str) -> None:
    with _COUNTS_LOCK:
        _COUNTS[(site, mode)] = _COUNTS.get((site, mode), 0) + 1
    # Metrics only — no obs event here: FaultPlan.check already emitted
    # the fault_trigger event, and the events_emit site consulting back
    # into the event log is exactly the recursion this avoids.
    from parallel_convolution_tpu.obs import metrics

    if metrics.enabled():
        metrics.counter(
            "pctpu_disk_faults_total",
            "storage-shaped failures injected by resilience.diskio",
            ("site", "mode")).inc(site=site, mode=mode)


def _trigger(site: str) -> str | None:
    """Consult the site; returns the installed disk mode when the plan
    fires (counted), None when it doesn't.  A triggered site with NO
    installed mode re-raises the raw InjectedFault (pre-round-24
    drills keep their exact semantics)."""
    try:
        _CONSULTS[site]()
        return None
    except InjectedFault:
        mode = _MODES.get(site)
        if mode is None:
            raise
        _note(site, mode)
        return mode


def _raise_mode(site: str, mode: str) -> None:
    if mode == "enospc":
        raise OSError(errno.ENOSPC,
                      f"injected ENOSPC at {site} (disk full)")
    raise OSError(errno.EIO, f"injected EIO at {site} ({mode})")


def consult(site: str) -> None:
    """Bare guard for IO the caller shapes itself (reads, renames,
    probes): ENOSPC/EIO/torn all raise their ``OSError`` here (a torn
    read surface can't half-succeed), slow_write stalls then returns."""
    mode = _trigger(site)
    if mode is None:
        return
    if mode == "slow_write":
        time.sleep(SLOW_WRITE_S)
        return
    _raise_mode(site, "eio" if mode == "torn_write" else mode)


def deferred_consult(site: str) -> str | None:
    """Like :func:`consult`, but returns ``"torn_write"`` instead of
    raising it, so the caller can land the torn prefix at its REAL
    write site (the WAL shape: the garbage must hit the journal tail,
    where the reader's CRC check is the thing under test).  Every
    other mode behaves as in :func:`consult`; returns None when
    nothing fired."""
    mode = _trigger(site)
    if mode is None:
        return None
    if mode == "slow_write":
        time.sleep(SLOW_WRITE_S)
        return None
    if mode == "torn_write":
        return "torn_write"
    _raise_mode(site, mode)


def guarded_write(site: str, fh, data):
    """``fh.write(data)`` under the site's guard.  torn_write lands a
    PREFIX of the payload and flushes it before raising — the bytes a
    power loss leaves behind, which the reader's CRC/length checks must
    catch."""
    mode = _trigger(site)
    if mode is not None:
        if mode == "slow_write":
            time.sleep(SLOW_WRITE_S)
        elif mode == "torn_write":
            fh.write(data[:max(1, len(data) // 2)])
            fh.flush()
            raise OSError(errno.EIO,
                          f"injected torn write at {site}")
        else:
            _raise_mode(site, mode)
    return fh.write(data)


def guarded_fsync(site: str, fh) -> None:
    """``os.fsync(fh)`` under the site's guard (the record may be
    WRITTEN but not durable when this fires — the wal_fsync shape)."""
    mode = _trigger(site)
    if mode is not None:
        if mode == "slow_write":
            time.sleep(SLOW_WRITE_S)
        else:
            _raise_mode(site, mode)
    os.fsync(fh.fileno() if hasattr(fh, "fileno") else fh)


def guarded_open(site: str, path, mode: str = "r", **kw):
    """``open(path, mode)`` under the site's guard (a failed open is how
    a dead directory/quota surfaces before any byte is written)."""
    m = _trigger(site)
    if m is not None:
        if m == "slow_write":
            time.sleep(SLOW_WRITE_S)
        else:
            _raise_mode(site, "eio" if m == "torn_write" else m)
    return open(path, mode, **kw)


def guarded_replace(site: str, src, dst) -> None:
    """``os.replace(src, dst)`` under the site's guard.  torn_write on a
    rename surface means the METADATA operation died (EIO) — rename is
    atomic, so no half-state is modeled; the src file simply stays."""
    mode = _trigger(site)
    if mode is not None:
        if mode == "slow_write":
            time.sleep(SLOW_WRITE_S)
        else:
            _raise_mode(site, "eio" if mode == "torn_write" else mode)
    os.replace(src, dst)
