"""Supervised leg queue: the tested replacement for the chip-session shell.

``tunnel_watch.sh`` + ``chip_session_r5*.sh`` encoded, in copy-pasted
shell, exactly three ideas: (1) a queue of legs, each complete iff its
output artifact exists (often with a required content pattern, e.g. a
``"summary"`` row); (2) retry-on-transient with the tunnel probed between
passes; (3) a terminal-failure sentinel (``HALT_r5c``) that stops the
watcher when retrying cannot heal the condition (magic-round MISMATCH).
This module is those three ideas as one importable, unit-tested class;
``scripts/run_supervised.py`` is the CLI.

Each attempt's stdout/stderr land in ``<state_dir>/<leg>.out|.err``; a
JSON status ledger (``status.json``) is atomically rewritten after every
attempt, so an operator (or the next session) can see exactly where a
run died without scraping logs.
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import re
import subprocess
import time
from pathlib import Path

from parallel_convolution_tpu.obs import events as obs_events, metrics as obs_metrics
from parallel_convolution_tpu.resilience.retry import RetryPolicy

HALT_NAME = "HALT"
LEDGER_NAME = "status.json"

# status.json schema (round 11): 2 adds `schema_version` itself plus a
# `heartbeat`/`heartbeat_unix` pair refreshed between leg polls — an
# external watcher can now tell "running" (heartbeat advancing) from
# "hung" (stale heartbeat, no state change).  Readers must tolerate
# version-1 ledgers without the fields (:func:`read_ledger`).
LEDGER_SCHEMA = 2


def read_ledger(path) -> dict:
    """Parse a supervisor ledger, filling pre-round-11 defaults.

    Old ledgers (no ``schema_version``) read as version 1 with the
    heartbeat falling back to ``updated`` (the best liveness signal they
    carried).  Raises on missing/unparseable files — a watcher must see
    the difference between "no ledger yet" and "ledger says X".
    """
    data = json.loads(Path(path).read_text())
    if not isinstance(data, dict):
        raise ValueError(f"ledger {path} is not a JSON object")
    data.setdefault("schema_version", 1)
    data.setdefault("heartbeat", data.get("updated"))
    data.setdefault("heartbeat_unix", None)
    data.setdefault("legs", {})
    data.setdefault("halt", None)
    return data


@dataclasses.dataclass
class Leg:
    """One unit of work with an artifact-based completion predicate.

    ``done_file`` + optional ``done_pattern`` (regex) define completion —
    the queue is idempotent, like the ``[ -e ]`` guards in the old shell:
    a re-run skips landed legs.  With no ``done_file``, completion is
    simply a zero exit.  ``terminal_pattern`` (regex, searched in the
    attempt's combined stdout+stderr) marks failures retrying cannot heal
    — the supervisor drops the sentinel and stops the whole queue.
    """

    name: str
    cmd: list[str]
    done_file: str | None = None
    done_pattern: str | None = None
    terminal_pattern: str | None = None
    timeout: float | None = None
    env: dict | None = None
    # Reshape-aware legs (elastic recovery): ``meshes`` is the mesh-spec
    # ladder to walk (e.g. ["2x4", "2x2", "1x2", "1x1"]), ``mesh_env``
    # the env var the current rung is exported through (default
    # elastic.MESH_ENV), and ``reshape_pattern`` the regex that marks an
    # attempt as "a device died" — matching output advances the ladder
    # (to the first rung that fits the probed live-device count) instead
    # of retrying the same doomed grid.
    meshes: list | None = None
    mesh_env: str | None = None
    reshape_pattern: str | None = None

    @classmethod
    def from_dict(cls, d: dict) -> "Leg":
        unknown = set(d) - {f.name for f in dataclasses.fields(cls)}
        if unknown:
            raise ValueError(f"unknown leg field(s) {sorted(unknown)}")
        leg = cls(**d)
        if not leg.name or not leg.cmd:
            raise ValueError("leg needs a name and a non-empty cmd")
        if leg.reshape_pattern and not leg.meshes:
            raise ValueError(
                f"leg {leg.name!r}: reshape_pattern needs a meshes ladder")
        if leg.meshes:
            from parallel_convolution_tpu.resilience import elastic

            for spec in leg.meshes:
                elastic.parse_spec(str(spec))  # loud on a typo'd rung
        return leg

    def is_complete(self) -> bool:
        if self.done_file is None:
            return False  # rc==0 of an attempt is the only signal
        p = Path(self.done_file)
        if not p.exists():
            return False
        if self.done_pattern is None:
            return True
        try:
            return re.search(self.done_pattern, p.read_text()) is not None
        except OSError:
            return False


class Supervisor:
    """Run a :class:`Leg` queue with classified retry + terminal sentinel."""

    def __init__(self, legs: list[Leg], state_dir, *,
                 policy: RetryPolicy | None = None, sleep=time.sleep,
                 log=None, heartbeat_every: float = 5.0):
        self.legs = list(legs)
        names = [leg.name for leg in self.legs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate leg names in {names}")
        self.state_dir = Path(state_dir)
        self.policy = policy or RetryPolicy(max_attempts=5, base_delay=2.0,
                                            max_delay=240.0)
        self._sleep = sleep
        self._log = log or (lambda msg: print(msg, flush=True))
        # How often the attempt loop re-stamps the ledger heartbeat while
        # a leg subprocess runs (the running-vs-hung watcher signal).
        self.heartbeat_every = max(0.1, float(heartbeat_every))
        self._status: dict = {"schema_version": LEDGER_SCHEMA,
                              "legs": {}, "halt": None}

    # -- ledger ------------------------------------------------------------
    @property
    def halt_path(self) -> Path:
        return self.state_dir / HALT_NAME

    @property
    def ledger_path(self) -> Path:
        return self.state_dir / LEDGER_NAME

    def _flush_ledger(self) -> None:
        tmp = self.ledger_path.with_suffix(".json.tmp")
        tmp.write_text(json.dumps(self._status, indent=2))
        os.replace(tmp, self.ledger_path)

    def _stamp_heartbeat(self) -> None:
        self._status["heartbeat"] = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                                  time.gmtime())
        self._status["heartbeat_unix"] = round(time.time(), 3)

    def _write_ledger(self) -> None:
        self._status["updated"] = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                                time.gmtime())
        self._stamp_heartbeat()
        self._flush_ledger()

    def _touch_heartbeat(self, leg_name: str = "") -> None:
        """Refresh ONLY the liveness pair mid-attempt: ``updated`` keeps
        meaning 'last state change', heartbeat means 'supervisor alive'.

        Best-effort by contract: this runs INSIDE the attempt poll loop
        while a leg subprocess is alive, so an I/O failure here (ENOSPC,
        state dir pruned) must never bubble into the attempt handling —
        it would misclassify a running leg and leak/duplicate the child."""
        self._stamp_heartbeat()
        try:
            self._flush_ledger()
            if obs_metrics.enabled():
                obs_events.emit("heartbeat", leg=leg_name,
                                unix=self._status["heartbeat_unix"])
        except OSError:
            pass

    def _leg_status(self, leg: Leg) -> dict:
        return self._status["legs"].setdefault(
            leg.name, {"state": "pending", "attempts": 0})

    # -- execution ---------------------------------------------------------
    def _attempt(self, leg: Leg,
                 extra_env: dict | None = None) -> tuple[int | None, str]:
        """One subprocess attempt; returns (rc or None on timeout, text).

        The wait is sliced into ``heartbeat_every`` polls, the ledger
        heartbeat re-stamped between polls — a watcher reading
        ``status.json`` can distinguish a long-running leg (heartbeat
        advancing) from a hung supervisor (heartbeat frozen)."""
        out = self.state_dir / f"{leg.name}.out"
        err = self.state_dir / f"{leg.name}.err"
        env = dict(os.environ)
        if leg.env:
            env.update({k: str(v) for k, v in leg.env.items()})
        if extra_env:
            env.update({k: str(v) for k, v in extra_env.items()})
        p = None
        try:
            with open(out, "wb") as fo, open(err, "wb") as fe:
                p = subprocess.Popen(leg.cmd, stdout=fo, stderr=fe, env=env)
                deadline = (time.monotonic() + leg.timeout
                            if leg.timeout is not None else None)
                while True:
                    slice_s = self.heartbeat_every
                    if deadline is not None:
                        slice_s = min(slice_s,
                                      max(0.0, deadline - time.monotonic()))
                    try:
                        rc = p.wait(timeout=slice_s)
                        break
                    except subprocess.TimeoutExpired:
                        if (deadline is not None
                                and time.monotonic() >= deadline):
                            p.kill()
                            p.wait()
                            rc = None
                            break
                        # Best-effort (swallows its own I/O errors): a
                        # failing heartbeat must not reach the handler
                        # below while the child is alive.
                        self._touch_heartbeat(leg.name)
        except OSError as e:  # unrunnable cmd / capture-file failure
            if p is not None and p.poll() is None:
                # Never leak a live child into a "failed" attempt — the
                # retry would double-execute the leg against the same
                # checkpoint/evidence files.
                p.kill()
                p.wait()
            try:
                err.write_bytes(repr(e).encode())
            except OSError:
                pass
            rc = -1
        text = ""
        for p_ in (out, err):
            try:
                text += p_.read_text(errors="replace")
            except OSError:
                pass
        return rc, text

    def _next_mesh_idx(self, leg: Leg, idx: int) -> int:
        """The ladder rung after ``idx`` that fits current device health
        (elastic.next_fit).  The probe runs in a child process and is
        best-effort: any failure means "health unknown" — step one rung."""
        from parallel_convolution_tpu.resilience import elastic

        live = None
        try:
            from parallel_convolution_tpu.utils.platform import (
                probe_device_count,
            )

            live = probe_device_count(timeout=30.0)
        except Exception:  # noqa: BLE001 — a broken probe must not halt
            live = None
        return elastic.next_fit([str(s) for s in leg.meshes], idx + 1, live)

    def _leg_event(self, leg: Leg, state: str, **fields) -> None:
        if obs_metrics.enabled():
            obs_metrics.counter(
                "pctpu_supervisor_legs_total",
                "supervisor leg state transitions",
                ("state",)).inc(state=state)
            obs_events.emit("leg", leg=leg.name, state=state, **fields)

    def _halt(self, leg: Leg, reason: str) -> None:
        self._leg_event(leg, "terminal", reason=reason)
        self._status["halt"] = {"leg": leg.name, "reason": reason}
        self.halt_path.write_text(
            f"leg {leg.name}: {reason}\n"
            "Terminal failure: retrying cannot heal it. Remove this file "
            "only after fixing the cause.\n")
        self._write_ledger()
        self._log(f"supervisor: TERMINAL failure in leg {leg.name!r}: "
                  f"{reason} — sentinel at {self.halt_path}")

    def run(self) -> int:
        """Run the queue.  0 = all legs complete; 1 = some leg exhausted
        its retries (queue continued past it); 2 = terminal halt."""
        self.state_dir.mkdir(parents=True, exist_ok=True)
        if self.halt_path.exists():
            self._log(f"supervisor: refusing to run — sentinel present at "
                      f"{self.halt_path}")
            return 2
        exhausted = False
        for leg in self.legs:
            st = self._leg_status(leg)
            if leg.is_complete():
                st["state"] = "done"
                st.setdefault("completed_at", time.strftime(
                    "%Y-%m-%dT%H:%M:%SZ", time.gmtime()))
                self._write_ledger()
                self._log(f"supervisor: leg {leg.name!r} already complete")
                continue
            done = False
            # One RNG drawn exactly like RetryPolicy.delays()/with_retry:
            # the same policy must produce the same schedule everywhere.
            rng = random.Random(self.policy.seed)
            mesh_idx = 0
            for attempt in range(1, self.policy.max_attempts + 1):
                st["state"] = "running"
                st["attempts"] = attempt
                extra_env = None
                if leg.meshes:
                    from parallel_convolution_tpu.resilience import elastic

                    spec = str(leg.meshes[min(mesh_idx,
                                              len(leg.meshes) - 1)])
                    extra_env = {leg.mesh_env or elastic.MESH_ENV: spec}
                    st["mesh"] = spec
                self._write_ledger()
                rc, text = self._attempt(leg, extra_env)
                st["last_rc"] = rc
                if leg.terminal_pattern and re.search(leg.terminal_pattern,
                                                      text):
                    st["state"] = "terminal"
                    self._halt(leg, f"output matched terminal pattern "
                                    f"{leg.terminal_pattern!r}")
                    return 2
                complete = (leg.is_complete() if leg.done_file is not None
                            else rc == 0)
                if complete:
                    st["state"] = "done"
                    st["completed_at"] = time.strftime(
                        "%Y-%m-%dT%H:%M:%SZ", time.gmtime())
                    self._write_ledger()
                    self._leg_event(leg, "done", attempt=attempt)
                    self._log(f"supervisor: leg {leg.name!r} complete "
                              f"(attempt {attempt})")
                    done = True
                    break
                st["last_error"] = ("timeout" if rc is None
                                    else f"rc={rc}, incomplete")
                if (leg.reshape_pattern and leg.meshes
                        and mesh_idx < len(leg.meshes) - 1
                        and re.search(leg.reshape_pattern, text)):
                    # Device-loss signature: retrying the same grid is
                    # doomed — advance the ladder to the first rung that
                    # fits the probed live-device count (health-unknown
                    # probes just step down one rung).
                    mesh_idx = self._next_mesh_idx(leg, mesh_idx)
                    st["reshapes"] = st.get("reshapes", 0) + 1
                    self._leg_event(leg, "reshape",
                                    mesh=str(leg.meshes[mesh_idx]),
                                    attempt=attempt)
                    self._log(
                        f"supervisor: leg {leg.name!r} hit device-loss "
                        f"pattern; reshaping onto "
                        f"{leg.meshes[mesh_idx]}")
                self._write_ledger()
                if attempt < self.policy.max_attempts:
                    d = self.policy.delay(attempt, rng)
                    self._log(f"supervisor: leg {leg.name!r} attempt "
                              f"{attempt} failed ({st['last_error']}); "
                              f"retrying in {d:.1f}s")
                    self._sleep(d)
            if not done:
                st["state"] = "exhausted"
                self._write_ledger()
                self._leg_event(leg, "exhausted",
                                attempts=self.policy.max_attempts)
                self._log(f"supervisor: leg {leg.name!r} exhausted "
                          f"{self.policy.max_attempts} attempts; continuing")
                exhausted = True
        return 1 if exhausted else 0


def legs_from_json(text: str) -> list[Leg]:
    """Parse a JSON list of leg dicts (the ``--legs`` file format)."""
    data = json.loads(text)
    if not isinstance(data, list):
        raise ValueError("legs file must be a JSON list of leg objects")
    return [Leg.from_dict(d) for d in data]
