"""Graceful backend degradation: probe once, walk the fallback chain.

Round-5's chip story had two expensive versions of this done by hand: a
tiled-RDMA kernel whose compile crashed the chipless helper (a transient,
healed by retry/fallback), and two driver rounds whose headline row was a
silent CPU fallback nobody noticed until the evidence audit.  The policy
here makes both impossible to repeat silently:

* Each (mesh, backend, config) is probed ONCE per process — a tiny
  sharded end-to-end compile + run — and the verdict (or the exception)
  is cached, the same pattern the magic-round byte-guard established
  (``pallas_stencil._compiled_magic_ok``).
* On a classified-**transient** probe failure the chain walks
  ``pallas_rdma → pallas → shifted`` (separable tiers rejoin at
  ``pallas``), emitting a structured :class:`BackendDegradedWarning`.
* **Terminal** failures (config/shape/contract errors) raise immediately:
  degradation must never paper over a programming error.
* The resolved name is returned to the caller, and ``utils.bench`` stamps
  it into every row as ``effective_backend`` — a fallback can no longer
  masquerade as the requested tier in published numbers.
"""

from __future__ import annotations

import warnings

from parallel_convolution_tpu.obs import events as obs_events, metrics as obs_metrics
from parallel_convolution_tpu.ops.filters import Filter
from parallel_convolution_tpu.resilience.retry import (
    TERMINAL, RetryExhausted, classify,
)

__all__ = [
    "BackendDegradedWarning", "clear_probe_cache", "degradation_chain",
    "probe_backend", "resolve_backend",
]


class BackendDegradedWarning(UserWarning):
    """A requested backend failed transiently and a lower tier was used."""


# Next tier down for each backend.  The separable tiers rejoin at the
# plain 2D Pallas kernel rather than each other: pallas_sep's rank-1
# rounding order is only byte-identical for dyadic filters in quantize
# mode, so degrading INTO it could change bytes — degrading out of any
# Pallas tier to 'shifted' (the normative XLA path) never can.
_FALLBACK_NEXT = {
    "pallas_rdma": "pallas",
    "pallas_sep": "pallas",
    "pallas": "shifted",
    "xla_conv": "shifted",
    "separable": "shifted",
}


def degradation_chain(backend: str) -> tuple[str, ...]:
    """The orderly walk from ``backend`` down to the normative path."""
    chain = [backend]
    while chain[-1] in _FALLBACK_NEXT:
        chain.append(_FALLBACK_NEXT[chain[-1]])
    return tuple(chain)


# (mesh, filter, backend, config) -> None on success, or the exception the
# probe raised.  Caching the FAILURE too keeps the walk deterministic
# within a process: a flaky compile that failed once stays failed until
# the process (or the cache) is reset, mirroring how the magic-round
# guard latches its verdict.
_PROBE_CACHE: dict[tuple, BaseException | None] = {}


def clear_probe_cache() -> None:
    _PROBE_CACHE.clear()
    _LAST_RESOLVED.clear()


def _probe_key(mesh, filt: Filter, backend: str, quantize, fuse, boundary,
               tile, interior_split, storage, block_hw,
               overlap=False, col_mode="packed") -> tuple:
    return (mesh, filt.name, filt.radius, backend, bool(quantize), int(fuse),
            boundary, tile, bool(interior_split), storage, block_hw,
            bool(overlap), str(col_mode))


def probe_backend(mesh, filt: Filter, backend: str, *, quantize: bool = True,
                  fuse: int = 1, boundary: str = "zero",
                  tile: tuple[int, int] | None = None,
                  interior_split: bool = False,
                  storage: str = "f32",
                  block_hw: tuple[int, int] | None = None,
                  overlap: bool = False,
                  col_mode: str = "packed") -> None:
    """Compile + run one ``fuse``-iteration sharded chunk of ``backend``.

    Raises whatever the compile/launch raised (replayed from cache on
    repeat calls); returns None on (possibly cached) success.

    ``block_hw`` is the REAL run's per-device block: kernel selection
    depends on it (e.g. ``pallas_rdma`` auto-switches to the tiled HBM
    kernel — the round-5 silicon compile-crash class — only past its VMEM
    bound), so the probe must compile the same kernel family and storage
    dtype the real run will, not a miniature.  Callers inside the library
    always pass it; ``None`` falls back to the fused slab floor
    (``max(8, radius*fuse)`` per side) for standalone use.  Cost: one
    compile + ``fuse`` iterations on a zeros block, once per (mesh,
    backend, config) per process.
    """
    key = _probe_key(mesh, filt, backend, quantize, fuse, boundary, tile,
                     interior_split, storage, block_hw, overlap, col_mode)
    if key in _PROBE_CACHE:
        err = _PROBE_CACHE[key]
        if err is not None:
            raise err
        return
    try:
        _run_probe(mesh, filt, backend, quantize, fuse, boundary, tile,
                   interior_split, storage, block_hw, overlap, col_mode)
    except Exception as e:  # noqa: BLE001 — the verdict IS the product
        _PROBE_CACHE[key] = e
        raise
    _PROBE_CACHE[key] = None


def _run_probe(mesh, filt, backend, quantize, fuse, boundary, tile,
               interior_split, storage, block_hw, overlap=False,
               col_mode="packed") -> None:
    import jax
    import numpy as np

    from parallel_convolution_tpu.parallel import step as step_lib
    from parallel_convolution_tpu.parallel.mesh import grid_shape

    grid = grid_shape(mesh)
    fuse = max(1, int(fuse))
    if block_hw is None:
        b = max(8, filt.radius * fuse)
        block_hw = (b, b)
    x = np.zeros((1, grid[0] * block_hw[0], grid[1] * block_hw[1]),
                 np.float32)
    xs, valid_hw, block_hw = step_lib._prepare(x, mesh, filt.radius, storage)
    fn = step_lib._build_iterate(mesh, filt, fuse, quantize, valid_hw,
                                 block_hw, backend, fuse, boundary, tile,
                                 interior_split, overlap,
                                 step_lib.clamp_col_mode(col_mode, backend))
    jax.block_until_ready(fn(xs))


# requested-backend -> effective-backend of the most recent resolution in
# this process; lets entry points (CLI checkpoint branch) label their
# output without re-deriving the probe key.
_LAST_RESOLVED: dict[str, str] = {}


def effective_for(requested: str) -> str | None:
    """The effective backend of this process's last resolution of
    ``requested`` (None if it was never resolved)."""
    return _LAST_RESOLVED.get(requested)


def resolve_backend(mesh, filt: Filter, backend: str, *, quantize: bool = True,
                    fuse: int = 1, boundary: str = "zero",
                    tile: tuple[int, int] | None = None,
                    interior_split: bool = False, storage: str = "f32",
                    block_hw: tuple[int, int] | None = None,
                    overlap: bool = False,
                    col_mode: str = "packed",
                    warn: bool = True) -> str:
    """Return the first backend in ``degradation_chain(backend)`` whose
    probe passes; raise immediately on a terminal probe failure.

    Emits :class:`BackendDegradedWarning` when the result differs from the
    request — callers (``utils.bench``, ``ConvolutionModel``) additionally
    stamp the returned name into their rows/attributes so the degradation
    is visible in artifacts, not only on stderr.

    ``overlap`` is clamped per walked tier (only the RDMA kernels have
    an overlapped form), so each probe compiles exactly the program the
    real launch would use on that tier — including the case where the
    OVERLAPPED RDMA program fails transiently and the walk lands on a
    serialized lower tier.
    """
    chain = degradation_chain(backend)
    last: BaseException | None = None
    for b in chain:
        try:
            probe_backend(mesh, filt, b, quantize=quantize, fuse=fuse,
                          boundary=boundary, tile=tile,
                          interior_split=interior_split, storage=storage,
                          block_hw=block_hw,
                          overlap=bool(overlap) and b == "pallas_rdma",
                          col_mode=col_mode)
        except Exception as e:  # noqa: BLE001
            if classify(e) == TERMINAL:
                raise
            last = e
            continue
        if b != backend:
            if warn:
                warnings.warn(
                    f"backend {backend!r} degraded to {b!r} after transient "
                    f"failure: {last!r}",
                    BackendDegradedWarning, stacklevel=2,
                )
            if obs_metrics.enabled():
                obs_metrics.counter(
                    "pctpu_degrades_total",
                    "backend degradation walks that resolved a lower tier",
                    ("requested", "effective")).inc(
                    requested=backend, effective=b)
                obs_events.emit("degrade", requested=backend, effective=b,
                                cause=repr(last)[:200])
        _LAST_RESOLVED[backend] = b
        return b
    raise RetryExhausted(
        f"every backend in {chain} failed transiently; last: {last!r}"
    ) from last
