"""Per-replica circuit breaker: passive health from typed failures.

The replica router's ACTIVE health signal is the ``/readyz`` poll; this
is the PASSIVE one — the router observes every dispatch outcome anyway,
so consecutive failures against one replica should stop traffic to it
*between* polls (a poll interval is an eternity at request rate).

State walk (the classic three states, deterministic and clock-injectable
so tests drive it without sleeping):

* **closed** — healthy; every request allowed.  ``threshold``
  consecutive failures (successes reset the count) trip it to open.
* **open** — no requests for ``cooldown_s``; the router spills this
  replica's keys to the next ring replica.  After the cooldown the next
  ``allow()`` transitions to half-open and admits exactly ONE probe.
* **half_open** — one in-flight probe decides: success closes the
  breaker, failure re-opens it for another cooldown.  A probe that never
  reports (a wedged transport) stops blocking after ``cooldown_s`` —
  the breaker must degrade to polling, never deadlock the replica out
  of the ring forever.

Failure *classification* reuses :func:`resilience.retry.classify`: a
TERMINAL exception (ValueError-class contract bugs) is the *request's*
fault, not the replica's, and does not count against the breaker —
exactly the taxonomy split the retry layer already encodes.  Transport
errors (ConnectionError, timeouts, RPC loss) classify transient and do
count: those are the replica-down signals.

stdlib-only, jax-free.
"""

from __future__ import annotations

import threading
import time

from parallel_convolution_tpu.resilience.retry import TERMINAL, classify

__all__ = ["CLOSED", "HALF_OPEN", "OPEN", "CircuitBreaker"]

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class CircuitBreaker:
    """Consecutive-failure breaker with half-open probing.

    ``allow()`` is the gate the router consults immediately before a
    dispatch it is otherwise committed to (calling it consumes the
    half-open probe slot, so don't use it as a passive peek — that's
    :meth:`state`); ``record_success``/``record_failure`` report the
    dispatch outcome.
    """

    def __init__(self, threshold: int = 3, cooldown_s: float = 2.0,
                 clock=time.monotonic):
        if threshold < 1 or cooldown_s < 0:
            raise ValueError("threshold >= 1 and cooldown_s >= 0 required")
        self.threshold = int(threshold)
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0           # consecutive, reset on success
        self._opened_at = 0.0
        self._probe_at: float | None = None  # half-open probe launch time
        self.stats = {"opened": 0, "closed": 0, "probes": 0}

    # -- the gate ------------------------------------------------------------
    def allow(self) -> bool:
        """May the router dispatch to this replica right now?

        In OPEN past the cooldown this transitions to HALF_OPEN and
        grants the single probe slot; in HALF_OPEN the slot re-arms only
        after ``cooldown_s`` without a verdict (wedged-probe guard).
        """
        with self._lock:
            if self._state == CLOSED:
                return True
            now = self._clock()
            if self._state == OPEN:
                if now - self._opened_at < self.cooldown_s:
                    return False
                self._state = HALF_OPEN
                self._probe_at = now
                self.stats["probes"] += 1
                return True
            # HALF_OPEN: one probe at a time, re-armed if it went silent.
            if (self._probe_at is not None
                    and now - self._probe_at < self.cooldown_s):
                return False
            self._probe_at = now
            self.stats["probes"] += 1
            return True

    # -- outcome reports -----------------------------------------------------
    def record_success(self) -> None:
        with self._lock:
            if self._state != CLOSED:
                self.stats["closed"] += 1
            self._state = CLOSED
            self._failures = 0
            self._probe_at = None

    def record_failure(self, exc: BaseException | None = None) -> None:
        """Count one dispatch failure.  A TERMINAL-classified exception
        (the request's own contract bug) never counts — the breaker
        watches replica health, not request validity."""
        if exc is not None and classify(exc) == TERMINAL:
            return
        with self._lock:
            self._failures += 1
            if self._state == HALF_OPEN or self._failures >= self.threshold:
                if self._state != OPEN:
                    # Straggler failures reported while already OPEN
                    # (in-flight requests draining after the kill) must
                    # NOT restart the cooldown — the half-open probe is
                    # due cooldown_s after the TRANSITION, not after the
                    # last straggler.
                    self.stats["opened"] += 1
                    self._opened_at = self._clock()
                self._state = OPEN
                self._probe_at = None

    # -- introspection -------------------------------------------------------
    def state(self) -> str:
        """The current state WITHOUT consuming a probe slot (open
        breakers past their cooldown still report ``open`` here — the
        transition happens in :meth:`allow`)."""
        with self._lock:
            return self._state

    def snapshot(self) -> dict:
        with self._lock:
            return {"state": self._state, "failures": self._failures,
                    **self.stats}
