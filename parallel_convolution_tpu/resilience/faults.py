"""Seeded, process-global fault plan with named injection sites.

Library code consults :func:`fault_point` at the places our operational
history shows failures actually occur (the site registry below maps 1:1
onto observed outage modes).  With no plan installed the hook is a single
global load + ``is None`` test — zero overhead on every hot path, safe to
leave in production code.  With a plan installed, each consult increments
the site's hit counter and raises :class:`InjectedFault` when the plan's
rule for that site triggers — deterministically (hit-indexed rules) or
pseudo-randomly from the plan seed (probability rules), so every failure
a test injects is replayable bit-for-bit.

Spec grammar (env ``PCTPU_FAULTS``, seed ``PCTPU_FAULT_SEED``)::

    site:TRIGGER[!][,site:TRIGGER[!]...]

    TRIGGER :=  N      fail exactly the N-th hit (1-based)
             |  N+     fail every hit from the N-th on
             |  *      fail every hit
             |  pX     fail each hit with probability X (plan-seeded)
    !        := classify the fault terminal instead of transient

Examples::

    checkpoint_write_shard:2        # tear the snapshot at the 2nd shard
    backend_compile:1               # first compile dies (tunnel blip)
    halo_exchange:p0.1,io_read:3+   # flaky fabric + dead file handle
    backend_compile:1!              # a compile failure retry can't heal

This module is deliberately jax-free and import-light: hooks live in
modules (``utils.platform``) that must stay cheap to import.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import random
import threading

# One name per observed outage mode; specs naming anything else are
# rejected up front so a typo'd site can't silently never fire.  THIS
# TABLE is the documented site registry (previously DESIGN.md prose
# only — drift-guarded by tests/test_chaos.py, which greps the tree for
# every ``fault_point(name)`` consult and pins it against these keys):
# compute/IO sites first (rounds 7+), then the transport sites the
# round-18 chaos layer injects through (serving.chaos.ChaosTransport
# consults them around every router→replica hop).
SITE_TABLE = {
    "backend_compile":        "tracing/compiling an iteration runner",
    "halo_exchange":          "building the exchange (ppermute or RDMA)",
    "checkpoint_write_shard": "before each per-shard .npy write",
    "checkpoint_write_meta":  "before meta.json, and before the LATEST flip",
    "device_probe":           "backend liveness probe (the tunnel check)",
    "io_read":                "sharded block read from disk",
    "transport_send":         "router→replica request leaving the client "
                              "(drop / latency / black-hole: the work "
                              "never reaches the replica)",
    "transport_recv":         "replica→router response on the way back "
                              "(drop / corrupt: the work EXECUTED but the "
                              "response is lost or unparseable — the "
                              "idempotency-ledger case)",
    "transport_stream":       "one progressive NDJSON row in flight "
                              "(mid-stream disconnect after best-so-far "
                              "rows already landed — the resume case)",
    "readyz_probe":           "active-health /readyz poll (flapping "
                              "readiness: the router's routing input lies)",
    "wal_write":              "before appending one record to the router "
                              "WAL (serving/wal.py — a failed append "
                              "degrades durability loudly, never serving)",
    "wal_fsync":              "before fsyncing the WAL after an append "
                              "(the record is written but not yet durable "
                              "when this fires)",
    "router_kill":            "drill poll: the moment the router process "
                              "dies (drills consult it per streamed row "
                              "via serving.chaos.router_kill_due and "
                              "convert the verdict into an abandoned "
                              "stream + a WAL takeover)",
    "cache_spill":            "before spilling one result-cache entry to "
                              "its disk tier (serving/cache.py — a failed "
                              "spill demotes the disk tier, never serves "
                              "bad bytes)",
    "cache_promote":          "before reading one disk-tier cache entry "
                              "back on a hit (a failed promote is a loud "
                              "journaled miss, never a stale serve)",
    "events_emit":            "before writing one obs event line "
                              "(obs/events.py — a failed write counts a "
                              "dropped line instead of raising into the "
                              "serving path)",
    "evidence_write":         "before writing/replacing an evidence file "
                              "(utils/evidence_io.py — smoke legs surface "
                              "the failure typed instead of tearing a "
                              "shared curve)",
}
KNOWN_SITES = frozenset(SITE_TABLE)


class InjectedFault(RuntimeError):
    """A deliberately injected failure; carries its retry classification."""

    def __init__(self, site: str, hit: int, transient: bool = True):
        super().__init__(
            f"injected fault at {site!r} (hit {hit}, "
            f"{'transient' if transient else 'terminal'})"
        )
        self.site = site
        self.hit = hit
        self.transient = transient


@dataclasses.dataclass(frozen=True)
class _Rule:
    """One site's trigger: hit index / open range / every / probability."""

    at: int | None = None      # fail exactly this 1-based hit
    from_: int | None = None   # fail this hit and every later one
    every: bool = False        # fail all hits
    prob: float | None = None  # fail each hit with this probability
    terminal: bool = False

    def fires(self, hit: int, rng: random.Random) -> bool:
        if self.every:
            return True
        if self.at is not None:
            return hit == self.at
        if self.from_ is not None:
            return hit >= self.from_
        return rng.random() < (self.prob or 0.0)


class FaultPlan:
    """Immutable rules + mutable per-site hit counters (thread-safe)."""

    def __init__(self, rules: dict[str, _Rule], seed: int = 0):
        unknown = set(rules) - KNOWN_SITES
        if unknown:
            raise ValueError(
                f"unknown fault site(s) {sorted(unknown)}; "
                f"known: {sorted(KNOWN_SITES)}"
            )
        self.rules = dict(rules)
        self.seed = int(seed)
        self._rng = random.Random(self.seed)
        self._hits: dict[str, int] = {}
        self._fired: list[tuple[str, int]] = []
        self._lock = threading.Lock()

    def check(self, site: str) -> None:
        rule = self.rules.get(site)
        if rule is None:
            return  # un-spec'd sites are not even counted: keeps plans O(spec)
        with self._lock:
            hit = self._hits.get(site, 0) + 1
            self._hits[site] = hit
            fire = rule.fires(hit, self._rng)
            if fire:
                self._fired.append((site, hit))
        if fire:
            # Telemetry before the raise: the timeline must show the
            # trigger even when the fault kills the workload.  obs is
            # stdlib-only and import-light, preserving this module's
            # cheap-to-import contract.
            from parallel_convolution_tpu.obs import events, metrics

            if metrics.enabled():
                metrics.counter(
                    "pctpu_faults_fired_total",
                    "injected faults that actually raised, per site",
                    ("site",)).inc(site=site)
                events.emit("fault_trigger", site=site, hit=hit,
                            transient=not rule.terminal)
            raise InjectedFault(site, hit, transient=not rule.terminal)

    @property
    def fired(self) -> list[tuple[str, int]]:
        """(site, hit) pairs that actually raised, in order — for asserts."""
        with self._lock:
            return list(self._fired)

    def hits(self, site: str) -> int:
        with self._lock:
            return self._hits.get(site, 0)


def _parse_rule(text: str) -> _Rule:
    terminal = text.endswith("!")
    body = text[:-1] if terminal else text
    if body == "*":
        return _Rule(every=True, terminal=terminal)
    if body.startswith("p"):
        p = float(body[1:])
        if not 0.0 <= p <= 1.0:
            raise ValueError(f"fault probability must be in [0,1], got {p}")
        return _Rule(prob=p, terminal=terminal)
    if body.endswith("+"):
        n = int(body[:-1])
        if n < 1:
            raise ValueError(f"hit index must be >= 1, got {n}")
        return _Rule(from_=n, terminal=terminal)
    n = int(body)
    if n < 1:
        raise ValueError(f"hit index must be >= 1, got {n}")
    return _Rule(at=n, terminal=terminal)


def plan_from_spec(spec: str, seed: int = 0) -> FaultPlan:
    """Parse the ``site:TRIGGER,...`` grammar (see module docstring)."""
    rules: dict[str, _Rule] = {}
    for part in filter(None, (p.strip() for p in spec.split(","))):
        if ":" not in part:
            raise ValueError(
                f"bad fault spec {part!r}: expected site:TRIGGER"
            )
        site, trig = part.split(":", 1)
        try:
            rules[site.strip()] = _parse_rule(trig.strip())
        except ValueError as e:
            raise ValueError(f"bad fault spec {part!r}: {e}") from e
    if not rules:
        raise ValueError(f"empty fault spec {spec!r}")
    return FaultPlan(rules, seed=seed)


def plan_from_env(env: dict | None = None) -> FaultPlan | None:
    """Build a plan from ``PCTPU_FAULTS`` / ``PCTPU_FAULT_SEED`` (or None)."""
    env = os.environ if env is None else env
    spec = env.get("PCTPU_FAULTS", "").strip()
    if not spec:
        return None
    return plan_from_spec(spec, seed=int(env.get("PCTPU_FAULT_SEED", "0")))


# The process-global plan. fault_point() reads it without a lock: plans
# are installed before the workload starts, and a torn read can only see
# None or a fully constructed plan (CPython attribute store is atomic).
_PLAN: FaultPlan | None = None


def install_plan(plan: FaultPlan | str | None, seed: int = 0) -> FaultPlan | None:
    """Install ``plan`` (a FaultPlan or a spec string) globally; returns it."""
    global _PLAN
    if isinstance(plan, str):
        plan = plan_from_spec(plan, seed=seed)
    _PLAN = plan
    return plan


def uninstall_plan() -> None:
    global _PLAN
    _PLAN = None


def install_from_env(env: dict | None = None) -> FaultPlan | None:
    """Entry-point hook: honor ``PCTPU_FAULTS`` if set (else no-op)."""
    plan = plan_from_env(env)
    if plan is not None:
        install_plan(plan)
    return plan


@contextlib.contextmanager
def injected(plan: FaultPlan | str | None, seed: int = 0):
    """Scoped installation for tests; always restores the previous plan."""
    global _PLAN
    prev = _PLAN
    installed = install_plan(plan, seed=seed)
    try:
        yield installed
    finally:
        _PLAN = prev


def fault_point(site: str) -> None:
    """Consult the active fault plan at a named site.

    THE hot-path contract: with no plan installed this is one global load
    and an ``is None`` test — nothing is counted, allocated, or locked, so
    the hook is free to sit in compile paths and per-shard I/O loops.
    """
    plan = _PLAN
    if plan is not None:
        plan.check(site)
