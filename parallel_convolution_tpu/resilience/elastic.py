"""Elastic mesh recovery: survive a change in the device set.

The reference's ``MPI_Cart_create`` grid is a death pact — lose one rank
and the communicator, the decomposition, and every buffer keyed on it
are gone.  Round 7 could already heal onto a slower *backend*; this
module heals onto a smaller (or different) *grid*: detect that the
device set changed, propose a new mesh spec that fits what is alive,
and let every layer re-bind —

* checkpoints reshard onto the new grid (``utils.checkpoint``:
  grid-shape-agnostic ``load_state``),
* the supervisor walks a leg's mesh ladder on a device-loss signature
  (``resilience.supervisor``: ``mesh_env``/``meshes``/``reshape_pattern``),
* the serving engine drains, invalidates, and re-warms its executable
  cache mid-process (``serving.engine.WarmEngine.reshape``).

"Persistent and Partitioned MPI for Stencil Communication" (PAPERS.md)
shows halo pipelines re-binding to changed communicator layouts cheaply;
here the re-bind is a fresh ``shard_map`` compile for the new grid while
everything keyed on other meshes stays warm (``parallel.step``'s build
caches key on the mesh object).

jax-free and import-light: device probing happens in a child process
(``utils.platform.probe_device_count``), so the supervisor can consult
health without initializing a backend in its own process.
"""

from __future__ import annotations

import dataclasses

# The env var reshape-aware legs read their mesh spec from (the
# supervisor writes it per attempt; entry points parse it with
# ``mesh_from_spec``).  One name, so drills and legs cannot drift.
MESH_ENV = "PCTPU_MESH"


def parse_spec(spec: str) -> tuple[int, int]:
    """``"RxC"`` -> (R, C); the grammar of ``mesh.mesh_from_spec``."""
    try:
        r, c = (int(v) for v in spec.lower().split("x"))
    except ValueError as e:
        raise ValueError(f"mesh spec must be RxC, got {spec!r}") from e
    if r < 1 or c < 1:
        raise ValueError(f"mesh spec must be positive, got {spec!r}")
    return r, c


def format_spec(grid: tuple[int, int]) -> str:
    return f"{grid[0]}x{grid[1]}"


def grid_ladder(start: tuple[int, int]) -> list[str]:
    """The shrink ladder from ``start`` down to 1x1, halving the larger
    axis each step — e.g. (2, 4) -> ["2x4", "2x2", "2x1", "1x1"].

    Each rung needs at most half the previous rung's devices, so ANY
    shrink of the device set lands on some rung; the ladder is what
    reshape-aware supervisor legs and the soak drill walk.
    """
    out = [format_spec(start)]
    r, c = start
    while (r, c) != (1, 1):
        if c >= r:
            c = max(1, c // 2)
        else:
            r = max(1, r // 2)
        out.append(format_spec((r, c)))
    return out


def next_fit(specs: list[str], start: int, live: int | None) -> int:
    """The index of the next spec in ``specs[start:]`` that fits ``live``
    devices (first one when ``live`` is None — health unknown, just step
    down one rung).  Falls back to the last (smallest) spec when nothing
    fits; clamps into range so callers can pass ``idx + 1`` blindly.
    """
    if not specs:
        return 0
    start = min(max(0, start), len(specs) - 1)
    if live is None:
        return start
    for i in range(start, len(specs)):
        r, c = parse_spec(specs[i])
        if r * c <= max(1, live):
            return i
    return len(specs) - 1


@dataclasses.dataclass(frozen=True)
class MeshChange:
    """A detected change in the usable device set."""

    old_grid: tuple[int, int]
    live: int                      # devices the probe can see now
    new_spec: str | None           # proposed RxC that fits, None = none fits

    @property
    def lost(self) -> int:
        return self.old_grid[0] * self.old_grid[1] - self.live


def detect_change(mesh, timeout: float = 60.0) -> MeshChange | None:
    """Probe device health; None when the mesh's devices all still fit.

    A shrink proposes the first rung of :func:`grid_ladder` that fits
    the live count (near-square is NOT forced: keeping the aspect of the
    original decomposition keeps block shapes — and any tuned plans for
    them — closer to the original run's).  A probe failure (None count)
    also returns None: "health unknown" must not trigger a reshape.
    """
    from parallel_convolution_tpu.parallel.mesh import grid_shape
    from parallel_convolution_tpu.utils.platform import probe_device_count

    grid = grid_shape(mesh)
    n = grid[0] * grid[1]
    live = probe_device_count(timeout=timeout)
    if live is None or live >= n:
        return None
    ladder = grid_ladder(grid)
    idx = next_fit(ladder, 1, live)
    spec = ladder[idx]
    r, c = parse_spec(spec)
    return MeshChange(old_grid=grid, live=live,
                      new_spec=spec if r * c <= live else None)


def reshape_mesh(spec_or_grid, devices=None):
    """Build the post-change mesh: ``"RxC"`` (or a grid tuple) over the
    first R*C live devices.  The elastic counterpart of
    ``mesh.mesh_from_spec`` that also accepts an explicit device list
    (e.g. the survivors after filtering a dead chip out)."""
    import jax

    from parallel_convolution_tpu.parallel.mesh import make_grid_mesh

    grid = (parse_spec(spec_or_grid) if isinstance(spec_or_grid, str)
            else (int(spec_or_grid[0]), int(spec_or_grid[1])))
    devices = list(devices) if devices is not None else jax.devices()
    n = grid[0] * grid[1]
    if len(devices) < n:
        raise ValueError(
            f"mesh {format_spec(grid)} needs {n} devices, "
            f"only {len(devices)} available")
    return make_grid_mesh(devices[:n], grid)
