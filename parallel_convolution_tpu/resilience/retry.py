"""Transient/terminal error taxonomy + retry with deterministic backoff.

This is the tested replacement for the retry/HALT-sentinel logic that
previously lived in two divergent shell scripts (``tunnel_watch.sh``'s
4-minute probe loop and ``chip_session_r5c.sh``'s per-leg keep-best /
MISMATCH-is-terminal handling).  The taxonomy encodes what four rounds of
operating the tunnel platform actually taught:

transient (retrying can heal it)
    tunnel/RPC loss (``jax.devices()`` hang, UNAVAILABLE, socket resets),
    OOM on a probe (RESOURCE_EXHAUSTED), Mosaic/XLA INTERNAL compile
    crashes (the round-5 tiled-RDMA helper crash recovered on retry),
    timeouts of any stripe.

terminal (retrying burns chip time forever — stop, leave a sentinel)
    magic-round byte MISMATCH (a compiler-behavior change), checkpoint
    config/grid mismatch, shape errors, and generally every
    ``ValueError``/``TypeError``-class programming or contract error.

Unknown exceptions default to **terminal**: an unbounded retry loop on a
condition nobody has classified is exactly the failure mode the round-5
scripts had to hand-patch (the watcher refiring a MISMATCH session every
4 minutes).  Add markers here as new transients are observed.

Backoff jitter is deterministic (seeded ``random.Random``), so a retry
schedule in a test or an incident report is replayable exactly.
"""

from __future__ import annotations

import dataclasses
import random
import time

from parallel_convolution_tpu.obs import events as obs_events, metrics as obs_metrics
from parallel_convolution_tpu.resilience.faults import InjectedFault

TRANSIENT = "transient"
TERMINAL = "terminal"

# Lower-cased substrings matched against "ExcType: message".  Terminal
# markers win over transient ones: "MISMATCH" inside an RPC error text is
# a detected compiler fold, not a tunnel blip.
TERMINAL_MARKERS = (
    # NOTE: keep these NARROW.  Shape/contract errors are already terminal
    # via their exception types (ValueError/TypeError below); a bare
    # "shape" substring here would misclassify transient Mosaic INTERNAL
    # crashes whose messages mention vector shapes.
    "mismatch",            # magic-round guard / byte-compare failures
    "config mismatch",
    "checkpoint grid",
    "requires quantize",
    "unknown backend",
)
TRANSIENT_MARKERS = (
    "unavailable",
    "deadline_exceeded",
    "deadline exceeded",
    "socket closed",
    "connection reset",
    "connection refused",
    "broken pipe",
    "tunnel",
    "resource_exhausted",
    "resource exhausted",
    "out of memory",
    "rpc",
    "internal:",           # XlaRuntimeError INTERNAL (Mosaic compile crash)
    "mosaic",
    "timed out",
    "timeout",
)

_TERMINAL_TYPES = (
    ValueError, TypeError, KeyError, IndexError, AttributeError,
    AssertionError, NotImplementedError, ZeroDivisionError,
)
_TRANSIENT_TYPES = (
    TimeoutError, ConnectionError, BrokenPipeError, InterruptedError,
)


def classify(exc: BaseException) -> str:
    """Map an exception to ``"transient"`` or ``"terminal"``.

    Order matters: injected faults carry their own classification;
    explicit exception types beat message scans; terminal markers beat
    transient ones; unknowns are terminal (see module docstring).
    """
    if isinstance(exc, InjectedFault):
        return TRANSIENT if exc.transient else TERMINAL
    if isinstance(exc, _TERMINAL_TYPES):
        return TERMINAL
    if isinstance(exc, _TRANSIENT_TYPES):
        return TRANSIENT
    msg = f"{type(exc).__name__}: {exc}".lower()
    if any(m in msg for m in TERMINAL_MARKERS):
        return TERMINAL
    if any(m in msg for m in TRANSIENT_MARKERS):
        return TRANSIENT
    return TERMINAL


class RetryExhausted(RuntimeError):
    """All attempts failed with transient errors; the last one is chained."""


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Capped exponential backoff with deterministic jitter.

    Attempt k (1-based) sleeps ``min(cap, base * mult**(k-1))`` scaled by
    a jitter factor drawn uniformly from [0.5, 1.0] — drawn from a
    ``Random(seed)`` private to each :func:`with_retry` call, so a given
    (policy, failure pattern) always produces the same schedule.
    """

    max_attempts: int = 5
    base_delay: float = 0.5
    max_delay: float = 60.0
    multiplier: float = 2.0
    seed: int = 0

    def delay(self, attempt: int, rng: random.Random) -> float:
        raw = min(self.max_delay,
                  self.base_delay * self.multiplier ** (attempt - 1))
        return raw * (0.5 + 0.5 * rng.random())

    def delays(self) -> list[float]:
        """The full schedule this policy would sleep (for tests/reports)."""
        rng = random.Random(self.seed)
        return [self.delay(k, rng) for k in range(1, self.max_attempts)]


def with_retry(fn, policy: RetryPolicy | None = None, *,
               classify=classify, sleep=time.sleep, on_retry=None):
    """Call ``fn()``; retry classified-transient failures per ``policy``.

    Terminal failures re-raise immediately and untouched (the caller's
    sentinel/halt logic sees the original exception).  When every attempt
    fails transiently, raises :class:`RetryExhausted` chained to the last
    error.  ``on_retry(attempt, exc, delay)`` observes each backoff;
    ``sleep`` is injectable so tests assert schedules without waiting.
    """
    policy = policy or RetryPolicy()
    rng = random.Random(policy.seed)
    last: BaseException | None = None
    for attempt in range(1, policy.max_attempts + 1):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — classification is the point
            if classify(e) == TERMINAL:
                raise
            last = e
            if attempt == policy.max_attempts:
                break
            d = policy.delay(attempt, rng)
            if obs_metrics.enabled():
                obs_metrics.counter(
                    "pctpu_retries_total",
                    "transient failures healed by with_retry backoff",
                    ("error",)).inc(error=type(e).__name__)
                obs_events.emit("retry", attempt=attempt,
                                error=repr(e)[:200], delay_s=round(d, 4))
            if on_retry is not None:
                on_retry(attempt, e, d)
            sleep(d)
    raise RetryExhausted(
        f"{policy.max_attempts} attempts failed transiently; last: {last!r}"
    ) from last
