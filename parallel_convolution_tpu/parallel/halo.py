"""Ghost-cell (halo) exchange via XLA collective-permute (component C5).

This is the heart of the port (SURVEY.md §2 C5): the reference posts eight
``MPI_Isend/Irecv`` pairs per iteration — N/S/E/W edges plus four corner
diagonals — into the ghost ring of a ``(rows+2)×(cols+2)`` padded block,
with ``MPI_Type_vector`` datatypes for strided columns.

The TPU equivalent is :func:`jax.lax.ppermute` (XLA ``collective-permute``
over ICI) applied in **two sequential phases**:

1. shift r-row edge slabs along mesh axis 'x' (top/bottom ghosts);
2. shift r-column edge slabs of the *already row-padded* block along 'y'.

Phase 2's column slabs include the freshly received row ghosts, so corner
ghost cells arrive after two hops — no diagonal messages, 4 permutes total
instead of the reference's 8 sends.  Strided-column datatypes have no
equivalent because XLA slices lay out transfers itself.

Boundary condition: a ``ppermute`` leaves devices with no inbound edge in
the permutation holding **zeros**, which is exactly the reference's zero
ghost ring at the image boundary — non-periodic borders come for free.

Everything here runs *inside* ``jax.shard_map`` over the ('x', 'y') mesh;
``block`` is one device's planar (C, h, w) float32 tile.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def _shift(x: jnp.ndarray, axis_name: str, n: int, down: bool,
           periodic: bool = False) -> jnp.ndarray:
    """ppermute ``x`` one step along ``axis_name`` (n devices on that axis).

    ``down=True`` sends toward higher indices (each device receives its
    lower-index neighbor's slab).  Non-periodic boundaries fall out of
    ppermute semantics — devices with no inbound edge receive zeros (the
    zero ghost ring).  ``periodic=True`` closes the ring (the wrap-around
    rotation of ring attention's KV pass, SURVEY.md §5 long-context row):
    every device has an inbound edge, modulo n.
    """
    if n == 1:
        if periodic:
            return x  # my own opposite edge wraps to me
        return jnp.zeros_like(x)
    if periodic:
        if down:
            perm = [(i, (i + 1) % n) for i in range(n)]
        else:
            perm = [(i, (i - 1) % n) for i in range(n)]
    elif down:
        perm = [(i, i + 1) for i in range(n - 1)]
    else:
        perm = [(i + 1, i) for i in range(n - 1)]
    return lax.ppermute(x, axis_name, perm)


def halo_pad_axis(
    block: jnp.ndarray, r: int, axis_name: str, n: int, dim: int,
    periodic: bool = False,
) -> jnp.ndarray:
    """Pad one spatial dim of ``block`` with r-wide halos from mesh neighbors."""
    lo_slice = [slice(None)] * block.ndim
    hi_slice = [slice(None)] * block.ndim
    lo_slice[dim] = slice(0, r)          # my first r rows/cols → upper neighbor
    hi_slice[dim] = slice(block.shape[dim] - r, block.shape[dim])
    # Ghosts I receive: lower neighbor's last r (becomes my leading ghost),
    # higher neighbor's first r (trailing ghost).
    lead_ghost = _shift(block[tuple(hi_slice)], axis_name, n, down=True,
                        periodic=periodic)
    trail_ghost = _shift(block[tuple(lo_slice)], axis_name, n, down=False,
                         periodic=periodic)
    return jnp.concatenate([lead_ghost, block, trail_ghost], axis=dim)


def halo_exchange(block: jnp.ndarray, r: int, grid: tuple[int, int],
                  boundary: str = "zero") -> jnp.ndarray:
    """Full two-phase halo pad of a planar (C, h, w) block → (C, h+2r, w+2r).

    Phase order (rows then columns of the row-padded slab) propagates corner
    ghosts correctly — SURVEY.md §8 item 5: outputs must match the
    reference's explicit 8-neighbor exchange bit-for-bit, and do, because
    corner values take the same two-hop path the diagonal message shortcuts.

    ``boundary``: 'zero' (the reference's ghost ring) or 'periodic' (torus
    wrap — ring-collective topology for simulation workloads).
    """
    from parallel_convolution_tpu.utils.config import BOUNDARIES

    if boundary not in BOUNDARIES:
        raise ValueError(f"boundary must be one of {BOUNDARIES}, got {boundary!r}")
    periodic = boundary == "periodic"
    R, C = grid
    padded = halo_pad_axis(block, r, "x", R, dim=1, periodic=periodic)
    return halo_pad_axis(padded, r, "y", C, dim=2, periodic=periodic)
