"""Kernel-form registry: dispatch is a table, not an if-ladder.

Until round 15 ``parallel/step.py`` selected its per-backend program by
string comparison (``backend == "pallas_rdma"`` / the
``_correlate_for_backend`` ladder), and every capability question —
"does this backend have an overlapped halo pipeline?" — was answered by
repeating the same comparison at each call site (three verbatim clamps
in step.py alone).  New stencil families (the multigrid transfer
operators this round, 3D forms later) would each have grown the ladder.

This module is the replacement: a process-global registry of
:class:`KernelForm` records keyed by ``(rank, name, boundary)``:

* ``rank`` — spatial rank of the stencil (2 today; a (D, H, W) volume
  path registers rank 3 without touching dispatch);
* ``name`` — the program family: a backend name from the canonical
  ``BACKENDS`` registry for smoothers, or an operator name for other
  stencil forms (``restrict_fw``, ``prolong_bilinear``);
* ``boundary`` — one key per supported boundary, so an unsupported
  (form, boundary) combination fails at *resolution*, loudly, instead
  of deep inside a trace.

Each form carries its ``stencil_form`` class (``smooth`` | ``restrict``
| ``prolong``), a per-form **capability bit** for the overlapped halo
pipeline (``overlap_capable`` — the one place that knowledge lives; the
clamps that were duplicated across step/bench/engine/degrade now call
:func:`clamp_overlap`), and its ``build`` callable — the factory that
returns the per-block step function ``parallel/step._build_*`` compiles
into shard_map programs.

Key contract (pinned by ``tests/test_multigrid.py``): the ``smooth``
key set is exactly ``{(2, b, bd) for b in BACKENDS for bd in
BOUNDARIES}`` — the old ladder, no more, no less; transfer operators
and future forms extend the registry under their own ``stencil_form``
without widening the smoother set.

jax-free at import: forms are *declared* here and *registered* by the
modules that own their implementations (``parallel/step.py`` registers
the six smoother families at import; ``solvers/transfer.py`` the
multigrid transfer operators).  :func:`resolve` lazily imports the
default providers on a miss, so a caller that asks before importing
step still gets an answer.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

__all__ = ["KernelForm", "clamp_overlap", "overlap_capable",
           "persistent_capable", "register", "registered_keys", "resolve"]

# The stencil-form vocabulary (closed: dispatch code switches on it).
# "physics" (round 23) classes the time-dependent rank-3 forms (wave,
# Gray–Scott): they iterate like smoothers but are NOT convergence
# smoothers — converge admission keys off the class, not the name.
STENCIL_FORMS = ("smooth", "restrict", "prolong", "physics")


@dataclasses.dataclass(frozen=True)
class KernelForm:
    """One registered stencil program family.

    ``build`` is the step factory; its signature is owned by the
    registering module (for smoothers it is exactly the historical
    ``step._make_block_step`` contract: ``build(filt, grid, valid_hw,
    block_hw, quantize, fuse, boundary, tile, interpret,
    interior_split, overlap) -> step``, where ``step`` maps one
    device's planar block to the next).  The registry stores and
    resolves; it never calls.
    """

    name: str
    rank: int = 2
    stencil_form: str = "smooth"
    boundaries: tuple[str, ...] = ("zero", "periodic")
    overlap_capable: bool = False
    # Persistent halo channels (parallel.channels): the form binds its
    # exchange descriptors once per identity and reuses them across
    # fused iterations / converge chunks / V-cycle levels.  The one
    # place that knowledge lives (round 16) — the cost model's zeroed
    # setup term and the col_mode resolution both key off it.
    persistent_capable: bool = False
    build: Callable | None = None

    def __post_init__(self) -> None:
        if self.stencil_form not in STENCIL_FORMS:
            raise ValueError(
                f"stencil_form must be one of {STENCIL_FORMS}, got "
                f"{self.stencil_form!r}")
        if not self.boundaries:
            raise ValueError(f"form {self.name!r} supports no boundary")


_FORMS: dict[tuple[int, str, str], KernelForm] = {}


def register(form: KernelForm) -> KernelForm:
    """Install ``form`` under one key per supported boundary.

    Re-registering the same (name, rank) with a different shape — or a
    different ``build`` provider — raises: two modules silently fighting
    over a key would make dispatch depend on import order.  Idempotent
    re-registration (module reload) is allowed when the declared
    capabilities match and ``build`` resolves to the same provider
    (compared by module/qualname, not object identity, so a reload's
    fresh function objects still count as the same provider).
    """
    for bd in form.boundaries:
        key = (form.rank, form.name, bd)
        old = _FORMS.get(key)
        if old is not None and (
                old.stencil_form != form.stencil_form
                or old.overlap_capable != form.overlap_capable
                or old.persistent_capable != form.persistent_capable
                or old.boundaries != form.boundaries
                or _build_id(old.build) != _build_id(form.build)):
            raise ValueError(
                f"kernel form {key} already registered with different "
                f"capabilities ({old.stencil_form}/"
                f"overlap={old.overlap_capable}) or a different build "
                f"provider")
        _FORMS[key] = form
    return form


def _build_id(build) -> tuple:
    """Stable identity of a build callable: the underlying function's
    (module, qualname) plus any ``functools.partial`` args."""
    if build is None:
        return (None,)
    f = getattr(build, "func", build)
    return (getattr(f, "__module__", None),
            getattr(f, "__qualname__", None),
            tuple(getattr(build, "args", ())))


def _ensure_default_forms() -> None:
    """Import the default providers (idempotent) so resolution works in
    any import order — the registry is jax-free, the implementations
    are not, so they land lazily on the first miss."""
    from parallel_convolution_tpu.parallel import step  # noqa: F401
    from parallel_convolution_tpu.solvers import transfer  # noqa: F401
    from parallel_convolution_tpu.volumes import forms  # noqa: F401


def resolve(rank: int, name: str, boundary: str) -> KernelForm:
    """The form dispatch compiles for ``(rank, name, boundary)``.

    Raises ``ValueError`` (the service's typed-``invalid`` class) naming
    the available keys when nothing is registered — the error surface
    the old ladder's ``unknown backend`` branch provided, now covering
    every stencil form.
    """
    key = (int(rank), str(name), str(boundary))
    form = _FORMS.get(key)
    if form is None:
        _ensure_default_forms()
        form = _FORMS.get(key)
    if form is None:
        names = sorted({k[1] for k in _FORMS if k[0] == key[0]})
        raise ValueError(
            f"no kernel form registered for rank={key[0]} name={key[1]!r} "
            f"boundary={key[2]!r}; registered rank-{key[0]} forms: {names}")
    return form


def registered_keys(stencil_form: str | None = None) -> frozenset:
    """The registered ``(rank, name, boundary)`` key set, optionally
    filtered by stencil form — the pinned-test surface."""
    _ensure_default_forms()
    return frozenset(k for k, f in _FORMS.items()
                     if stencil_form is None
                     or f.stencil_form == stencil_form)


def overlap_capable(name: str, rank: int = 2) -> bool:
    """Whether ``name`` has an interior-first overlapped halo pipeline —
    the per-form capability bit.  Unknown names are simply not capable
    (the degrade walk may probe names mid-registration)."""
    _ensure_default_forms()
    for bd in ("zero", "periodic"):
        form = _FORMS.get((int(rank), str(name), bd))
        if form is not None:
            return form.overlap_capable
    return False


def persistent_capable(name: str, rank: int = 2) -> bool:
    """Whether ``name`` binds persistent halo channels — the per-form
    capability bit (round 16).  Unknown names are not capable."""
    _ensure_default_forms()
    for bd in ("zero", "periodic"):
        form = _FORMS.get((int(rank), str(name), bd))
        if form is not None:
            return form.persistent_capable
    return False


def clamp_overlap(overlap, name: str, rank: int = 2) -> bool:
    """The one overlap-legality clamp: a resolved/degraded backend keeps
    ``overlap=True`` only if its registered form is overlap-capable.

    Replaces the three verbatim ``overlap and backend == "pallas_rdma"``
    clamps in step.py (and their copies in bench/engine/pipeline): a new
    overlap-capable form inherits legality by *registering* the bit, and
    the multigrid smoother inherits it for free because it dispatches
    through the same names.
    """
    return bool(overlap) and overlap_capable(name, rank)
