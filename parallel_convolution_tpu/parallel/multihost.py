"""Multi-host deployment (the reference's multi-node ``mpiexec`` tier).

The reference scales past one node by launching MPI ranks across hosts; the
TPU-native equivalent is JAX's multi-controller runtime: one Python process
per host, ``jax.distributed.initialize`` (the ``MPI_Init`` analog), and a
mesh over ``jax.devices()`` — which then spans every host's chips.  All the
machinery in this package (shard_map step, ppermute halos, sharded I/O,
per-shard checkpoints) is already multi-host-safe because it only ever
touches ``addressable_shards`` on the host side; XLA routes the halo
collectives over ICI within a slice and DCN across slices.

Single-host runs need none of this — the module is a thin, documented shim
so a pod launch is three lines:

    from parallel_convolution_tpu.parallel import multihost
    multihost.initialize()          # on every host, same flags
    model = ConvolutionModel()      # mesh spans the whole pod

This environment has one host/one chip, so the path is exercised by the
single-host no-op branch plus the CPU-mesh tests; the barrier/sync helpers
wrap ``jax.experimental.multihost_utils``.
"""

from __future__ import annotations

import jax


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """``MPI_Init`` for TPU pods.  No-op when single-process.

    With no arguments, relies on the TPU environment's auto-bootstrap
    (GKE/GCE metadata), which is the common case on Cloud TPU pods.
    """
    if num_processes is not None and num_processes <= 1:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def process_info() -> dict:
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }


def barrier(name: str = "pctpu_barrier") -> None:
    """Cross-host sync point (the ``MPI_Barrier`` before/after timing)."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


def broadcast_scalar(value: float) -> float:
    """Agree on one host-side scalar across processes (rank-0 wins)."""
    if jax.process_count() == 1:
        return value
    from jax.experimental import multihost_utils

    import numpy as np

    arr = multihost_utils.broadcast_one_to_all(np.asarray(value))
    return float(arr)
