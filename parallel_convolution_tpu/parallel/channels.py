"""Persistent halo channels: the exchange's descriptor plan, built once.

"Persistent and Partitioned MPI for Stencil Communication" (PAPERS.md)
binds a stencil's communication schedule once per exchange *identity* —
``MPI_Send_init`` / ``MPI_Psend_init`` — and then merely (re)starts the
bound channels every iteration, instead of re-deriving buffers, counts,
and partners per call.  This module is that layer for the RDMA kernels:

* :class:`ChannelKey` is the exchange identity the paper keys on —
  ``(mesh grid, block, radius, fuse, dtype, boundary)`` plus which
  kernel form consumes it (monolithic VMEM vs tiled HBM-pad — their
  slab geometry differs) and the column transport (``col_mode``);
* :class:`ChannelPlan` is the bound structure: per-direction slab
  descriptors (source/destination rectangles in pad coordinates,
  neighbor offset, semaphore slot) plus the self-wrap flags, computed
  ONCE per identity and cached process-globally;
* :func:`plan_for` is the cache: every trace of
  ``ops.pallas_rdma.fused_rdma_step`` — every fused iteration chunk,
  every converge-chunk build, every multigrid V-cycle level — fetches
  the SAME plan object for the same identity instead of recomputing the
  slab arithmetic per phase.  ``stats()['builds']`` therefore equals
  the number of *distinct exchange identities* a process has run, which
  the ``--channels-smoke`` leg asserts stays flat across iterations.

Honesty note on "persistent" in this stack: a Pallas remote-copy
descriptor is a trace-time construct — XLA compiles it into the program,
so the *compiled executable* is already the paper's "bound channel"
(reused across every iteration of a ``fori_loop`` and every call of a
warm serving key).  What used to be re-derived per exchange phase was
the descriptor *geometry* (offsets, extents, partners, semaphore
pairing) at trace time, once per kernel build; this module hoists that
into one cached plan per identity, makes reuse observable (the
build/hit counters, mirrored into obs when enabled), and gives the
kernels one authoritative slab table instead of four copies of inline
slice arithmetic.  DESIGN.md "Persistent & partitioned halo channels"
states the full mapping to the paper.

jax-free: pure dataclasses + int arithmetic (the sublane table is the
tuning cost model's mirrored constant), so plans build identically on a
dev laptop, in CI, and on the chip host.
"""

from __future__ import annotations

import dataclasses
import threading

from parallel_convolution_tpu.tuning.costmodel import LANE, SUBLANE
from parallel_convolution_tpu.utils.config import (
    COL_MODE_CHOICES, COL_MODES,
)

__all__ = [
    "COL_MODES", "COL_MODE_CHOICES", "ChannelKey", "ChannelPlan", "Slab",
    "plan_for", "reset", "stats",
]

# COL_MODES / COL_MODE_CHOICES are re-exported from the canonical
# jax-free registry (utils.config): "packed" stages the strided column
# slab through a contiguous buffer and moves it with ONE dense RDMA;
# "strided" issues the direct strided copy; "auto" (user surfaces only)
# is resolved to a concrete mode before any plan or key is built.

# Semaphore slots, mirrored from ops.pallas_rdma (one (send, recv) pair
# per direction; the plan records the slot so kernel and plan can never
# disagree on pairing).
SEM_UP, SEM_DOWN, SEM_LEFT, SEM_RIGHT = 0, 1, 2, 3

DIRECTIONS = ("up", "down", "left", "right")

# The direction whose inbound copy writes MY ghost on the given side
# (SPMD symmetry: my top ghost is written by my upper neighbor's "down"
# send, so retiring the "up" slab waits the "down" copy's recv
# semaphore).  One table, consumed by both kernels' retirement code.
OPPOSITE = {"up": "down", "down": "up", "left": "right", "right": "left"}


@dataclasses.dataclass(frozen=True)
class ChannelKey:
    """One exchange identity (the persistent-channel binding key)."""

    grid: tuple[int, int]
    block_hw: tuple[int, int]
    radius: int
    fuse: int
    dtype: str                 # storage dtype name (the wire dtype)
    boundary: str
    kernel: str = "monolithic"  # "monolithic" | "tiled"
    col_mode: str = "strided"   # resolved transport: "packed" | "strided"

    def __post_init__(self) -> None:
        if self.kernel not in ("monolithic", "tiled"):
            raise ValueError(f"unknown kernel form {self.kernel!r}")
        if self.col_mode not in COL_MODES:
            raise ValueError(
                f"col_mode must be one of {COL_MODES} (resolved, never "
                f"'auto') at the plan layer, got {self.col_mode!r}")


@dataclasses.dataclass(frozen=True)
class Slab:
    """One direction's ghost-slab channel: where it reads, where it
    lands on the partner, which partner, which semaphore pair.

    Rectangles are half-open ``(lo, hi)`` in the owning kernel's pad
    coordinates; ``rows=None`` means the full padded height (the tiled
    kernel's column bands, whose extent depends on the launch's tile
    geometry, not the exchange identity)."""

    direction: str
    src_rows: tuple[int, int] | None
    src_cols: tuple[int, int]
    dst_rows: tuple[int, int] | None
    dst_cols: tuple[int, int]
    nbr: tuple[int, int]
    sem: int


@dataclasses.dataclass(frozen=True)
class ChannelPlan:
    """The bound descriptor structure of one exchange identity.

    ``row_slabs``/``col_slabs`` are empty on axes with no remote partner
    (a 1-extent axis) — the degenerate 1x1 grid's plan holds NO channels
    at all, which is what lets the kernels statically elide the whole
    machinery there (pinned: the 1x1 program is the serialized one
    verbatim, independent of col_mode).  ``row_wrap``/``col_wrap`` mark
    periodic self-wrap axes (local copies, not channels).
    """

    key: ChannelKey
    row_slabs: tuple[Slab, ...]
    col_slabs: tuple[Slab, ...]
    row_wrap: bool
    col_wrap: bool

    @property
    def packed_cols(self) -> bool:
        """Whether this plan stages its column slabs (packed transport
        with a remote column partner to stage for)."""
        return self.key.col_mode == "packed" and bool(self.col_slabs)

    def slabs(self) -> tuple[Slab, ...]:
        return self.row_slabs + self.col_slabs

    def slab(self, direction: str) -> Slab | None:
        for s in self.slabs():
            if s.direction == direction:
                return s
        return None


def _monolithic_slabs(key: ChannelKey):
    """Slab geometry of the all-VMEM kernel: ghost depth d = radius*fuse,
    row slabs at interior columns, column slabs at FULL padded height
    (the two-hop corner propagation — column bytes carry the corners)."""
    (R, C), (h, w) = key.grid, key.block_hw
    d = key.radius * max(1, key.fuse)
    periodic = key.boundary == "periodic"
    row_slabs: tuple[Slab, ...] = ()
    col_slabs: tuple[Slab, ...] = ()
    if R > 1:
        row_slabs = (
            Slab("up", (d, 2 * d), (d, d + w),
                 (h + d, h + 2 * d), (d, d + w), (-1, 0), SEM_UP),
            Slab("down", (h, h + d), (d, d + w),
                 (0, d), (d, d + w), (+1, 0), SEM_DOWN),
        )
    if C > 1:
        full = (0, h + 2 * d)
        col_slabs = (
            Slab("left", full, (d, 2 * d),
                 full, (w + d, w + 2 * d), (0, -1), SEM_LEFT),
            Slab("right", full, (w, w + d),
                 full, (0, d), (0, +1), SEM_RIGHT),
        )
    return row_slabs, col_slabs, periodic and R == 1, periodic and C == 1


def _tiled_slabs(key: ChannelKey):
    """Slab geometry of the HBM-pad windowed kernel: transfers move a
    full (sublane, 128)-aligned band whose trailing/leading r*fuse
    rows/cols land on the receiver's ghost positions (ops.pallas_rdma's
    aligned-band scheme); column bands run the full padded height
    (``rows=None`` — the extent is a launch property, not an exchange
    identity property)."""
    (R, C), (h, w) = key.grid, key.block_hw
    sub_v = SUBLANE[_storage_of(key.dtype)]
    periodic = key.boundary == "periodic"
    row_slabs: tuple[Slab, ...] = ()
    col_slabs: tuple[Slab, ...] = ()
    if R > 1:
        row_slabs = (
            Slab("up", (sub_v, 2 * sub_v), (LANE, LANE + w),
                 (h + sub_v, h + 2 * sub_v), (LANE, LANE + w),
                 (-1, 0), SEM_UP),
            Slab("down", (h, h + sub_v), (LANE, LANE + w),
                 (0, sub_v), (LANE, LANE + w), (+1, 0), SEM_DOWN),
        )
    if C > 1:
        col_slabs = (
            Slab("left", None, (LANE, 2 * LANE),
                 None, (w + LANE, w + 2 * LANE), (0, -1), SEM_LEFT),
            Slab("right", None, (w, w + LANE),
                 None, (0, LANE), (0, +1), SEM_RIGHT),
        )
    return row_slabs, col_slabs, periodic and R == 1, periodic and C == 1


def _storage_of(dtype_name: str) -> str:
    """Map a numpy dtype name onto the storage registry's key (the
    sublane table's index); unknown dtypes tile like f32."""
    return {"float32": "f32", "bfloat16": "bf16", "uint8": "u8"}.get(
        dtype_name, "f32")


# -- the process-global plan cache (the persistence) -----------------------

_PLANS: dict[ChannelKey, ChannelPlan] = {}
_STATS = {"builds": 0, "hits": 0}
_LOCK = threading.Lock()


def plan_for(key: ChannelKey) -> ChannelPlan:
    """The (cached) channel plan for one exchange identity.

    Builds are counted separately from hits so reuse is *assertable*:
    after a warm fused converge run (or a V-cycle), ``builds`` equals
    the number of distinct identities, however many iterations ran.
    """
    with _LOCK:
        plan = _PLANS.get(key)
        if plan is not None:
            _STATS["hits"] += 1
            _note("hits")
            return plan
        rows, cols, rw, cw = (_tiled_slabs(key) if key.kernel == "tiled"
                              else _monolithic_slabs(key))
        plan = ChannelPlan(key, rows, cols, rw, cw)
        _PLANS[key] = plan
        _STATS["builds"] += 1
        _note("builds")
        return plan


def _note(which: str) -> None:
    """Mirror one build/hit into the obs registry (one branch when obs
    is off — the counters here stay authoritative either way)."""
    from parallel_convolution_tpu.obs import metrics

    if not metrics.enabled():
        return
    name = ("pctpu_channel_builds_total" if which == "builds"
            else "pctpu_channel_reuse_total")
    metrics.counter(
        name, "halo channel-plan descriptor builds vs cache reuses",
        ()).inc()


def stats() -> dict:
    """``{"builds": n, "hits": n}`` — the channel-reuse evidence."""
    with _LOCK:
        return dict(_STATS)


def reset() -> None:
    """Drop the cache and zero the counters (tests / smoke legs)."""
    with _LOCK:
        _PLANS.clear()
        _STATS["builds"] = 0
        _STATS["hits"] = 0
