"""Sharded iteration driver: the reference's main loop, SPMD-compiled.

Reference components C6 + C8 (SURVEY.md §2) and the hot loop of §3.2:

    for t in loops:
        exchange halos (Isend/Irecv + Waitall)
        convolute(block)
        swap(src, dst)
        every N iters: local diff → MPI_Allreduce → maybe break

becomes one ``jax.jit``-compiled ``shard_map`` over the ('x','y') mesh whose
body runs the whole iteration loop on-device: ``lax.fori_loop`` (fixed
iteration count) or ``lax.while_loop`` (run-to-convergence, the
``MPI_Allreduce`` becoming ``lax.pmax`` of the per-block max-abs diff).
The functional loop carry is the double buffer; donated input storage gives
XLA the reference's pointer swap for free.

Non-divisible images (e.g. 2520 over a 4-high grid) are padded to the next
block multiple and re-masked to zero every iteration, which keeps the pad
region behaving exactly like the oracle's zero ghost ring — outputs stay
bit-identical to the serial oracle for any mesh shape.
"""

from __future__ import annotations

import os
import time
import warnings
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from parallel_convolution_tpu.obs import events as obs_events, metrics as obs_metrics
from parallel_convolution_tpu.ops import conv
from parallel_convolution_tpu.ops.filters import Filter
from parallel_convolution_tpu.parallel import halo, kernels as kernel_forms
from parallel_convolution_tpu.parallel.mesh import (
    AXES,
    block_sharding,
    grid_shape,
    make_grid_mesh,
    padded_extent,
)
from parallel_convolution_tpu.resilience.faults import fault_point
from parallel_convolution_tpu.utils.config import (  # canonical registries
    AUTO, BACKENDS,
)
from parallel_convolution_tpu.utils.jax_compat import shard_map

__all__ = ["BACKENDS", "STORAGE_DTYPES", "sharded_iterate", "sharded_converge",
           "sharded_converge_stream", "iterate_prepared", "reshard_prepared",
           "resolve_overlap", "resolve_col_mode", "clamp_col_mode"]


def _note_compile(builder: str, backend: str, grid, iters: int, fuse: int,
                  boundary: str, block_hw) -> None:
    """Telemetry for one fresh trace/compile (a build-cache miss): the
    ``compile`` event + a labeled counter.  One branch when obs is off.

    When the compile happens under an active trace (a cold serving key:
    the engine's compile span is current on this thread), the event
    carries the trace/span ids — ``trace_report.py`` can then show which
    request's span tree triggered which build-cache miss."""
    if not obs_metrics.enabled():
        return
    from parallel_convolution_tpu.obs import trace as obs_trace

    obs_metrics.counter(
        "pctpu_compiles_total", "fresh traces/compiles of iteration runners",
        ("builder", "backend")).inc(builder=builder, backend=backend)
    ctx = obs_trace.current()
    obs_events.emit(
        "compile", builder=builder, backend=backend,
        grid=f"{grid[0]}x{grid[1]}", iters=int(iters), fuse=int(fuse),
        boundary=boundary, block=[int(b) for b in block_hw],
        **({"trace_id": ctx.trace_id, "span_id": ctx.span_id}
           if ctx is not None else {}))


def _record_step_obs(backend: str, mesh: Mesh, block_hw, radius: int,
                     fuse: int, iters: int, channels: int, storage: str,
                     boundary: str, wall_s: float | None, shape,
                     quantize: bool, tile, source: str,
                     overlap: bool = False,
                     col_mode: str = "packed") -> None:
    from parallel_convolution_tpu.obs import attribution

    grid = grid_shape(mesh)
    dev0 = mesh.devices.flat[0]
    attribution.record_step(
        backend=backend, grid=grid, block_hw=block_hw, radius=radius,
        fuse=fuse, iters=iters, channels=channels, storage=storage,
        boundary=boundary, wall_s=wall_s, shape=shape, quantize=quantize,
        tile=tile, platform=dev0.platform,
        device_kind=getattr(dev0, "device_kind", "") or "", source=source,
        overlap=overlap, col_mode=col_mode)


def _valid_mask(valid_hw, block_hw, margin: int = 0):
    """Per-block validity mask of globally-in-image pixels, as (1, h, w) f32.

    ``margin`` extends the block by m on every side (the temporal-fusion
    intermediate levels live on such extended blocks); positions outside
    the valid global image — beyond the image edge *or* in the
    pad-to-multiple rim — are 0.
    """
    H, W = valid_hw
    bh, bw = block_hw
    m = margin
    row0 = lax.axis_index("x") * bh - m
    col0 = lax.axis_index("y") * bw - m
    shape = (bh + 2 * m, bw + 2 * m)
    rows = row0 + lax.broadcasted_iota(jnp.int32, shape, 0)
    cols = col0 + lax.broadcasted_iota(jnp.int32, shape, 1)
    ok = (rows >= 0) & (rows < H) & (cols >= 0) & (cols < W)
    return ok[None].astype(jnp.float32)


# Overlap resolution warn-once registry (one line per cause per process;
# the stamped knob, not stderr, is the durable record).
_OVERLAP_WARNED: set = set()

# Env escape hatch: run the overlapped program under interpreted Pallas
# anyway.  The CPU shim has no real async semaphore timing, so overlap
# buys nothing there and is force-serialized by default — but CI byte
# proofs (scripts/rdma_fuse_ab.py --overlap, the --overlap-smoke leg)
# must drive the overlapped PROGRAM through the full dispatch stack.
# Canonical name lives in the jax-free config registry; re-exported here
# because dispatch call sites (and tests) historically read it off step.
from parallel_convolution_tpu.utils.config import (  # noqa: E402
    OVERLAP_INTERPRET_ENV,
)


def _warn_overlap_once(cause: str, msg: str) -> None:
    if cause in _OVERLAP_WARNED:
        return
    _OVERLAP_WARNED.add(cause)
    warnings.warn(msg, UserWarning, stacklevel=3)


def resolve_overlap(overlap: bool | None, backend: str, mesh: Mesh) -> bool:
    """The overlap knob a launch will ACTUALLY compile with.

    ``None`` (the explicit-backend default) resolves to False — the
    serialized order; ``backend="auto"`` callers get a concrete bool
    from the tuning resolver before reaching here.  ``True`` is a
    clamped request, mirrored by ``tuning.resolve``:

    * only the RDMA kernels have an overlapped pipeline — any other
      backend force-serializes with a one-time warning;
    * interpreted Pallas (a CPU mesh) force-serializes with a one-time
      warning: the interpreter's DMAs have no real async timing, so the
      pipeline proves nothing and costs trace complexity — UNLESS
      ``PCTPU_OVERLAP_INTERPRET=1``, the CI byte-proof escape hatch
      (the A/B harness and the --overlap-smoke leg run the overlapped
      program through the whole dispatch stack to prove byte equality).

    Every bench row / serving response stamps the RESOLVED value, so a
    clamp is visible in artifacts, never only on stderr.
    """
    if overlap is None or not overlap:
        return False
    if not kernel_forms.overlap_capable(backend):
        # The per-form capability bit (kernel registry): only forms that
        # REGISTER an overlapped pipeline may keep the request.
        _warn_overlap_once(
            f"backend:{backend}",
            f"overlap=True requested but backend {backend!r} has no "
            "overlapped halo pipeline (RDMA kernels only); running "
            "serialized — rows stamp overlap=False")
        return False
    if _mesh_interpret(mesh) and not os.environ.get(OVERLAP_INTERPRET_ENV):
        _warn_overlap_once(
            "interpret",
            "overlap=True force-serialized under interpreted Pallas (the "
            "CPU shim has no real async semaphore timing; set "
            f"{OVERLAP_INTERPRET_ENV}=1 to run the overlapped program "
            "anyway for byte proofs) — rows stamp overlap=False")
        return False
    return True


def resolve_col_mode(col_mode, backend: str, mesh: Mesh, block_hw,
                     radius: int, fuse: int, storage: str) -> str:
    """The column-slab transport a launch will ACTUALLY compile with.

    ``None``/``"auto"`` resolve through the cost model
    (``costmodel.pick_col_mode`` — the derived-datatypes decision:
    strided descriptor overhead vs packed staging bytes) for
    persistent-capable forms; every other form has no in-kernel column
    RDMA transport, so the knob is inert there and normalizes to the
    canonical ``"packed"`` label (one value → one EngineKey / bench
    identity, matching the legacy-plan-record default).  An explicit
    packed/strided request on a capable form is honored verbatim — the
    two transports are byte-identical by construction, so no clamp
    warning is needed.  Every bench row / serving response stamps the
    RESOLVED value.
    """
    from parallel_convolution_tpu.parallel import channels

    if col_mode is not None and col_mode not in channels.COL_MODE_CHOICES:
        raise ValueError(
            f"col_mode must be one of {channels.COL_MODE_CHOICES}, got "
            f"{col_mode!r}")
    if not kernel_forms.persistent_capable(backend):
        return "packed"
    if grid_shape(mesh)[1] <= 1:
        # No remote column axis: both transports compile the identical
        # statically-elided program, so even an explicit request
        # normalizes to the canonical label — one program, one
        # EngineKey / bench identity (the same rule the tuner's
        # _legal_col_modes applies).
        return "packed"
    if col_mode in (None, "auto"):
        from parallel_convolution_tpu.tuning import costmodel

        dev0 = mesh.devices.flat[0]
        hw = costmodel.hardware_for(
            dev0.platform, getattr(dev0, "device_kind", "") or "")
        return costmodel.pick_col_mode(
            grid_shape(mesh), tuple(int(b) for b in block_hw), int(radius),
            max(1, int(fuse)), storage, hw)
    return col_mode


def clamp_col_mode(col_mode: str, backend: str) -> str:
    """Re-clamp a resolved col_mode after a degrade walk: a backend with
    no persistent channels normalizes to the canonical 'packed'."""
    return (col_mode if kernel_forms.persistent_capable(backend)
            else "packed")


def _axis_class_index(a, n: int):
    """Dynamic index of device ``a``'s offset class along an ``n``-device
    axis, matching ``pallas_stencil.axis_offset_classes`` order."""
    if n == 1:
        return jnp.int32(0)
    if n == 2:
        return a.astype(jnp.int32)
    return jnp.where(a == 0, 0, jnp.where(a == n - 1, 2, 1)).astype(jnp.int32)


def _boundary_geometry(grid, valid_hw, block_hw, boundary: str):
    """Shared geometry checks of every rank-2 form: periodic divisibility
    and whether the pad-to-multiple rim needs re-masking."""
    periodic = boundary == "periodic"
    if periodic and (valid_hw[0] != block_hw[0] * grid[0]
                     or valid_hw[1] != block_hw[1] * grid[1]):
        raise ValueError(
            "periodic boundary requires dimensions divisible by the mesh "
            f"grid: image {valid_hw} on grid {grid}"
        )
    needs_mask = not periodic and (valid_hw[0] != block_hw[0] * grid[0]
                                   or valid_hw[1] != block_hw[1] * grid[1])
    return periodic, needs_mask


def _build_rdma_step(filt: Filter, grid, valid_hw, block_hw, quantize: bool,
                     fuse: int = 1, boundary: str = "zero",
                     tile: tuple[int, int] | None = None,
                     interpret: bool | None = None,
                     interior_split: bool = False,
                     overlap: bool = False,
                     col_mode: str = "strided"):
    """The ``pallas_rdma`` kernel form: exchange + stencil fused in ONE
    kernel (remote DMA over ICI instead of collective-permute +
    concatenate + re-read).  fuse=T>1 widens the in-kernel exchange to
    T*r-deep ghosts and runs T levels before returning — the kernel
    re-zeroes out-of-image positions per level against valid_hw, so the
    outer mask is only needed on the single-level path.  The only form
    registered ``overlap_capable`` (the interior-first pipeline) and
    ``persistent_capable`` (bound halo channels + the packed/strided
    ``col_mode`` column-transport A/B — resolved by the caller, never
    'auto' here)."""
    periodic, needs_mask = _boundary_geometry(grid, valid_hw, block_hw,
                                              boundary)

    def step(v):
        from parallel_convolution_tpu.ops import pallas_rdma

        p = pallas_rdma.fused_rdma_step(
            v, filt, grid, boundary, quantize=quantize,
            out_dtype=v.dtype, tile=tile, interpret=interpret,
            fuse=fuse, valid_hw=None if periodic else tuple(valid_hw),
            overlap=overlap, col_mode=col_mode,
        )
        if needs_mask and fuse == 1:
            p = p * _valid_mask(valid_hw, block_hw).astype(p.dtype)
        return p

    return step


def _build_halo_step(backend: str, filt: Filter, grid, valid_hw, block_hw,
                     quantize: bool, fuse: int = 1, boundary: str = "zero",
                     tile: tuple[int, int] | None = None,
                     interpret: bool | None = None,
                     interior_split: bool = False,
                     overlap: bool = False,
                     col_mode: str = "strided"):
    """The halo-exchange kernel forms (every backend but ``pallas_rdma``):
    ``fuse`` iterations on a local block per collective halo exchange.

    fuse=1 is the reference's loop shape: exchange 1-deep halos, stencil,
    [quantize], re-mask.  fuse=T>1 is temporal fusion: exchange a T*r-deep
    halo ONCE, then run T stencil levels locally, each shrinking the
    extended block by r — T× fewer collective rounds (the latency bound of
    small blocks, SURVEY.md §3.2) at the cost of recomputing the
    overlapping rim.  Bit-exactness is preserved because each level
    re-zeroes out-of-image positions via the margin mask, exactly
    reproducing the oracle's ghost ring at every intermediate level.

    The block dtype is the *storage* dtype (f32, or bf16 — exact for
    quantized u8 values, half the HBM/ICI traffic); accumulation is always
    f32 inside the correlate implementations.
    """
    periodic, needs_mask = _boundary_geometry(grid, valid_hw, block_hw,
                                              boundary)
    r = filt.radius
    pallas_like = backend in ("pallas", "pallas_sep")
    sep = backend == "pallas_sep"

    def correlate_level(p, out_dtype):
        if pallas_like:
            from parallel_convolution_tpu.ops import pallas_stencil

            return pallas_stencil.correlate_padded_pallas(
                p, filt, quantize=quantize, out_dtype=out_dtype,
                separable=sep, tile=tile, interpret=interpret,
            )
        out = _XLA_CORRELATES[backend](p, filt)
        if quantize:
            out = conv.quantize_f32(out)
        return out

    def step(v):
        depth = r * fuse
        fault_point("halo_exchange")  # trace-time: a launch-build failure
        p = halo.halo_exchange(v, depth, grid, boundary)
        if pallas_like and fuse > 1:
            # All T levels inside one kernel: one HBM round trip per chunk.
            from parallel_convolution_tpu.ops import pallas_stencil

            off = jnp.stack([
                lax.axis_index("x") * block_hw[0],
                lax.axis_index("y") * block_hw[1],
            ]).astype(jnp.int32)

            def fused(p, off, block_off):
                return pallas_stencil.fused_iterate_pallas(
                    p, off, filt, fuse,
                    None if periodic else tuple(valid_hw),
                    quantize=quantize, out_dtype=v.dtype, separable=sep,
                    tile=tile, interpret=interpret,
                    interior_split=block_off is not None,
                    block_off=block_off,
                )

            if not interior_split or periodic:
                return fused(p, off, None)
            # Interior split on any grid: a device's offset is dynamic
            # under SPMD, but its interior geometry depends only on which
            # image edges its block can touch — at most 3 static offset
            # classes per axis (pallas_stencil.axis_offset_classes).
            # One lax.switch per chunk picks this device's specialized
            # launch; the masked border calls inside each branch still use
            # the dynamic `off`, so class offset *ranges* stay exact.
            rcls = pallas_stencil.axis_offset_classes(grid[0], block_hw[0])
            ccls = pallas_stencil.axis_offset_classes(grid[1], block_hw[1])
            if len(rcls) == 1 and len(ccls) == 1:
                return fused(p, off, (rcls[0], ccls[0]))
            branches = [
                (lambda bo: lambda pp, oo: fused(pp, oo, bo))((rr, cc))
                for rr in rcls for cc in ccls
            ]
            idx = (_axis_class_index(lax.axis_index("x"), grid[0])
                   * len(ccls)
                   + _axis_class_index(lax.axis_index("y"), grid[1]))
            return lax.switch(idx, branches, p, off)
        for t in range(fuse):
            margin = depth - r * (t + 1)
            p = correlate_level(p, v.dtype)
            if not periodic and (needs_mask or margin > 0):
                p = p * _valid_mask(valid_hw, block_hw, margin).astype(p.dtype)
        return p.astype(v.dtype)

    return step


def _make_block_step(filt: Filter, grid, valid_hw, block_hw, quantize: bool,
                     backend: str, fuse: int = 1, boundary: str = "zero",
                     tile: tuple[int, int] | None = None,
                     interpret: bool | None = None,
                     interior_split: bool = False,
                     overlap: bool = False,
                     col_mode: str = "strided"):
    """One smoothing-step builder, dispatched through the kernel-form
    registry (``parallel.kernels``): ``(rank=2, backend, boundary)``
    resolves to the registered form, whose ``build`` returns the
    per-block step function.  Unknown backends/boundaries fail HERE with
    the registry's ValueError naming what exists — the old if-ladder's
    error surface, now covering every registered stencil form."""
    form = kernel_forms.resolve(2, backend, boundary)
    if form.stencil_form != "smooth":
        raise ValueError(
            f"kernel form {backend!r} is a {form.stencil_form} operator, "
            "not a smoother; transfer operators are driven by "
            "solvers.multigrid, not the iterate path")
    return form.build(filt, grid, valid_hw, block_hw, quantize, fuse,
                      boundary, tile, interpret, interior_split, overlap,
                      col_mode)


def _mesh_interpret(mesh: Mesh) -> bool:
    """interpret= for Pallas kernels compiled for THIS mesh's devices.

    The global default backend can be a TPU while the mesh is a forced-CPU
    one (utils.platform.cpu_devices in a process that already initialized
    the tunnel backend) — resolving off jax.devices() there hands Mosaic
    kernels to the CPU lowering, which rejects them.
    """
    from parallel_convolution_tpu.utils.platform import device_on_tpu

    return not device_on_tpu(mesh.devices.flat[0])


def _check_block_size(filt: Filter, block_hw) -> None:
    if min(block_hw) < filt.radius:
        raise ValueError(
            f"per-device block {block_hw} smaller than filter radius "
            f"{filt.radius}; use a smaller mesh for this image"
        )


@lru_cache(maxsize=64)
def _build_iterate(mesh: Mesh, filt: Filter, iters: int, quantize: bool,
                   valid_hw, block_hw, backend: str, fuse: int = 1,
                   boundary: str = "zero",
                   tile: tuple[int, int] | None = None,
                   interior_split: bool = False,
                   overlap: bool = False,
                   col_mode: str = "strided"):
    """Compile the fixed-count iteration runner for one (mesh, config).

    ``overlap`` and ``col_mode`` must already be RESOLVED
    (``resolve_overlap`` / ``resolve_col_mode``) — this layer compiles
    exactly what it is told, so the stamped knobs and the executable can
    never disagree.
    """
    # Consulted only on lru_cache misses — i.e. exactly when a fresh
    # trace/compile happens, the event the 'backend_compile' site models.
    fault_point("backend_compile")
    grid = grid_shape(mesh)
    _check_block_size(filt, block_hw)
    fuse = max(1, min(fuse, iters or 1))
    if min(block_hw) < filt.radius * fuse:
        raise ValueError(
            f"fuse={fuse} needs blocks >= {filt.radius * fuse}, got {block_hw}"
        )
    _note_compile("iterate", backend, grid, iters, fuse, boundary, block_hw)
    interp = _mesh_interpret(mesh)
    chunk = _make_block_step(filt, grid, valid_hw, block_hw, quantize,
                             backend, fuse, boundary, tile, interp,
                             interior_split, overlap, col_mode)
    n_chunks, rem = divmod(iters, fuse)
    tail = (_make_block_step(filt, grid, valid_hw, block_hw, quantize,
                             backend, rem, boundary, tile, interp,
                             interior_split, overlap, col_mode)
            if rem else None)

    def body(block):
        block = lax.fori_loop(0, n_chunks, lambda _, v: chunk(v), block)
        if tail is not None:
            block = tail(block)
        return block

    sharded = shard_map(
        body, mesh=mesh, in_specs=P(None, *AXES), out_specs=P(None, *AXES),
        check_vma=False,  # pallas interpret-mode slices trip the vma checker
    )
    return jax.jit(sharded, donate_argnums=0)


@lru_cache(maxsize=64)
def _build_converge(mesh: Mesh, filt: Filter, tol: float, max_iters: int,
                    check_every: int, quantize: bool, valid_hw, block_hw,
                    backend: str, boundary: str = "zero", fuse: int = 1,
                    tile: tuple[int, int] | None = None,
                    interior_split: bool = False,
                    overlap: bool = False,
                    col_mode: str = "strided"):
    """Compile the run-to-convergence runner (C6: every-N diff + allreduce).

    ``fuse``/``tile`` are the flagship iteration knobs (temporal fusion,
    kernel tile), valid here too: a check_every-iteration chunk runs as
    floor((n-1)/fuse) fused steps + the remainder as single steps + ONE
    final single step that forms the (prev, cur) convergence pair — so any
    fuse ≥ 1 works for any check_every and the iterate stays bit-identical
    to fuse=1 (fused steps are exact, tested in test_sharded.py).
    """
    fault_point("backend_compile")  # lru_cache miss == a fresh compile
    grid = grid_shape(mesh)
    _check_block_size(filt, block_hw)
    # A chunk fuses at most the n-1 pre-pair iterations (the final one is
    # always a single step so the (prev, cur) diff exists), so clamp to
    # check_every - 1 — otherwise fuse == check_every would silently run
    # every iteration unfused ((n-1)//fuse == 0).
    requested_fuse = fuse
    fuse = max(1, min(fuse, check_every - 1))
    if min(block_hw) < filt.radius * fuse:
        clamp_note = (f" (fuse={requested_fuse} clamped to {fuse}: a "
                      f"check_every={check_every} chunk fuses at most its "
                      "n-1 pre-pair iterations)"
                      if fuse != requested_fuse else "")
        raise ValueError(
            f"fuse={fuse} needs blocks >= {filt.radius * fuse}, got "
            f"{block_hw}{clamp_note}"
        )
    _note_compile("converge", backend, grid, max_iters, fuse, boundary,
                  block_hw)
    interp = _mesh_interpret(mesh)
    step = _make_block_step(filt, grid, valid_hw, block_hw, quantize, backend,
                            boundary=boundary, tile=tile, interpret=interp,
                            overlap=overlap, col_mode=col_mode)
    fused = (_make_block_step(filt, grid, valid_hw, block_hw, quantize,
                              backend, fuse, boundary, tile, interp,
                              interior_split, overlap, col_mode)
             if fuse > 1 else None)

    def body(block):
        def chunk(carry):
            cur, done, _ = carry
            n = jnp.minimum(check_every, max_iters - done)

            # Carry ONE buffer through the loop and form the (prev, cur)
            # diff pair only at the chunk boundary: carrying the pair
            # through fori_loop copies a full block every iteration
            # (measured 8x the stencil cost at 8192² on v5e — 45 ms/iter
            # vs 5.7 for the fixed-count path).
            if fused is None:
                prev = lax.fori_loop(0, n - 1, lambda _, v: step(v), cur)
            else:
                prev = lax.fori_loop(0, (n - 1) // fuse,
                                     lambda _, v: fused(v), cur)
                prev = lax.fori_loop(0, (n - 1) % fuse,
                                     lambda _, v: step(v), prev)
            cur = step(prev)
            # The MPI_Allreduce: global max of one iteration's change.
            delta = jnp.abs(cur.astype(jnp.float32) - prev.astype(jnp.float32))
            diff = lax.pmax(jnp.max(delta), AXES)
            return cur, done + n, diff

        def cond(carry):
            _, done, diff = carry
            return (done < max_iters) & (diff >= tol)

        init = (block, jnp.int32(0), jnp.float32(jnp.inf))
        cur, done, _ = lax.while_loop(cond, chunk, init)
        return cur, lax.pmax(done, AXES)

    sharded = shard_map(
        body, mesh=mesh, in_specs=P(None, *AXES),
        out_specs=(P(None, *AXES), P()),
        check_vma=False,  # pallas interpret-mode slices trip the vma checker
    )
    return jax.jit(sharded, donate_argnums=0)


@lru_cache(maxsize=64)
def _build_converge_chunk(mesh: Mesh, filt: Filter, n: int, quantize: bool,
                          valid_hw, block_hw, backend: str,
                          boundary: str = "zero", fuse: int = 1,
                          tile: tuple[int, int] | None = None,
                          interior_split: bool = False,
                          overlap: bool = False,
                          col_mode: str = "strided"):
    """Compile ONE convergence chunk: ``n`` iterations + the (prev, cur)
    max-abs diff, returned to the host.

    The progressive counterpart of :func:`_build_converge`: instead of
    the whole ``while_loop`` living on-device, each ``check_every``-sized
    chunk is its own fenced call so the HOST can observe the intermediate
    field (stream a snapshot, decide to stop, checkpoint...).  The chunk
    math is identical to one iteration of ``_build_converge``'s loop body
    — n-1 iterations (fused where legal) then one single step forming the
    (prev, cur) diff pair — so a host-driven chunk loop produces the same
    bytes as the compiled while_loop, which ``tests/test_router.py``
    asserts.  ``tol`` is NOT baked in: the host compares, so one compiled
    chunk serves every tolerance.
    """
    fault_point("backend_compile")  # lru_cache miss == a fresh compile
    grid = grid_shape(mesh)
    _check_block_size(filt, block_hw)
    # Fuse at most the n-1 pre-pair iterations (same rule as
    # _build_converge); a 1-iteration chunk has no pre-pair work at all.
    fuse = max(1, min(fuse, max(1, n - 1)))
    if min(block_hw) < filt.radius * fuse:
        raise ValueError(
            f"fuse={fuse} needs blocks >= {filt.radius * fuse}, got "
            f"{block_hw}")
    _note_compile("converge_chunk", backend, grid, n, fuse, boundary,
                  block_hw)
    interp = _mesh_interpret(mesh)
    step = _make_block_step(filt, grid, valid_hw, block_hw, quantize, backend,
                            boundary=boundary, tile=tile, interpret=interp,
                            overlap=overlap, col_mode=col_mode)
    fused = (_make_block_step(filt, grid, valid_hw, block_hw, quantize,
                              backend, fuse, boundary, tile, interp,
                              interior_split, overlap, col_mode)
             if fuse > 1 and n > 1 else None)

    def body(block):
        if fused is None:
            prev = lax.fori_loop(0, n - 1, lambda _, v: step(v), block)
        else:
            prev = lax.fori_loop(0, (n - 1) // fuse,
                                 lambda _, v: fused(v), block)
            prev = lax.fori_loop(0, (n - 1) % fuse,
                                 lambda _, v: step(v), prev)
        cur = step(prev)
        delta = jnp.abs(cur.astype(jnp.float32) - prev.astype(jnp.float32))
        diff = lax.pmax(jnp.max(delta), AXES)
        return cur, diff

    sharded = shard_map(
        body, mesh=mesh, in_specs=P(None, *AXES),
        out_specs=(P(None, *AXES), P()),
        check_vma=False,  # pallas interpret-mode slices trip the vma checker
    )
    return jax.jit(sharded, donate_argnums=0)


# Iteration-carry dtypes.  Quantized states are exact small integers, so
# narrower carries lose nothing: bf16 holds 0..255 exactly at half the
# HBM/ICI traffic of f32, and u8 — the reference's own ``unsigned char``
# buffer dtype — at a quarter (accumulation is always f32 inside the
# correlate implementations; u8 additionally requires quantize=True, checked
# in the entry points below).
STORAGE_DTYPES = {"f32": jnp.float32, "bf16": jnp.bfloat16, "u8": jnp.uint8}

# The jax-free registry (which the CLI/RunConfig validate against) and the
# dtype map here must never drift: a name accepted there but missing here
# would KeyError deep inside _prepare.
from parallel_convolution_tpu.utils.config import STORAGES as _STORAGES  # noqa: E402

if tuple(STORAGE_DTYPES) != _STORAGES:  # not assert: must survive python -O
    raise RuntimeError(
        f"storage registries drifted: {tuple(STORAGE_DTYPES)} != {_STORAGES}")


def _correlate_padded_xla(padded: jnp.ndarray, filt: Filter) -> jnp.ndarray:
    r = filt.radius
    lhs = padded.astype(jnp.float32)[:, None, :, :]
    rhs = jnp.asarray(filt.taps, jnp.float32)[None, None]
    out = lax.conv_general_dilated(
        lhs, rhs, (1, 1), "VALID", dimension_numbers=("NCHW", "OIHW", "NCHW"),
        precision=lax.Precision.HIGHEST,
    )
    return out[:, 0]


# The pure-XLA correlate implementations, keyed by form name (consumed by
# _build_halo_step; the old _correlate_for_backend ladder).
_XLA_CORRELATES = {
    "shifted": conv.correlate_padded,
    "xla_conv": _correlate_padded_xla,
    "separable": conv.correlate_padded_separable,
}


def _register_smoother_forms() -> None:
    """Install the six historical backends as rank-2 smoother forms.

    This IS the old if-ladder, stated once as data: each backend name
    maps to its builder, and ``pallas_rdma`` alone declares the
    overlapped-pipeline capability bit (the knowledge the three
    per-call-site clamps used to re-derive by string comparison)."""
    from functools import partial

    from parallel_convolution_tpu.utils.config import BOUNDARIES

    for name in BACKENDS:
        kernel_forms.register(kernel_forms.KernelForm(
            name=name, rank=2, stencil_form="smooth",
            boundaries=tuple(BOUNDARIES),
            overlap_capable=(name == "pallas_rdma"),
            persistent_capable=(name == "pallas_rdma"),
            build=(_build_rdma_step if name == "pallas_rdma"
                   else partial(_build_halo_step, name))))


_register_smoother_forms()


# Module-scope so jit's function-identity cache holds: a per-call lambda
# would retrace + recompile the reducer on every contract check.
_minmax_f32 = jax.jit(
    lambda a: jnp.stack([jnp.min(a), jnp.max(a)]).astype(jnp.float32))


def _check_quantize_contract(xs, filt: Filter, quantize: bool) -> None:
    """Fail loudly on out-of-contract quantize-mode inputs (ADVICE r4).

    ``quantize=True`` assumes u8-range pixel values (a decoded image,
    SURVEY §2 C1 semantics).  Convex filters elide the provably-idle
    store-back clamp, so a float plane with values outside [0, 255] would
    propagate UNCLAMPED where pre-elision code clamped it on the first
    store-back — silently different bytes.  One min/max reduce over the
    input per run (negligible vs the iterations) turns that into an error.
    Traced callers skip the check: the contract stays documented but is
    unverifiable mid-trace.
    """
    if not (quantize and filt.convex) or jnp.dtype(xs.dtype) == jnp.uint8:
        return
    if isinstance(xs, jax.core.Tracer):
        return
    # One fused device reduction + one 2-float readback (separate min/max
    # dispatches would each stream the whole array from HBM).
    lo, hi = (float(v) for v in _minmax_f32(xs))
    if lo < 0.0 or hi > 255.0:
        raise ValueError(
            f"quantize=True input has values in [{lo}, {hi}], outside the "
            "u8 contract [0, 255]: convex filters elide the store-back "
            "clamp, so out-of-range values would propagate unclamped. "
            "Clamp the input (or use quantize=False for float planes)."
        )


def _check_storage(storage: str, quantize: bool) -> None:
    if storage == "u8" and not quantize:
        raise ValueError(
            "storage='u8' requires quantize=True: u8 carries can only hold "
            "the quantized integer states; a float iterate would be "
            "silently truncated every iteration"
        )


def _prepare(x, mesh: Mesh, r: int, storage: str = "f32"):
    """Pad a global (C, H, W) image to block multiples and shard it."""
    x = jnp.asarray(x, STORAGE_DTYPES[storage])
    C, H, W = x.shape
    R, Cc = grid_shape(mesh)
    Hp, Wp = padded_extent(H, R), padded_extent(W, Cc)
    if (Hp, Wp) != (H, W):
        x = jnp.pad(x, ((0, 0), (0, Hp - H), (0, Wp - W)))
    x = jax.device_put(x, block_sharding(mesh))
    return x, (H, W), (Hp // R, Wp // Cc)


def reshard_prepared(xs, valid_hw, mesh: Mesh):
    """Move an already-prepared padded (C, Hp, Wp) array onto a DIFFERENT
    mesh: crop to the valid extent, re-pad to the new grid's block
    multiples, and re-shard (elastic recovery's in-memory counterpart of
    the checkpoint reshard — e.g. a serving engine shrinking mid-process
    without a disk round-trip).

    Bit-exact by the masking invariant: positions outside ``valid_hw``
    are zero on every grid, so crop + zero-re-pad reproduces exactly the
    state ``_prepare`` would have built on ``mesh`` from the valid
    pixels.  Compiled state for other meshes is untouched — the build
    caches key on the mesh, so swapping BACK later reuses the old
    executables.

    Materializes ONE host copy of the cropped state (a few MB at serving
    sizes); huge-image states should reshard through the checkpoint path
    instead (``utils.checkpoint.load_state``), which streams per-shard
    files and never holds the full image on one host.
    """
    H, W = (int(v) for v in valid_hw)
    R, Cc = grid_shape(mesh)
    Hp, Wp = padded_extent(H, R), padded_extent(W, Cc)
    x = xs[:, :H, :W]
    if (Hp, Wp) != (H, W):
        x = jnp.pad(x, ((0, 0), (0, Hp - H), (0, Wp - W)))
    return jax.device_put(np.asarray(x), block_sharding(mesh))


def _norm_tile(tile) -> tuple[int, int] | None:
    """Normalize a (TH, TW) kernel-tile override to a hashable tuple."""
    if tile is None:
        return None
    th, tw = (int(v) for v in tile)
    if th <= 0 or tw <= 0:
        raise ValueError(f"tile extents must be positive, got {(th, tw)}")
    return (th, tw)


def _storage_name(dtype) -> str:
    """The STORAGE_DTYPES name for an array dtype (default 'f32')."""
    for name, dt in STORAGE_DTYPES.items():
        if jnp.dtype(dt) == jnp.dtype(dtype):
            return name
    return "f32"


def _resolve_auto(mesh, filt, backend, fuse, tile, storage, quantize,
                  boundary, valid_hw, channels, check_every=None,
                  overlap=None, col_mode=None):
    """``backend='auto'`` -> concrete
    ``(backend, fuse, tile, overlap, col_mode, source)``.

    Resolution goes through the tuning subsystem (plan cache if a
    ``PCTPU_PLAN_FILE`` is armed, else the cost model) and happens
    BEFORE the resilience degrade walk — auto picks the tier, the
    fallback probe then guards the resolved launch exactly as it guards
    an explicitly-named one.  Explicit backends pass through untouched
    (``fuse=None`` then just normalizes to 1, the historical default;
    ``overlap`` stays as requested for :func:`resolve_overlap` to
    settle against the mesh).

    ``check_every`` (the convergence path only) is part of the tuning
    identity: it bounds the legal fusion depth (a chunk fuses at most
    its n-1 pre-pair iterations) and keys the plan cache, so a tuned
    convergence run resolves its own plan rather than a fixed-count one.
    """
    if backend != AUTO:
        return (backend, (1 if fuse is None else int(fuse)), tile, overlap,
                col_mode, None)
    from parallel_convolution_tpu import tuning

    res = tuning.resolve(
        mesh, filt, (channels, valid_hw[0], valid_hw[1]), storage=storage,
        quantize=quantize, boundary=boundary, fuse=fuse,
        tile=_norm_tile(tile), overlap=overlap, col_mode=col_mode,
        check_every=check_every)
    return (res.backend, res.fuse, res.tile, res.overlap, res.col_mode,
            res.source)


def _resolve_fallback(mesh, filt, backend, quantize, fuse, boundary, tile,
                      interior_split, storage="f32",
                      block_hw=None, overlap: bool = False,
                      col_mode: str = "packed") -> str:
    """Walk the degradation chain (resilience.degrade) for this config.

    ``block_hw``/``storage`` must describe the REAL run: kernel selection
    depends on both (e.g. pallas_rdma's tiled-vs-monolithic switch), so a
    probe on a different geometry or dtype could pass while the real
    launch crashes — exactly the gap this probe exists to close.
    ``overlap`` likewise: the overlapped RDMA program is a different
    kernel than the serialized one, so the probe must compile the same
    form (degrade clamps it per walked tier — only the RDMA tier has an
    overlapped form).
    """
    from parallel_convolution_tpu.resilience import degrade

    return degrade.resolve_backend(
        mesh, filt, backend, quantize=quantize, fuse=fuse, boundary=boundary,
        tile=tile, interior_split=interior_split, storage=storage,
        block_hw=block_hw, overlap=overlap, col_mode=col_mode)


def iterate_prepared(xs, filt: Filter, iters: int, mesh: Mesh,
                     valid_hw, quantize: bool = True,
                     backend: str = "shifted", fuse: int | None = 1,
                     boundary: str = "zero",
                     tile: tuple[int, int] | None = None,
                     interior_split: bool = False,
                     check_contract: bool = True,
                     fallback: bool = False,
                     overlap: bool | None = None,
                     col_mode: str | None = None):
    """Iterate an already-sharded padded (C, Hp, Wp) array in place(-ish).

    The zero-copy entry for huge images loaded via utils.sharded_io: input
    stays in its blocked sharding, output keeps the padded extent (pass it
    straight to ``save_sharded``).  The input array is donated.

    ``check_contract=False`` skips the quantize-range input check (one
    full-array reduction) — for loop callers like
    ``utils.checkpoint.run_checkpointed`` that validated the initial state
    once and whose chunk inputs are in contract by induction (quantized
    outputs are always in [0, 255]).

    ``fallback=True`` probes ``backend`` once per (mesh, config) per
    process BEFORE the real (donating) run and, on a classified-transient
    compile/launch failure, walks the degradation chain
    ``pallas_rdma → pallas → shifted`` (resilience.degrade) — emitting a
    BackendDegradedWarning rather than dying with the first failed tier.
    Probing first also means the donated input is never lost to a launch
    that was going to fail.

    ``backend="auto"`` resolves through the tuning subsystem first
    (plan cache, else cost model; ``fuse=None``/``tile=None`` are then
    tuned too, non-None values are pins) — the degrade walk below
    applies to the *resolved* backend.

    ``overlap`` selects the interior-first overlapped halo pipeline in
    the RDMA kernels (None = off for explicit backends, tuned for
    ``backend="auto"``); the resolved bool — clamped by
    :func:`resolve_overlap` and re-clamped to False if the degrade walk
    leaves the RDMA tier — is what actually compiles.

    ``col_mode`` selects the RDMA column-slab transport
    (packed | strided | auto; None = auto) — resolved by
    :func:`resolve_col_mode` (cost-model pick for the RDMA tier, inert
    'packed' elsewhere), re-clamped if the degrade walk leaves the tier.
    """
    if jnp.dtype(xs.dtype) == jnp.uint8 and not quantize:
        _check_storage("u8", quantize)  # public entry: same guard as above
    if check_contract:
        _check_quantize_contract(xs, filt, quantize)
    R, Cc = grid_shape(mesh)
    block_hw = (xs.shape[1] // R, xs.shape[2] // Cc)
    backend, fuse, tile, overlap, col_mode, _ = _resolve_auto(
        mesh, filt, backend, fuse, tile, _storage_name(xs.dtype), quantize,
        boundary, tuple(valid_hw), xs.shape[0], overlap=overlap,
        col_mode=col_mode)
    overlap = resolve_overlap(overlap, backend, mesh)
    col_mode = resolve_col_mode(col_mode, backend, mesh, block_hw,
                                filt.radius, fuse, _storage_name(xs.dtype))
    if fallback:
        backend = _resolve_fallback(mesh, filt, backend, quantize, fuse,
                                    boundary, _norm_tile(tile),
                                    interior_split,
                                    storage=_storage_name(xs.dtype),
                                    block_hw=block_hw, overlap=overlap,
                                    col_mode=col_mode)
        overlap = kernel_forms.clamp_overlap(overlap, backend)
        col_mode = clamp_col_mode(col_mode, backend)
    fn = _build_iterate(mesh, filt, iters, quantize, tuple(valid_hw),
                        block_hw, backend, fuse, boundary, _norm_tile(tile),
                        interior_split, overlap, col_mode)
    if not obs_metrics.enabled():
        return fn(xs)
    # Observed mode: attribute halo bytes/rounds and emit the exchange
    # event.  NO wall and NO fence: this entry dispatches asynchronously
    # (callers overlap the next chunk's work with device execution), and
    # adding a block_until_ready here would silently serialize them —
    # wall-based series come from the callers that already fence (bench,
    # serving, the convergence count readback).
    channels, shape = xs.shape[0], tuple(xs.shape)
    out = fn(xs)
    _record_step_obs(backend, mesh, block_hw, filt.radius,
                     max(1, min(fuse, iters or 1)), iters, channels,
                     _storage_name(out.dtype), boundary, None, shape,
                     quantize, _norm_tile(tile),
                     source="iterate_prepared", overlap=overlap,
                     col_mode=col_mode)
    return out


def sharded_iterate(x, filt: Filter, iters: int, mesh: Mesh | None = None,
                    quantize: bool = True, backend: str = "shifted",
                    storage: str = "f32", fuse: int | None = 1,
                    boundary: str = "zero",
                    tile: tuple[int, int] | None = None,
                    interior_split: bool = False,
                    fallback: bool = False,
                    overlap: bool | None = None,
                    col_mode: str | None = None):
    """Run ``iters`` stencil iterations of a global (C, H, W) f32 image
    sharded over the 2D mesh.  Returns the global (C, H, W) f32 result
    (bit-identical to the serial oracle for any mesh shape).

    ``storage='bf16'`` halves HBM/ICI traffic by carrying the state in
    bfloat16 between iterations — still bit-exact in quantize mode (u8
    values are exact in bf16); in float mode it is a documented
    precision/bandwidth trade.  ``tile=(TH, TW)`` overrides the Pallas
    kernels' VMEM output-tile shape (the scripts/tune_pallas.py knob);
    None = the per-kernel tuned default.

    ``quantize=True`` is the u8 store-back semantics and assumes pixel
    values in [0, 255] (a decoded u8 image): convex filters elide the
    provably-idle clamp (``Filter.convex``), so a float plane with
    out-of-range values is out of contract — it raises ValueError up
    front (``_check_quantize_contract``) rather than silently producing
    different bytes than the pre-elision code.
    """
    if mesh is None:
        mesh = make_grid_mesh()
    _check_storage(storage, quantize)
    xs, valid_hw, block_hw = _prepare(x, mesh, filt.radius, storage)
    out = iterate_prepared(xs, filt, iters, mesh, valid_hw,
                           quantize=quantize, backend=backend, fuse=fuse,
                           boundary=boundary, tile=tile,
                           interior_split=interior_split, fallback=fallback,
                           overlap=overlap, col_mode=col_mode)
    return out[:, : valid_hw[0], : valid_hw[1]].astype(jnp.float32)


def sharded_converge(x, filt: Filter, tol: float, max_iters: int,
                     check_every: int = 1, mesh: Mesh | None = None,
                     quantize: bool = False, backend: str = "shifted",
                     storage: str = "f32", boundary: str = "zero",
                     fuse: int | None = 1,
                     tile: tuple[int, int] | None = None,
                     interior_split: bool = False, fallback: bool = False,
                     overlap: bool | None = None, solver: str = "jacobi",
                     mg_levels: int | None = None,
                     col_mode: str | None = None):
    """Run-to-convergence (BASELINE config 5).  Returns (result, iters_run).

    ``fuse``/``tile`` mirror :func:`sharded_iterate`: fused chunks run
    between convergence checks (any fuse ≥ 1, any check_every), so config
    5 rides the same optimized kernels as the fixed-count path — including
    ``fallback=True`` backend degradation.

    ``solver="multigrid"`` dispatches to the geometric V-cycle
    (``solvers.multigrid.mg_converge``, lazily imported — the solver
    package imports this module): the returned count is then V-CYCLES
    run, ``max_iters`` bounds fine-grid work units, and ``check_every``
    is ignored (the cycle is the check cadence).  Same stopping measure
    either way: the max-abs change of one fine-grid sweep.
    """
    if solver == "multigrid":
        from parallel_convolution_tpu.solvers import multigrid

        out, res = multigrid.mg_converge(
            x, filt, tol=tol, max_iters=max_iters, mesh=mesh,
            quantize=quantize, backend=backend, storage=storage,
            boundary=boundary, fuse=fuse, tile=tile, fallback=fallback,
            overlap=overlap, mg_levels=mg_levels, col_mode=col_mode)
        return out, res.cycles
    if solver != "jacobi":
        from parallel_convolution_tpu.utils.config import SOLVERS

        raise ValueError(f"solver must be one of {SOLVERS}, got {solver!r}")
    if mesh is None:
        mesh = make_grid_mesh()
    _check_storage(storage, quantize)
    xs, valid_hw, block_hw = _prepare(x, mesh, filt.radius, storage)
    backend, fuse, tile, overlap, col_mode, _ = _resolve_auto(
        mesh, filt, backend, fuse, tile, storage, quantize, boundary,
        tuple(valid_hw), xs.shape[0], check_every=int(check_every),
        overlap=overlap, col_mode=col_mode)
    overlap = resolve_overlap(overlap, backend, mesh)
    col_mode = resolve_col_mode(col_mode, backend, mesh, block_hw,
                                filt.radius, int(fuse), storage)
    if fallback:
        backend = _resolve_fallback(mesh, filt, backend, quantize, fuse,
                                    boundary, _norm_tile(tile),
                                    interior_split, storage,
                                    block_hw=block_hw, overlap=overlap,
                                    col_mode=col_mode)
        overlap = kernel_forms.clamp_overlap(overlap, backend)
        col_mode = clamp_col_mode(col_mode, backend)
    _check_quantize_contract(xs, filt, quantize)
    fn = _build_converge(mesh, filt, float(tol), int(max_iters),
                         int(check_every), quantize, valid_hw, block_hw,
                         backend, boundary, int(fuse), _norm_tile(tile),
                         interior_split, overlap, col_mode)
    channels, shape = xs.shape[0], tuple(xs.shape)
    t0 = time.perf_counter()
    # The convergence run is fenced (the count readback), so it gets a
    # real device span: root of a fresh trace for a bare CLI call, child
    # of the caller's span when one is active.  record_step below then
    # hangs the model-attributed exchange/compute children off it.
    from parallel_convolution_tpu.obs import trace as obs_trace

    with obs_trace.span("device", source="sharded_converge",
                        backend=backend) as dsp:
        out, done = fn(xs)
        done = int(done)  # materializes the run (the convergence count)
        dsp.set(iters=done)
    if obs_metrics.enabled():
        with obs_trace.attach(dsp.context):
            _record_step_obs(backend, mesh, block_hw, filt.radius,
                             max(1, min(int(fuse),
                                        max(1, check_every - 1))),
                             done, channels, storage, boundary,
                             time.perf_counter() - t0, shape, quantize,
                             _norm_tile(tile), source="sharded_converge",
                             overlap=overlap, col_mode=col_mode)
    return out[:, : valid_hw[0], : valid_hw[1]].astype(jnp.float32), done


def sharded_converge_stream(x, filt: Filter, tol: float, max_iters: int,
                            check_every: int = 1, mesh: Mesh | None = None,
                            quantize: bool = False, backend: str = "shifted",
                            storage: str = "f32", boundary: str = "zero",
                            fuse: int | None = 1,
                            tile: tuple[int, int] | None = None,
                            interior_split: bool = False,
                            fallback: bool = False,
                            overlap: bool | None = None,
                            solver: str = "jacobi",
                            mg_levels: int | None = None,
                            col_mode: str | None = None):
    """Progressive run-to-convergence: a generator over snapshot chunks.

    Yields ``(image, iters_done, diff)`` after every ``check_every``-sized
    chunk — ``image`` is the (C, H, W) float32 field at the valid extent
    (a host copy, safe to keep), ``diff`` the max-abs single-iteration
    change that the convergence decision is made on.  The stream ends
    when ``diff < tol`` or ``iters_done >= max_iters``; the LAST yielded
    image is bit-identical to :func:`sharded_converge` with the same
    arguments (same chunk math, host-driven instead of ``while_loop`` —
    the per-chunk diff readback is the fence that makes the field
    observable, which is the point: a serving tier can stream best-so-far
    results out of a long job instead of holding an all-or-nothing
    deadline).

    ``solver="multigrid"`` yields one snapshot per V-CYCLE instead
    (``iters_done`` counts cycles; ``max_iters`` bounds fine-grid work
    units); callers that need the work-unit accounting per row use
    ``solvers.multigrid.mg_converge_stream`` directly, which this
    delegates to.
    """
    if solver == "multigrid":
        from parallel_convolution_tpu.solvers import multigrid

        for out, cycles, residual, _wu in multigrid.mg_converge_stream(
                x, filt, tol=tol, max_iters=max_iters, mesh=mesh,
                quantize=quantize, backend=backend, storage=storage,
                boundary=boundary, fuse=fuse, tile=tile, fallback=fallback,
                overlap=overlap, mg_levels=mg_levels, col_mode=col_mode):
            yield (out, cycles, residual)
        return
    if solver != "jacobi":
        from parallel_convolution_tpu.utils.config import SOLVERS

        raise ValueError(f"solver must be one of {SOLVERS}, got {solver!r}")
    if mesh is None:
        mesh = make_grid_mesh()
    _check_storage(storage, quantize)
    xs, valid_hw, block_hw = _prepare(x, mesh, filt.radius, storage)
    backend, fuse, tile, overlap, col_mode, _ = _resolve_auto(
        mesh, filt, backend, fuse, tile, storage, quantize, boundary,
        tuple(valid_hw), xs.shape[0], check_every=int(check_every),
        overlap=overlap, col_mode=col_mode)
    overlap = resolve_overlap(overlap, backend, mesh)
    col_mode = resolve_col_mode(col_mode, backend, mesh, block_hw,
                                filt.radius, int(fuse), storage)
    if fallback:
        backend = _resolve_fallback(mesh, filt, backend, quantize, fuse,
                                    boundary, _norm_tile(tile),
                                    interior_split, storage,
                                    block_hw=block_hw, overlap=overlap,
                                    col_mode=col_mode)
        overlap = kernel_forms.clamp_overlap(overlap, backend)
        col_mode = clamp_col_mode(col_mode, backend)
    _check_quantize_contract(xs, filt, quantize)
    check_every, max_iters = int(check_every), int(max_iters)
    done, diff = 0, float("inf")
    while done < max_iters and diff >= tol:
        n = min(check_every, max_iters - done)
        fn = _build_converge_chunk(mesh, filt, n, quantize, tuple(valid_hw),
                                   block_hw, backend, boundary, int(fuse),
                                   _norm_tile(tile), interior_split, overlap,
                                   col_mode)
        xs, d = fn(xs)
        diff = float(d)   # the readback fences the chunk
        done += n
        yield (np.asarray(xs[:, : valid_hw[0], : valid_hw[1]]
                          .astype(jnp.float32)), done, diff)
