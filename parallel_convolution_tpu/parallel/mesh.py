"""2D device-grid topology (reference component C4, SURVEY.md §2).

The reference maps P MPI ranks onto an R×C Cartesian grid with
``MPI_Dims_create`` + ``MPI_Cart_create`` and derives each rank's block and
neighbors.  On TPU the topology object is :class:`jax.sharding.Mesh`: XLA
knows the physical ICI graph, neighbor discovery is implicit in
``lax.ppermute`` index pairs, and block offsets fall out of the sharding.

Axis convention used across the package: mesh axes ``('x', 'y')`` shard the
planar image ``(C, H, W)`` as ``P(None, 'x', 'y')`` — 'x' splits rows (H),
'y' splits columns (W).
"""

from __future__ import annotations

import math

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("x", "y")


def dims_create(n: int) -> tuple[int, int]:
    """Near-square factorization of ``n`` — the MPI_Dims_create contract.

    Returns (R, C) with R*C == n and R <= C, R as close to sqrt(n) as the
    factorization allows.
    """
    if n < 1:
        raise ValueError("need at least one device")
    r = int(math.isqrt(n))
    while n % r:
        r -= 1
    return r, n // r


def make_grid_mesh(
    devices=None, shape: tuple[int, int] | None = None
) -> Mesh:
    """Build the 2D ('x', 'y') mesh — the MPI_Cart_create equivalent.

    ``shape`` defaults to :func:`dims_create` over all available devices.
    Device order follows ``jax.devices()`` reshaped row-major, which on real
    TPU slices keeps mesh neighbors ICI neighbors for the common topologies
    (use ``jax.experimental.mesh_utils`` for exotic slices).
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if shape is None:
        shape = dims_create(len(devices))
    r, c = shape
    if r * c != len(devices):
        raise ValueError(f"mesh shape {shape} != {len(devices)} devices")
    arr = np.empty((r, c), dtype=object)
    for i, d in enumerate(devices):
        arr[i // c, i % c] = d
    return Mesh(arr, AXES)


def mesh_from_spec(spec: str | None) -> Mesh:
    """Build the mesh a CLI ``--mesh`` flag names: ``"RxC"`` takes the
    first R*C devices; None/empty falls back to the supervisor's reshape
    env (``PCTPU_MESH``, resilience.elastic) and then to all devices
    near-square.  The ONE parser for this grammar (cli.py,
    scripts/serve.py, scripts/loadgen.py all route here, so the entry
    points cannot drift — and a reshape-aware supervised leg can re-grid
    ANY of them through the env without argv edits)."""
    if not spec:
        import os

        from parallel_convolution_tpu.resilience import elastic

        spec = os.environ.get(elastic.MESH_ENV)
    if not spec:
        return make_grid_mesh()
    try:
        r, c = (int(v) for v in spec.lower().split("x"))
    except ValueError as e:
        raise ValueError(f"mesh spec must be RxC, got {spec!r}") from e
    return make_grid_mesh(jax.devices()[: r * c], (r, c))


def block_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding of a planar (C, H, W) image over the grid: P(None, 'x', 'y')."""
    return NamedSharding(mesh, P(None, *AXES))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def grid_shape(mesh: Mesh) -> tuple[int, int]:
    return mesh.shape[AXES[0]], mesh.shape[AXES[1]]


def padded_extent(total: int, parts: int) -> int:
    """Smallest multiple of ``parts`` ≥ ``total``.

    shard_map needs equal per-device blocks; the reference simply required
    divisible dimensions, this framework pads and masks instead
    (SURVEY.md §7 hard parts: 2520 does not divide by every mesh shape).
    """
    return -(-total // parts) * parts
