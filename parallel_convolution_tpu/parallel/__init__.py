"""Distributed layer: mesh topology, halo exchange, sharded iteration.

The reference fuses communication and compute inside one per-iteration MPI
loop (SURVEY.md §1); here they are separate composable pieces — ``mesh.py``
(topology ≙ MPI_Cart_create), ``halo.py`` (ghost exchange ≙ MPI_Isend/Irecv),
``step.py`` (iteration + convergence ≙ the main loop + MPI_Allreduce) — and
XLA fuses them back together at compile time.
"""
