"""Command-line entrypoint (reference component C12, SURVEY.md §2).

Mirrors the reference's argv vocabulary — ``image path, rows, cols, loops,
grey|rgb`` — and replaces its ad-hoc workflow (qsub scripts + manual ``cmp``
of raw outputs) with subcommands:

  run       filter a raw image on the TPU mesh (the parallel main())
  serial    same via the NumPy oracle (the serial main(); golden path)
  generate  create a deterministic test image (the bundled-waterfall analog)
  compare   byte-compare two raw images (the reference's validation step)
  convert   raw -> PGM/PPM for visual inspection
  bench     time a synthetic workload, print one JSON row (MPI_Wtime tier)
  info      devices / mesh / filters at a glance
"""

from __future__ import annotations

import argparse
import sys

import numpy as np


def _add_image_args(p: argparse.ArgumentParser) -> None:
    p.add_argument("image", help="input .raw image path")
    p.add_argument("rows", type=int)
    p.add_argument("cols", type=int)
    p.add_argument("loops", type=int)
    p.add_argument("mode", choices=["grey", "rgb"])


def _add_perf_args(p: argparse.ArgumentParser) -> None:
    """Filter/mesh/kernel knobs shared by the run and bench subcommands."""
    # Choices come from the canonical jax-free registries so a new backend
    # or storage tier lands in the CLI without a second edit.
    from parallel_convolution_tpu.utils.config import (
        BACKEND_CHOICES, STORAGES,
    )

    p.add_argument("--filter", default="blur3", dest="filter_name")
    p.add_argument("--mesh", default=None,
                   help="RxC grid, e.g. 2x4 (default: $PCTPU_MESH if set "
                        "— the supervisor's reshape env — else all "
                        "devices).  A --checkpoint resume accepts a "
                        "DIFFERENT grid than the one that wrote the "
                        "snapshot: shards reshard transparently, bytes "
                        "are unchanged (elastic recovery)")
    p.add_argument("--backend", default=None, choices=list(BACKEND_CHOICES),
                   help="correlate implementation (default: shifted, the "
                        "normative XLA path).  'auto' resolves backend — "
                        "and any of --fuse/--tile left unset — through "
                        "the tuning subsystem: plan cache "
                        "(PCTPU_PLAN_FILE / scripts/tune.py --emit-plans) "
                        "when present, else the roofline cost model; "
                        "bits are identical to naming the resolved "
                        "backend explicitly")
    p.add_argument("--storage", default=None, choices=list(STORAGES),
                   help="iteration-carry dtype (default: f32); narrower "
                        "carries shrink HBM/ICI traffic and stay "
                        "bit-exact for u8 images")
    p.add_argument("--fuse", type=int, default=None, metavar="T",
                   help="iterations per halo exchange (temporal fusion; "
                        "default 1).  All backends, pallas_rdma included: "
                        "there the T*r-deep exchange AND the T levels run "
                        "inside one kernel (needs blocks >= radius*T)")
    p.add_argument("--tile", default=None, metavar="TH,TW",
                   help="Pallas kernel output-tile override, e.g. "
                        "1024,512 (default: per-kernel tuned value; "
                        "results are bit-identical for any tile)")
    p.add_argument("--overlap", default="auto",
                   choices=["auto", "on", "off"],
                   help="interior-first overlapped halo pipeline in the "
                        "RDMA kernels: ghost-band DMAs fly while the "
                        "block interior computes, receive waits retire "
                        "just before the rim (bit-identical to the "
                        "serialized order).  'auto' = off for explicit "
                        "backends, cost-model-decided for --backend "
                        "auto; 'on' is a request clamped to legality "
                        "(RDMA tier, compiled Pallas) — the RESOLVED "
                        "knob is what rows and summaries report")
    p.add_argument("--col-mode", default="auto", dest="col_mode",
                   choices=["auto", "packed", "strided"],
                   help="RDMA column-slab transport (round 16): "
                        "'packed' stages the strided slab through a "
                        "contiguous buffer and moves ONE dense RDMA, "
                        "'strided' issues the direct strided copy — "
                        "bit-identical either way; 'auto' lets the "
                        "cost model pick per (dtype, block, radius).  "
                        "Inert (normalized to 'packed') off the RDMA "
                        "tier; rows and responses stamp the RESOLVED "
                        "value")
    p.add_argument("--interior-split", action="store_true",
                   dest="interior_split",
                   help="unmasked-interior launch split for fused Pallas "
                        "backends: per-device edge-class launches skip "
                        "ghost-ring masking on provably-interior tiles "
                        "(bit-identical; no-op for fuse=1 and periodic)")
    p.add_argument("--fallback", action="store_true",
                   help="graceful backend degradation: probe the backend "
                        "once and walk pallas_rdma -> pallas -> shifted "
                        "on a transient compile/launch failure instead of "
                        "dying (the effective backend is printed/stamped)")
    p.add_argument("--fast", action="store_true",
                   help="on a TPU, fill any knob NOT explicitly passed "
                        "with the measured flagship family "
                        "(pallas_sep/bf16/fuse 32, BASELINE.md; fuse "
                        "clamped to the per-device block).  Off-TPU the "
                        "compiled XLA path is already the fast one, so "
                        "unset knobs keep their normal defaults.  "
                        "Explicit flags always win; output bits are "
                        "identical either way.  The resolved knobs are "
                        "printed — pass them explicitly when resuming a "
                        "checkpoint on different hardware")


def _resolve_perf_knobs(args, mesh) -> None:
    """Fill backend/storage/fuse (argparse default None = not passed).

    --fast on a TPU resolves unset knobs to the measured flagship family
    (BASELINE.md: pallas_sep / bf16 / fuse 32, with fuse clamped so
    blocks stay >= radius*fuse) and prints the resolution — checkpoint
    resume keys on these values, so a resume on different hardware needs
    them passed explicitly.  Explicit flags always win (None-sentinel,
    not value comparison: an explicit `--fuse 1` stays unfused).  Off-TPU
    the Pallas kernels run under the interpreter — far slower than
    compiled XLA — so --fast leaves unset knobs at the normal defaults.
    All combinations are bit-identical; knobs change speed, never bytes.

    Must run after the platform is settled (on_tpu touches jax.devices,
    which the bench path guards behind ensure_live_backend).
    """
    from parallel_convolution_tpu.utils.platform import on_tpu

    if getattr(args, "fast", False) and on_tpu():
        from parallel_convolution_tpu.ops.filters import get_filter
        from parallel_convolution_tpu.parallel.mesh import grid_shape

        if args.backend is None:
            args.backend = "pallas_sep"
        if args.storage is None:
            # Multigrid carries signed float residual/correction fields
            # (mg_converge rejects anything but f32) — --fast only
            # upgrades storage for the plain iterate/jacobi paths.
            args.storage = ("f32" if getattr(args, "solver", "jacobi")
                            == "multigrid" else "bf16")
        if args.fuse is None:
            R, C = grid_shape(mesh)
            block = min(-(-args.rows // R), -(-args.cols // C))
            r = get_filter(args.filter_name).radius
            args.fuse = max(1, min(32, block // max(1, r)))
        print(f"# --fast resolved: backend={args.backend} "
              f"storage={args.storage} fuse={args.fuse}", file=sys.stderr)
    if args.backend is None:
        args.backend = "shifted"
    if args.storage is None:
        args.storage = "f32"
    if args.fuse is None and args.backend != "auto":
        # backend='auto' keeps the None: it means 'tune the depth too'
        # (resolved with the backend through the plan cache/cost model).
        args.fuse = 1
    # --overlap: 'auto' -> None (off for explicit backends, tuned for
    # backend='auto'); on/off -> a clamped request (resolve_overlap).
    args.overlap = {"auto": None, "on": True, "off": False}[
        getattr(args, "overlap", "auto")]
    # --col-mode: 'auto' -> None (cost-model pick; resolve_col_mode).
    cm = getattr(args, "col_mode", "auto")
    args.col_mode = None if cm == "auto" else cm


def _run_volume(args, mesh) -> int:
    """The ``--rank 3`` arm of ``run``: the input file is raw float32
    ``(2, D, rows, cols)`` bytes (two interleaved fields — u/v for
    Gray–Scott, u/u_prev for wave, field+rhs for the FD forms), the
    output the same layout after ``loops`` sweeps (or a ``--converge``
    run).  Volumes stay float end-to-end — no u8 quantization."""
    from parallel_convolution_tpu.utils.config import (
        VOLUME_FIELDS, VOLUME_SMOOTH_FORMS, VOLUME_PHYSICS_FORMS,
    )
    from parallel_convolution_tpu.volumes import driver

    if args.depth is None or args.depth < 1:
        print("--rank 3 requires --depth D (the resident volume depth)",
              file=sys.stderr)
        return 2
    known = VOLUME_SMOOTH_FORMS + VOLUME_PHYSICS_FORMS
    if args.filter_name not in known:
        print(f"--rank 3 --filter must name a rank-3 form "
              f"({', '.join(known)}), got {args.filter_name!r}",
              file=sys.stderr)
        return 2
    if args.solver != "jacobi":
        print(f"--rank 3 supports --solver jacobi only (got "
              f"{args.solver}): rank-3 multigrid transfer ships as "
              "registry forms, not a CLI solver", file=sys.stderr)
        return 2
    want = (VOLUME_FIELDS, args.depth, args.rows, args.cols)
    raw = np.fromfile(args.image, dtype=np.float32)
    if raw.size != int(np.prod(want)):
        print(f"{args.image}: {raw.size} f32 values, expected "
              f"{int(np.prod(want))} for {want}", file=sys.stderr)
        return 2
    vol = raw.reshape(want)
    fuse = max(1, args.fuse or 1)
    r, c = mesh.shape["x"], mesh.shape["y"]
    if args.converge is not None:
        out, iters, diff = driver.volume_converge(
            vol, args.filter_name, tol=args.converge,
            max_iters=args.loops, check_every=args.check_every,
            mesh=mesh, boundary=args.boundary, fuse=fuse)
        np.ascontiguousarray(out, dtype=np.float32).tofile(args.output)
        print(f"volume converged after {iters} iters (diff {diff:.3g}, "
              f"tol {args.converge}) on {r}x{c} mesh -> {args.output}")
        return 0
    out = driver.volume_iterate(vol, args.filter_name, args.loops,
                                mesh=mesh, boundary=args.boundary,
                                fuse=fuse)
    np.ascontiguousarray(out, dtype=np.float32).tofile(args.output)
    print(f"ran {args.loops} x {args.filter_name} on "
          f"{args.depth}x{args.rows}x{args.cols} volume, {r}x{c} mesh "
          f"-> {args.output}")
    return 0


def _mesh_from_flag(spec: str | None):
    from parallel_convolution_tpu.parallel.mesh import mesh_from_spec

    # An unset --mesh falls back to the supervisor's reshape env
    # (PCTPU_MESH) inside mesh_from_spec — every entry point that routes
    # here inherits elastic re-gridding for free.
    return mesh_from_spec(spec)


def main(argv: list[str] | None = None) -> int:
    from parallel_convolution_tpu.resilience import diskio, faults
    from parallel_convolution_tpu.utils.config import BOUNDARIES, SOLVERS
    from parallel_convolution_tpu.utils.platform import apply_platform_env

    apply_platform_env()
    # Honor PCTPU_FAULTS / PCTPU_DISK_MODES so injected-fault drills run
    # end-to-end through the real CLI (no-op unless the env vars are set).
    faults.install_from_env()
    diskio.install_from_env()
    ap = argparse.ArgumentParser(prog="pconv-tpu", description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)

    run = sub.add_parser("run", help="distributed filtering on the TPU mesh")
    _add_image_args(run)
    run.add_argument("-o", "--output", required=True)
    _add_perf_args(run)
    run.add_argument("--boundary", default="zero",
                     choices=list(BOUNDARIES),
                     help="edge handling: zero ghost ring (the reference) "
                          "or periodic torus wrap")
    run.add_argument("--rank", type=int, default=2, choices=[2, 3],
                     help="workload rank: 2 = u8 images (the default), "
                          "3 = (2, D, rows, cols) raw float32 volumes "
                          "(two interleaved fields) through the rank-3 "
                          "registry forms — fd7/fd25 FD Laplacians, "
                          "wave leapfrog, Gray-Scott reaction-diffusion")
    run.add_argument("--depth", type=int, default=None, metavar="D",
                     help="volume depth (required with --rank 3): the "
                          "resident D axis; rows/cols shard on the mesh")
    run.add_argument("--converge", type=float, default=None, metavar="TOL",
                     help="run to convergence (loops becomes max iters)")
    run.add_argument("--solver", default="jacobi", choices=list(SOLVERS),
                     help="convergence strategy (with --converge): plain "
                          "jacobi sweeps, or the geometric multigrid "
                          "V-cycle (same stopping measure, orders of "
                          "magnitude fewer fine-grid work units)")
    run.add_argument("--mg-levels", type=int, default=None, metavar="N",
                     help="multigrid level-count cap (default: coarsen "
                          "to the planner's floor)")
    run.add_argument("--check-every", type=int, default=10)
    run.add_argument("--sharded-io", action="store_true",
                     help="block-stream the image between disk and devices "
                          "(huge images; never materializes on one host)")
    run.add_argument("--checkpoint", default=None, metavar="DIR",
                     help="snapshot state every --checkpoint-every iters "
                          "and auto-resume from DIR")
    run.add_argument("--checkpoint-every", type=int, default=100)

    ser = sub.add_parser("serial", help="serial baseline (golden reference)")
    _add_image_args(ser)
    ser.add_argument("-o", "--output", required=True)
    ser.add_argument("--filter", default="blur3", dest="filter_name")
    ser.add_argument("--impl", default="auto",
                     choices=["auto", "oracle", "native"],
                     help="auto = native C++ when built (bit-identical), "
                          "else the NumPy oracle")

    gen = sub.add_parser("generate", help="write a deterministic test image")
    gen.add_argument("output")
    gen.add_argument("rows", type=int)
    gen.add_argument("cols", type=int)
    gen.add_argument("mode", choices=["grey", "rgb"])
    gen.add_argument("--seed", type=int, default=0)

    cmp_ = sub.add_parser("compare", help="byte-compare two raw images")
    cmp_.add_argument("a")
    cmp_.add_argument("b")

    conv_ = sub.add_parser(
        "convert", help="raw -> PGM/PPM for visual inspection (no deps)"
    )
    conv_.add_argument("image")
    conv_.add_argument("rows", type=int)
    conv_.add_argument("cols", type=int)
    conv_.add_argument("mode", choices=["grey", "rgb"])
    conv_.add_argument("-o", "--output", required=True,
                       help=".pgm (grey) or .ppm (rgb) path")

    bench_p = sub.add_parser(
        "bench", help="time a synthetic workload; one JSON row to stdout"
    )
    bench_p.add_argument("rows", type=int)
    bench_p.add_argument("cols", type=int)
    bench_p.add_argument("loops", type=int)
    bench_p.add_argument("mode", choices=["grey", "rgb"])
    _add_perf_args(bench_p)
    bench_p.add_argument("--reps", type=int, default=3,
                         help="timing repetitions (min 1)")

    sub.add_parser("info", help="devices, default mesh, filters")

    args = ap.parse_args(argv)

    from parallel_convolution_tpu.utils import imageio

    if args.cmd == "generate":
        img = imageio.generate_test_image(args.rows, args.cols, args.mode,
                                          seed=args.seed)
        imageio.write_raw(args.output, img)
        print(f"wrote {args.output}: {args.rows}x{args.cols} {args.mode}")
        return 0

    if args.cmd == "compare":
        a = np.fromfile(args.a, dtype=np.uint8)
        b = np.fromfile(args.b, dtype=np.uint8)
        if a.shape == b.shape and np.array_equal(a, b):
            print("identical")
            return 0
        if a.shape != b.shape:
            print(f"size mismatch: {a.size} vs {b.size} bytes")
        else:
            n = int((a != b).sum())
            print(f"differ: {n} bytes ({100.0 * n / a.size:.4f}%), "
                  f"max delta {int(np.abs(a.astype(int) - b.astype(int)).max())}")
        return 1

    if args.cmd == "convert":
        img = imageio.read_raw(args.image, args.rows, args.cols, args.mode)
        magic = b"P5" if args.mode == "grey" else b"P6"
        with open(args.output, "wb") as f:
            f.write(magic + b"\n%d %d\n255\n" % (args.cols, args.rows))
            f.write(np.ascontiguousarray(img).tobytes())
        print(f"wrote {args.output} ({'PGM' if args.mode == 'grey' else 'PPM'})")
        return 0

    if args.cmd == "info":
        import jax
        from parallel_convolution_tpu.ops.filters import FILTERS
        from parallel_convolution_tpu.parallel.mesh import dims_create
        from parallel_convolution_tpu.utils.config import (
            BACKENDS, BOUNDARIES, STORAGES,
        )

        devs = jax.devices()
        print(f"backend: {jax.default_backend()}  devices: {len(devs)}")
        for d in devs[:8]:
            print(f"  {d}")
        print(f"default mesh: {dims_create(len(devs))}")
        print(f"filters: {', '.join(sorted(FILTERS))}")
        print(f"backends: {', '.join(BACKENDS)}")
        print(f"storages: {', '.join(STORAGES)}  "
              f"boundaries: {', '.join(BOUNDARIES)}")
        print("perf knobs: --fuse T, --tile TH,TW, --interior-split, "
              "--fast (measured flagship preset)")
        return 0

    if args.cmd == "serial":
        from parallel_convolution_tpu.ops import oracle
        from parallel_convolution_tpu.ops.filters import get_filter

        img = imageio.read_raw(args.image, args.rows, args.cols, args.mode)
        filt = get_filter(args.filter_name)
        impl = args.impl
        if impl in ("auto", "native"):
            try:
                from parallel_convolution_tpu.native import serial_native

                out = serial_native.run_serial_u8(img, filt, args.loops)
                impl = "native"
            except Exception:
                if impl == "native":
                    raise
                impl = "oracle"
        if impl == "oracle":
            out = oracle.run_serial_u8(img, filt, args.loops)
        imageio.write_raw(args.output, out)
        print(f"serial[{impl}]: {args.loops} x {args.filter_name} "
              f"-> {args.output}")
        return 0

    # run / bench share the tile flag; mesh construction stays inside
    # each branch (bench must not touch jax.devices() before its
    # dead-tunnel guard has settled the platform).
    tile = None
    if getattr(args, "tile", None):
        try:
            tile = tuple(int(v) for v in args.tile.split(","))
            if len(tile) != 2 or min(tile) <= 0:
                raise ValueError
        except ValueError:
            ap.error(f"--tile must be TH,TW positive ints, got {args.tile!r}")

    if args.cmd == "bench":
        import json

        from parallel_convolution_tpu.ops.filters import get_filter
        from parallel_convolution_tpu.utils import bench as bench_lib
        from parallel_convolution_tpu.utils.platform import (
            enable_compile_cache, ensure_live_backend,
        )

        # Same dead-tunnel guard as the driver bench.py: a benchmark
        # that hangs forever on backend init is worse than a labeled
        # CPU fallback row.
        note = ensure_live_backend()
        enable_compile_cache()
        mesh = _mesh_from_flag(args.mesh)
        _resolve_perf_knobs(args, mesh)
        row = bench_lib.bench_iterate(
            (args.rows, args.cols), get_filter(args.filter_name),
            args.loops, mesh=mesh,
            channels=3 if args.mode == "rgb" else 1,
            interior_split=args.interior_split,
            backend=args.backend, storage=args.storage, fuse=args.fuse,
            reps=args.reps, tile=tile, fallback=args.fallback,
            overlap=args.overlap, col_mode=args.col_mode,
        )
        if note:
            row["platform_note"] = note
        print(json.dumps(row))
        return 0

    # run
    from parallel_convolution_tpu.models import ConvolutionModel, JacobiSolver

    mesh = _mesh_from_flag(args.mesh)
    _resolve_perf_knobs(args, mesh)
    if getattr(args, "rank", 2) == 3:
        return _run_volume(args, mesh)
    if args.solver != "jacobi" and args.converge is None:
        print(f"--solver {args.solver} requires --converge TOL: without "
              "it the run is a fixed-count iterate and the solver choice "
              "would be silently ignored", file=sys.stderr)
        return 2
    if args.solver == "multigrid" and args.storage != "f32":
        print(f"--solver multigrid requires --storage f32 (got "
              f"{args.storage}): residual/correction fields need full "
              "float carries", file=sys.stderr)
        return 2
    if args.converge is not None:
        mg = args.solver == "multigrid"
        solver = JacobiSolver(
            filt=args.filter_name, tol=args.converge, max_iters=args.loops,
            check_every=args.check_every, mesh=mesh, backend=args.backend,
            # Multigrid carries signed float residual/correction fields —
            # the u8 store-back would clamp the error equation (typed
            # ValueError in mg_converge); jacobi keeps the historical
            # quantized semantics.
            quantize=not mg, fuse=args.fuse, tile=tile,
            boundary=args.boundary, storage=args.storage,
            interior_split=args.interior_split, overlap=args.overlap,
            col_mode=args.col_mode,
            solver=args.solver, mg_levels=args.mg_levels,
        )
        img = imageio.read_raw(args.image, args.rows, args.cols, args.mode)
        x = imageio.interleaved_to_planar(img).astype(np.float32)
        out, iters = solver.solve(x)
        imageio.write_raw(
            args.output,
            imageio.planar_to_interleaved(
                np.clip(np.rint(out), 0, 255).astype(np.uint8)),
        )
        if mg and solver.last_mg is not None:
            res = solver.last_mg
            print(f"converged after {res.cycles} V-cycles "
                  f"({res.work_units} fine-grid work units, "
                  f"{res.levels} levels {res.level_shapes}, "
                  f"residual {res.residual:.3g}, tol {args.converge}) "
                  f"-> {args.output}")
        else:
            print(f"converged after {iters} iters (tol {args.converge}) "
                  f"-> {args.output}")
        return 0

    model = ConvolutionModel(filt=args.filter_name, mesh=mesh,
                             backend=args.backend, storage=args.storage,
                             fuse=args.fuse, boundary=args.boundary,
                             tile=tile,
                             interior_split=args.interior_split,
                             overlap=args.overlap,
                             col_mode=args.col_mode,
                             fallback=args.fallback)
    if args.checkpoint:
        from parallel_convolution_tpu.parallel import step as step_lib
        from parallel_convolution_tpu.utils import checkpoint, sharded_io

        xs = sharded_io.load_sharded(args.image, args.rows, args.cols,
                                     args.mode, mesh)
        out = checkpoint.run_checkpointed(
            xs, model.filt, args.loops, mesh, (args.rows, args.cols),
            ckpt_dir=args.checkpoint, every=args.checkpoint_every,
            backend=args.backend, fuse=args.fuse, boundary=args.boundary,
            tile=tile, interior_split=args.interior_split,
            fallback=args.fallback, overlap=args.overlap,
            col_mode=args.col_mode,
        )
        sharded_io.save_sharded(args.output, out, args.rows, args.cols,
                                args.mode)
        if args.fallback:
            # run_checkpointed resolved per chunk inside iterate_prepared;
            # surface the process's last resolution so a degraded run is
            # labeled in the summary line, not only on stderr.
            from parallel_convolution_tpu.resilience import degrade

            req = args.backend
            if req == "auto":
                # The degrade walk saw the RESOLVED tier, never 'auto'.
                from parallel_convolution_tpu import tuning

                last = tuning.last_resolution()
                req = last.backend if last else req
            model.effective_backend = degrade.effective_for(req) or req
    elif args.sharded_io:
        model.run_raw_file_sharded(args.image, args.output, args.rows,
                                   args.cols, args.mode, args.loops)
    else:
        model.run_raw_file(args.image, args.output, args.rows, args.cols,
                           args.mode, args.loops)
    r, c = mesh.shape["x"], mesh.shape["y"]
    eff = getattr(model, "effective_backend", None) or args.backend
    if args.backend == "auto":
        # Auto-resolved, not degraded: label the tier AND where the plan
        # came from (measured|interpolated|predicted) so a mistune or a
        # missing plan file is visible in the summary line.  The
        # checkpoint branch resolves inside iterate_prepared (the model
        # object never runs), so fall back to the process's last
        # resolution for both pieces.
        from parallel_convolution_tpu import tuning

        last = tuning.last_resolution()
        src = getattr(model, "plan_source", "explicit")
        if src == "explicit" and last is not None:
            src = last.source
        if eff == "auto" and last is not None:
            eff = last.backend
        label = f"auto resolved to {eff} [{src}]"
    else:
        label = (args.backend if eff == args.backend
                 else f"{args.backend} degraded to {eff}")
    if getattr(model, "effective_overlap", None):
        label += ", overlapped halo pipeline"
    if getattr(model, "effective_col_mode", None) == "strided":
        label += ", strided column RDMA"
    print(f"ran {args.loops} x {args.filter_name} on {r}x{c} mesh "
          f"({label}) -> {args.output}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
