"""``python -m parallel_convolution_tpu`` → the pconv-tpu CLI (cli.main)."""

import sys

from parallel_convolution_tpu.cli import main

if __name__ == "__main__":
    sys.exit(main())
