"""parallel_convolution_tpu — a TPU-native iterative 2D stencil framework.

A ground-up re-design of the capabilities of ``jimouris/parallel-convolution``
(C + MPI + OpenMP iterative image convolution) for TPU hardware:

* the ``MPI_Cart_create`` R×C process grid  → a 2D :class:`jax.sharding.Mesh`
* ``MPI_Isend/Irecv`` ghost-row/column halo → :func:`jax.lax.ppermute`
  (XLA ``collective-permute`` over ICI)
* the OpenMP per-tile convolution loop      → a Pallas 2D stencil kernel
* ``MPI_Allreduce`` convergence check       → :func:`jax.lax.psum`

See ``SURVEY.md`` at the repo root for the structural map of the reference
(component inventory C1–C13) and how each maps onto this package.

Layout
------
``ops/``       filters (C3), NumPy oracle (C1/C2), lax reference conv, Pallas
               stencil kernels (C2).
``parallel/``  mesh topology (C4), ppermute halo exchange (C5), the jitted
               iteration step with double buffering + convergence (C6/C8).
``models/``    end-to-end pipelines: the flagship distributed ConvolutionModel
               and the Jacobi run-to-convergence solver.
``serving/``   the long-lived service tier: warm-executable cache,
               micro-batching, admission control, HTTP/in-process fronts.
``utils/``     raw image I/O (C7), benchmark timers (C10), tracing, config.
``cli.py``     command-line entrypoint mirroring the reference's argv
               vocabulary (C12).
"""

from parallel_convolution_tpu.ops.filters import FILTERS, Filter, get_filter
from parallel_convolution_tpu.ops import oracle

__version__ = "0.1.0"

__all__ = ["Filter", "get_filter", "FILTERS", "oracle", "ConvolutionModel",
           "JacobiSolver", "RunConfig", "__version__"]


def __getattr__(name: str):
    # Lazy: models pull in the full jax/parallel stack; keep bare imports
    # of the package cheap.
    if name in ("ConvolutionModel", "JacobiSolver"):
        from parallel_convolution_tpu import models

        return getattr(models, name)
    if name == "RunConfig":
        from parallel_convolution_tpu.utils.config import RunConfig

        return RunConfig
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
