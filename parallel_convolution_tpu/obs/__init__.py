"""Unified observability layer: metrics registry, event log, attribution.

One telemetry spine for the whole stack (round 11).  Three parts:

* :mod:`obs.metrics` — process-global typed counters / gauges /
  fixed-bucket histograms with labeled series; ``snapshot()`` for the
  in-process client, Prometheus text via ``render_text()`` (served at
  ``/metrics`` on the HTTP frontend).  ``PCTPU_OBS=0`` turns every
  mutator into a one-branch no-op (perf-tested).
* :mod:`obs.events` — append-only JSONL structured event log (monotonic
  ``seq``, wall+perf clocks, typed kinds) with atomic rotation;
  installed process-globally from ``PCTPU_OBS_EVENTS`` so drills leave a
  replayable timeline instead of scattered warnings.
* :mod:`obs.attribution` — analytic per-direction halo-byte accounting
  and the roofline exchange-vs-compute split, the instrumentation the
  overlapped-halo and topology roadmap items are judged against.
* :mod:`obs.trace` — causal request tracing (round 13):
  trace_id/span_id/parent_id spans emitted as ``span`` events into the
  same log, propagated across transports via ``traceparent`` strings;
  ``scripts/trace_report.py`` reconstructs per-request trees, batch
  critical paths, and Chrome ``trace_event`` JSON, and
  ``scripts/perf_gate.py`` is the perf-regression sentry over
  ``evidence/perf_history.jsonl``.

``scripts/obs_report.py`` folds an event log + metrics snapshot into the
human summary (per-phase quantiles, exchange fraction per backend,
retry/degrade/quarantine totals, predicted-vs-measured drift per plan
key).

Import discipline: ``obs.metrics``/``obs.events`` are stdlib-only and
jax-free — safe to import from ``resilience.faults``-class modules that
must stay cheap.  ``obs.attribution`` additionally pulls the (jax-free)
tuning cost model.
"""

from parallel_convolution_tpu.obs import events, metrics, trace

__all__ = ["attribution", "events", "metrics", "trace"]


def __getattr__(name):
    # attribution imports tuning (heavier); load it on first touch so
    # `from parallel_convolution_tpu.obs import metrics` stays light.
    if name == "attribution":
        import importlib

        return importlib.import_module(
            "parallel_convolution_tpu.obs.attribution")
    raise AttributeError(name)
