"""Append-only JSONL structured event log with atomic rotation.

Every drill (``soak.py``, supervisor legs, serving smokes) previously
left its story scattered across warnings, per-leg ``.out`` files, and
stderr.  The event log is the replayable timeline: one JSONL line per
event, every line carrying

* ``seq``  — per-WRITER monotonic sequence number (each process is its
  own stream, identified by the ``pid`` field — a gap within one pid's
  stream = lost line; interleaved pids are expected when supervisor and
  leg children share one ``PCTPU_OBS_EVENTS`` path);
* ``ts``   — wall clock (``time.time()``, for humans and cross-process
  merging);
* ``perf`` — ``time.perf_counter()`` (monotonic, for intra-process
  deltas that wall-clock steps can't corrupt);
* ``kind`` — one of :data:`KINDS`, the typed vocabulary below;
* free-form event fields (JSON-safe scalars/lists/dicts).

Rotation is atomic: when the live file would exceed ``max_bytes`` the
writer renames it to ``<path>.1`` (shifting older generations up, oldest
dropped) via ``os.replace`` and starts fresh — a reader never observes a
half-rotated file, and ``seq`` continues across generations so the
stitched timeline stays gap-checkable.

Module-level :func:`emit` consults the process-global log exactly like
``resilience.faults.fault_point`` consults its plan: with no log
installed (or obs disabled, ``PCTPU_OBS=0``) it is one global load and a
test — free on hot paths.  Entry points install a log from the
``PCTPU_OBS_EVENTS`` env (a path) via :func:`install_from_env`.

stdlib-only, jax-free.
"""

from __future__ import annotations

import json
import os
import threading
import time
from pathlib import Path

from parallel_convolution_tpu.obs import metrics as _metrics
from parallel_convolution_tpu.resilience import diskio as _diskio
from parallel_convolution_tpu.resilience.faults import InjectedFault

# Reentrancy guard for the ``events_emit`` fault site: the fault plan
# itself emits a ``fault_trigger`` event when a site fires, so the
# inner emit must NOT consult again — under ``events_emit:*`` that
# would recurse without bound.  Thread-local because two threads'
# emits are independent consults.
_EMIT_GUARD = threading.local()

__all__ = [
    "EVENTS_ENV", "EventLog", "KINDS", "configure", "emit", "get_log",
    "install_from_env", "read_events", "validate_event",
]

EVENTS_ENV = "PCTPU_OBS_EVENTS"

# The typed event vocabulary — one name per thing that happens to the
# stack, mapping 1:1 onto the subsystems that emit it.  Emitting an
# unknown kind raises: a typo'd kind would otherwise silently fork the
# schema every report consumer depends on.
KINDS = frozenset({
    "compile",             # a fresh trace/compile (step build-cache miss)
    "exchange",            # halo traffic attribution for one iterate call
    "degrade",             # backend degradation walk resolved a lower tier
    "retry",               # with_retry observed a transient + backoff
    "checkpoint_save",     # snapshot written (duration + bytes)
    "checkpoint_load",     # snapshot loaded (duration + bytes)
    "checkpoint_reshard",  # load crossed a grid change
    "quarantine",          # a torn snapshot was quarantined (cause per shard)
    "reshape",             # serving engine swapped its mesh mid-process
    "admission",           # a request was shed with a typed reason
    "fault_trigger",       # an injected fault fired at a named site
    "heartbeat",           # supervisor liveness tick
    "leg",                 # supervisor leg state change (start/done/...)
    "serve",               # service lifecycle (boot, close)
    "router",              # replica-set router: failover, spill, replica
    #                        ready-state flip, tenant-quota shed,
    #                        kill/revive (round 14); replica add/remove/
    #                        ring-join (round 17 pool mutation)
    "autoscale",           # fleet control loop: scale decision + the
    #                        signals that drove it, pre-warm report,
    #                        drain report (round 17)
    "resume",              # durable converge job resumed mid-stream on a
    #                        surviving replica from its ledger token
    #                        (round 18: from/to replica, iters, work
    #                        units already spent)
    "chaos",               # chaos transport injected a network-shaped
    #                        failure (round 18: site, mode, replica)
    "wal",                 # router write-ahead journal lifecycle
    #                        (round 19: recovered / torn_tail /
    #                        quarantined / append_failed / takeover —
    #                        the crash-safe control plane's timeline)
    "span",                # one closed trace span (obs.trace): trace_id/
    #                        span_id/parent_id + start_ts/dur_s/links
    "shard",               # sharded control plane (round 21): cross-
    #                        shard fenced takeover, shard-map version
    #                        bump, peer anti-entropy sync, peer-death
    #                        suspicion — the multi-router membership
    #                        timeline
})

_REQUIRED = ("seq", "ts", "perf", "kind")


class EventLog:
    """One append-only JSONL event file with size-bounded rotation."""

    def __init__(self, path, *, max_bytes: int = 8 << 20, keep: int = 2):
        if max_bytes < 4096:
            raise ValueError("max_bytes must be >= 4096")
        if keep < 0:
            raise ValueError("keep must be >= 0")
        self.path = Path(path)
        self.max_bytes = int(max_bytes)
        self.keep = int(keep)
        self._lock = threading.Lock()
        self._seq = 0
        self._size = 0
        self._fh = None
        # Lines lost to disk failure (round 24): the event log is
        # telemetry, and telemetry IO must never raise into the
        # serving path — a failed write COUNTS here instead (the seq
        # it consumed becomes the documented in-stream gap).
        self.dropped = 0
        self.path.parent.mkdir(parents=True, exist_ok=True)

    def _open(self) -> None:
        self._fh = open(self.path, "a", encoding="utf-8")
        self._size = self._fh.tell()

    def _ensure_live(self) -> None:
        """Re-open if another PROCESS rotated (or removed) the live file
        out from under our fd — writes must land in the current
        generation, never keep streaming into a renamed ``.1``."""
        if self._fh is None:
            self._open()
            return
        try:
            st = os.stat(self.path)
        except OSError:
            st = None
        if st is None or st.st_ino != os.fstat(self._fh.fileno()).st_ino:
            self._fh.close()
            self._open()

    def _rotate_locked(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        if self.keep == 0:
            try:
                self.path.unlink()
            except OSError:
                pass
        else:
            # Shift generations up, oldest first, each step atomic.  Every
            # rename is guarded against exactly ONE failure: a SIBLING
            # process (or any external actor) rotating the same path can
            # win the race between our ``exists()`` check and the
            # ``os.replace`` — a vanished source must degrade to "that
            # generation already moved", not to a FileNotFoundError that
            # kills the writer thread and LOSES the line being emitted
            # (the multi-thread rotation stress in tests/test_obs.py pins
            # this).  Persistent failures (EACCES, a no-rename mount) are
            # NOT swallowed — silently disabling rotation would let the
            # live file grow past max_bytes forever.
            for i in range(self.keep - 1, 0, -1):
                src = self.path.with_name(f"{self.path.name}.{i}")
                if src.exists():
                    try:
                        os.replace(src, self.path.with_name(
                            f"{self.path.name}.{i + 1}"))
                    except FileNotFoundError:
                        pass
            try:
                os.replace(self.path,
                           self.path.with_name(f"{self.path.name}.1"))
            except FileNotFoundError:
                pass  # live vanished: a sibling already rotated it away
            # Drop anything beyond keep (the shift above may have created
            # .keep+1 transiently — remove it).
            extra = self.path.with_name(f"{self.path.name}.{self.keep + 1}")
            try:
                extra.unlink()
            except OSError:
                pass
        self._open()

    def emit(self, kind: str, **fields) -> dict:
        """Append one event; returns the record built (tests assert on
        it).  Raises ValueError on an unknown kind or a reserved field.
        DISK failure (real or via the ``events_emit`` fault site) never
        raises: the line is counted dropped — its consumed seq is the
        in-stream gap readers already know how to interpret."""
        if kind not in KINDS:
            raise ValueError(
                f"unknown event kind {kind!r}; known: {sorted(KINDS)}")
        bad = set(fields) & set(_REQUIRED)
        if bad:
            raise ValueError(f"fields {sorted(bad)} are reserved")
        # Consult OUTSIDE self._lock: a firing site emits its own
        # fault_trigger event through this very log, and that inner
        # emit must be able to take the (non-reentrant) lock.  The
        # guard keeps the inner emit from consulting again.
        failed = False
        if not getattr(_EMIT_GUARD, "active", False):
            _EMIT_GUARD.active = True
            try:
                _diskio.consult("events_emit")
            except (OSError, InjectedFault):
                # The telemetry ladder: the site's documented contract
                # is "counts a dropped line instead of raising into
                # the serving path" — both for translated disk modes
                # and for the raw injected fault.
                failed = True
            finally:
                _EMIT_GUARD.active = False
        with self._lock:
            self._seq += 1
            rec = {"seq": self._seq, "ts": round(time.time(), 6),
                   "perf": round(time.perf_counter(), 6),
                   "pid": os.getpid(), "kind": kind, **fields}
            line = json.dumps(rec, default=str) + "\n"
            # Size accounting in BYTES (the unit tell()/max_bytes use):
            # len(line) counts characters, which under-counts any
            # non-ASCII field and lets the file overshoot max_bytes.
            nbytes = len(line.encode("utf-8"))
            try:
                if failed:
                    raise OSError("injected events_emit failure")
                self._ensure_live()
                if (self._size + nbytes > self.max_bytes
                        and self._size > 0):
                    self._rotate_locked()
                self._fh.write(line)
                self._fh.flush()
                self._size += nbytes
            except OSError:
                self.dropped += 1
                if _metrics.enabled():
                    _metrics.counter(
                        "pctpu_events_dropped_total",
                        "event lines lost to disk failure (the log "
                        "keeps its seq gap; serving unaffected)").inc()
        return rec

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def generations(self) -> list[Path]:
        """Existing files, oldest first (``.N`` ... ``.1``, then live)."""
        out = []
        for i in range(self.keep + 1, 0, -1):
            p = self.path.with_name(f"{self.path.name}.{i}")
            if p.exists():
                out.append(p)
        if self.path.exists():
            out.append(self.path)
        return out


def validate_event(rec: dict) -> list[str]:
    """Schema problems of one parsed event line ([] = valid).

    The contract every consumer (obs_report, the smoke leg, tests) checks
    instead of re-inventing: required keys present and typed, kind known,
    seq positive."""
    problems = []
    if not isinstance(rec, dict):
        return [f"not an object: {type(rec).__name__}"]
    for k in _REQUIRED:
        if k not in rec:
            problems.append(f"missing {k!r}")
    if isinstance(rec.get("seq"), bool) or not isinstance(
            rec.get("seq"), int) or (isinstance(rec.get("seq"), int)
                                     and rec["seq"] < 1):
        problems.append(f"bad seq {rec.get('seq')!r}")
    for k in ("ts", "perf"):
        if k in rec and not isinstance(rec[k], (int, float)):
            problems.append(f"bad {k} {rec.get(k)!r}")
    if rec.get("kind") not in KINDS:
        problems.append(f"unknown kind {rec.get('kind')!r}")
    return problems


def read_events(path, include_rotated: bool = True) -> list[dict]:
    """Parse a JSONL event log (plus rotated generations, oldest first).

    Unparseable lines raise — a torn tail is a real finding, and the
    writer flushes per line, so one should never exist outside a crash.
    """
    p = Path(path)
    paths: list[Path] = []
    if include_rotated:
        i = 1
        gens = []
        while True:
            g = p.with_name(f"{p.name}.{i}")
            if not g.exists():
                break
            gens.append(g)
            i += 1
        paths.extend(reversed(gens))
    if p.exists():
        paths.append(p)
    out: list[dict] = []
    for fp in paths:
        for n, line in enumerate(fp.read_text().splitlines(), 1):
            if not line.strip():
                continue
            try:
                out.append(json.loads(line))
            except ValueError as e:
                raise ValueError(f"{fp}:{n}: unparseable event line: {e}")
    return out


# ---------------------------------------------------------------------------
# Process-global log.  Same torn-read argument as faults._PLAN: installed
# before the workload starts; a reader sees None or a whole EventLog.

_LOG: EventLog | None = None


def configure(path, *, max_bytes: int = 8 << 20,
              keep: int = 2) -> EventLog:
    """Install the process-global event log (returns it)."""
    global _LOG
    if _LOG is not None:
        _LOG.close()
    _LOG = EventLog(path, max_bytes=max_bytes, keep=keep)
    return _LOG


def install_from_env(env: dict | None = None) -> EventLog | None:
    """Honor ``PCTPU_OBS_EVENTS=<path>`` if set (else no-op).  Entry
    points (serve.py, loadgen, soak, run_supervised) call this once at
    boot so child processes inherit the timeline via the env."""
    env = os.environ if env is None else env
    path = env.get(EVENTS_ENV, "").strip()
    if not path:
        return None
    return configure(path)


def deconfigure() -> None:
    global _LOG
    if _LOG is not None:
        _LOG.close()
    _LOG = None


def get_log() -> EventLog | None:
    return _LOG


def emit(kind: str, **fields) -> None:
    """Emit to the process-global log — free when none is installed or
    obs is disabled (one load + one test, the fault_point contract)."""
    log = _LOG
    if log is None or not _metrics.enabled():
        return
    log.emit(kind, **fields)
