"""Process-global metrics registry: typed counters, gauges, histograms.

Until this PR, telemetry was three bespoke paths: ``PhaseTimer`` walls
living only in bench rows and per-request responses, the supervisor's own
``status.json`` counters, and ad-hoc ``stats`` dicts in the serving layer
(service / batcher / engine) — no common schema, no time dimension, no
pull endpoint.  This module is the one spine they all flow through:

* :class:`Counter` — monotonic, labeled series (``inc``);
* :class:`Gauge` — last-write-wins, labeled series (``set``);
* :class:`Histogram` — fixed-bucket latency/size distributions
  (``observe``), with bucket-interpolated quantiles for reports;
* :class:`Registry` — get-or-create by name, one lock, ``snapshot()``
  (the in-process client surface) and :func:`render_text` (Prometheus
  text exposition v0.0.4, served at ``/metrics`` by the HTTP frontend).

Disabled mode (``PCTPU_OBS=0``): every mutator returns after ONE module
attribute load and a truthiness test — the ``fault_point`` contract
(resilience.faults): nothing is counted, allocated, or locked, so hooks
are free to sit in compile paths, per-shard I/O loops, and the serving
hot path.  Guarded by a perf test in ``tests/test_obs.py``.

This module is deliberately stdlib-only and jax-free: it is imported by
modules (``resilience.faults``, ``utils.tracing``) that must stay cheap
to import.
"""

from __future__ import annotations

import json
import math
import os
import threading
from collections.abc import MutableMapping

__all__ = [
    "Counter", "Gauge", "Histogram", "MirroredStats", "Registry",
    "counter", "enabled", "gauge", "histogram", "parse_text", "render_text",
    "reset", "set_enabled", "snapshot",
]

OBS_ENV = "PCTPU_OBS"

# Read once at import; set_enabled() flips it (tests, tools).  Mutators
# check this FIRST — the disabled hot path is one load + one branch.
_ENABLED = os.environ.get(OBS_ENV, "1").strip().lower() not in (
    "0", "false", "off")


def enabled() -> bool:
    return _ENABLED


def set_enabled(on: bool) -> None:
    """Flip the global obs switch (tests / entry points).  Metric handles
    stay valid across flips: they consult the switch per operation."""
    global _ENABLED
    _ENABLED = bool(on)


# Default latency buckets (seconds): sub-ms to tens of seconds — covers a
# CPU-sim halo round (~100 µs) through a cold silicon compile (~10 s).
LATENCY_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)


def _label_key(labelnames: tuple[str, ...], labels: dict) -> tuple[str, ...]:
    if set(labels) != set(labelnames):
        raise ValueError(
            f"labels {sorted(labels)} do not match declared "
            f"labelnames {sorted(labelnames)}")
    return tuple(str(labels[n]) for n in labelnames)


class _Metric:
    """Shared plumbing: name, help, labelnames, per-series storage."""

    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: tuple[str, ...],
                 lock: threading.Lock):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = lock
        self._series: dict[tuple[str, ...], object] = {}

    def _series_snapshot(self) -> list[dict]:
        with self._lock:
            items = list(self._series.items())
        return [{"labels": dict(zip(self.labelnames, k)), "value": v}
                for k, v in items]

    def value(self, **labels) -> object:
        """One series' current value (0/None when never touched)."""
        k = _label_key(self.labelnames, labels)
        with self._lock:
            return self._series.get(k, 0)

    def remove(self, **labels) -> None:
        """Drop one labeled series from the exposition (no-op when it
        never existed).  The retirement surface for label values with a
        bounded lifetime — a shape-bucketed batcher lane that drained,
        a replica that left the ring — so ``/metrics`` cardinality
        tracks LIVE objects, not every label value ever seen."""
        k = _label_key(self.labelnames, labels)
        with self._lock:
            self._series.pop(k, None)


class Counter(_Metric):
    kind = "counter"

    def inc(self, value: float = 1.0, **labels) -> None:
        if not _ENABLED:
            return
        if value < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        k = _label_key(self.labelnames, labels)
        with self._lock:
            self._series[k] = self._series.get(k, 0.0) + value


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        if not _ENABLED:
            return
        k = _label_key(self.labelnames, labels)
        with self._lock:
            self._series[k] = float(value)

    def max(self, value: float, **labels) -> None:
        """Keep the running maximum (the high-water-mark idiom)."""
        if not _ENABLED:
            return
        k = _label_key(self.labelnames, labels)
        with self._lock:
            cur = self._series.get(k)
            if cur is None or value > cur:
                self._series[k] = float(value)


class _HistSeries:
    __slots__ = ("counts", "sum", "count")

    def __init__(self, n_buckets: int):
        self.counts = [0] * n_buckets   # cumulative rendered at exposition
        self.sum = 0.0
        self.count = 0


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help, labelnames, lock,
                 buckets: tuple[float, ...] = LATENCY_BUCKETS):
        super().__init__(name, help, labelnames, lock)
        bs = tuple(sorted({float(b) for b in buckets}))
        if not bs or any(not math.isfinite(b) for b in bs):
            # +Inf is the IMPLICIT last bucket: an explicit one would
            # render a duplicate le="+Inf" sample a scraper rejects.
            raise ValueError(
                f"histogram buckets must be finite and non-empty, "
                f"got {buckets}")
        self.buckets = bs  # upper bounds; +Inf is implicit

    def observe(self, value: float, **labels) -> None:
        if not _ENABLED:
            return
        k = _label_key(self.labelnames, labels)
        v = float(value)
        with self._lock:
            s = self._series.get(k)
            if s is None:
                s = self._series[k] = _HistSeries(len(self.buckets) + 1)
            i = 0
            for i, ub in enumerate(self.buckets):  # noqa: B007
                if v <= ub:
                    break
            else:
                i = len(self.buckets)
            s.counts[i] += 1
            s.sum += v
            s.count += 1

    def quantile(self, q: float, **labels) -> float | None:
        """Bucket-interpolated quantile of one series (None when empty).

        Linear interpolation inside the containing bucket — the standard
        Prometheus ``histogram_quantile`` estimate; values in the +Inf
        bucket report the last finite bound (a floor, flagged by being
        exactly that bound)."""
        k = _label_key(self.labelnames, labels)
        with self._lock:
            s = self._series.get(k)
            if s is None or s.count == 0:
                return None
            counts = list(s.counts)
            total = s.count
        rank = q * total
        cum = 0.0
        for i, c in enumerate(counts):
            prev_cum = cum
            cum += c
            if cum >= rank and c > 0:
                if i >= len(self.buckets):
                    return self.buckets[-1]
                lo = self.buckets[i - 1] if i > 0 else 0.0
                hi = self.buckets[i]
                return lo + (hi - lo) * (rank - prev_cum) / c
        return self.buckets[-1]

    def _series_snapshot(self) -> list[dict]:
        with self._lock:
            items = [(k, (list(s.counts), s.sum, s.count))
                     for k, s in self._series.items()]
        out = []
        for k, (counts, ssum, count) in items:
            out.append({
                "labels": dict(zip(self.labelnames, k)),
                "buckets": list(self.buckets),
                "counts": counts,
                "sum": ssum,
                "count": count,
            })
        return out


class Registry:
    """Named metrics, get-or-create; one lock shared by every series.

    Re-registration with the same (kind, labelnames) returns the existing
    metric — module-level handles and late callers converge on one series
    set.  A name re-registered with a DIFFERENT shape raises: two callers
    silently feeding differently-shaped series under one name is exactly
    the ad-hoc-dict drift this registry exists to end.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}

    def _get_or_create(self, cls, name: str, help: str, labelnames,
                       **kw) -> _Metric:
        labelnames = tuple(labelnames)
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls) or m.labelnames != labelnames:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{m.kind}{m.labelnames}, not "
                        f"{cls.kind}{labelnames}")
                return m
            m = cls(name, help, labelnames, self._lock, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "",
                labelnames=()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "", labelnames=()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "", labelnames=(),
                  buckets: tuple[float, ...] = LATENCY_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets)

    def reset(self) -> None:
        """Drop every metric (tests).  Handles created before a reset are
        orphaned — re-create them through the registry."""
        with self._lock:
            self._metrics.clear()

    # -- export ------------------------------------------------------------
    def snapshot(self) -> dict:
        """Structured dump for the in-process client / evidence files."""
        with self._lock:
            metrics = list(self._metrics.values())
        return {
            "enabled": _ENABLED,
            "metrics": [
                {"name": m.name, "kind": m.kind, "help": m.help,
                 "series": m._series_snapshot()}
                for m in sorted(metrics, key=lambda m: m.name)
            ],
        }

    def render_text(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        lines: list[str] = []
        for m in sorted(self.snapshot()["metrics"], key=lambda d: d["name"]):
            name, kind = m["name"], m["kind"]
            if m["help"]:
                lines.append(f"# HELP {name} {m['help']}")
            lines.append(f"# TYPE {name} {kind}")
            for s in m["series"]:
                lbl = _fmt_labels(s["labels"])
                if kind == "histogram":
                    cum = 0
                    for ub, c in zip(s["buckets"] + [math.inf],
                                     s["counts"]):
                        cum += c
                        le = "+Inf" if ub == math.inf else _fmt_num(ub)
                        lines.append(
                            f"{name}_bucket"
                            f"{_fmt_labels({**s['labels'], 'le': le})}"
                            f" {cum}")
                    lines.append(f"{name}_sum{lbl} {_fmt_num(s['sum'])}")
                    lines.append(f"{name}_count{lbl} {s['count']}")
                else:
                    lines.append(f"{name}{lbl} {_fmt_num(s['value'])}")
        return "\n".join(lines) + ("\n" if lines else "")


def _fmt_num(v: float) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def _fmt_labels(labels: dict) -> str:
    if not labels:
        return ""
    body = ",".join(
        f'{k}="{_escape(str(v))}"' for k, v in sorted(labels.items()))
    return "{" + body + "}"


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _unescape(v: str) -> str:
    """Single-pass inverse of :func:`_escape`.  Sequential .replace()
    passes would corrupt values like ``\\\\n`` (a literal backslash
    followed by 'n' — any repr'd exception message with a newline): the
    second pass re-interprets bytes the first pass already produced."""
    out: list[str] = []
    i, n = 0, len(v)
    while i < n:
        c = v[i]
        if c == "\\" and i + 1 < n:
            nxt = v[i + 1]
            out.append({"n": "\n", "\\": "\\", '"': '"'}.get(nxt,
                                                            "\\" + nxt))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def parse_text(text: str) -> dict[str, list[tuple[dict, float]]]:
    """Parse Prometheus text exposition into ``{name: [(labels, value)]}``.

    The validator half of :meth:`Registry.render_text` — the obs smoke leg
    and the exposition round-trip test both parse what the frontend
    serves rather than trusting the renderer.  Raises ValueError on any
    malformed sample line.
    """
    out: dict[str, list[tuple[dict, float]]] = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        # name{label="v",...} value    |    name value
        if "{" in line:
            name, rest = line.split("{", 1)
            lbl_body, sep, val = rest.rpartition("}")
            if not sep:
                raise ValueError(f"unterminated label set in {line!r}")
            labels = {}
            for part in _split_labels(lbl_body):
                if not part:
                    continue
                k, _, v = part.partition("=")
                if not (v.startswith('"') and v.endswith('"')):
                    raise ValueError(f"malformed label in {line!r}")
                labels[k.strip()] = _unescape(v[1:-1])
        else:
            name, _, val = line.partition(" ")
            labels = {}
        name, val = name.strip(), val.strip()
        if not name or not val:
            raise ValueError(f"malformed sample line {line!r}")
        out.setdefault(name, []).append(
            (labels, math.inf if val == "+Inf" else float(val)))
    return out


def _split_labels(body: str) -> list[str]:
    """Split a label body on commas outside quotes."""
    parts, cur, in_q, esc = [], [], False, False
    for ch in body:
        if esc:
            cur.append(ch)
            esc = False
        elif ch == "\\":
            cur.append(ch)
            esc = True
        elif ch == '"':
            cur.append(ch)
            in_q = not in_q
        elif ch == "," and not in_q:
            parts.append("".join(cur).strip())
            cur = []
        else:
            cur.append(ch)
    parts.append("".join(cur).strip())
    return parts


class MirroredStats(MutableMapping):
    """A legacy ``stats`` dict whose every write also lands in a Gauge.

    The serving layer's compat view: ``service.stats`` / ``batcher.stats``
    / ``engine.stats`` keep exact dict semantics (``stats["hits"] += 1``,
    ``dict(stats)``, key iteration — the tier-1 surface) while the same
    values flow through the registry and out the ``/metrics`` endpoint as
    ``<gauge>{key="hits"}`` series.  The local dict is authoritative —
    serving semantics (admission accounting, cache hit asserts) must not
    depend on whether obs is enabled — and the gauge mirror no-ops when
    obs is off, so the compat surface is identical in both modes.

    Thread-safety matches the plain dicts it replaces: callers mutate
    under their own subsystem lock (service._lock, batcher._cv, ...); the
    gauge write takes the registry lock internally.
    """

    def __init__(self, gauge_metric: Gauge, initial: dict | None = None,
                 **fixed_labels):
        if "key" not in gauge_metric.labelnames:
            raise ValueError("MirroredStats gauge needs a 'key' label")
        self._gauge = gauge_metric
        self._fixed = fixed_labels
        self._data: dict[str, float] = {}
        for k, v in (initial or {}).items():
            self[k] = v

    def __setitem__(self, key: str, value) -> None:
        self._data[key] = value
        self._gauge.set(value, key=key, **self._fixed)

    def __getitem__(self, key: str):
        return self._data[key]

    def __delitem__(self, key: str) -> None:
        del self._data[key]
        # Retire the mirrored series too: a deleted stats key (a drained
        # batcher lane) must leave the exposition, not linger at its
        # last value forever.
        self._gauge.remove(key=key, **self._fixed)

    def __iter__(self):
        return iter(self._data)

    def __len__(self) -> int:
        return len(self._data)

    def __repr__(self) -> str:
        return f"MirroredStats({self._data!r})"


# ---------------------------------------------------------------------------
# The process-global registry + module-level conveniences.  Library code
# creates handles through these so every subsystem lands in ONE exposition.

REGISTRY = Registry()


def counter(name: str, help: str = "", labelnames=()) -> Counter:
    return REGISTRY.counter(name, help, labelnames)


def gauge(name: str, help: str = "", labelnames=()) -> Gauge:
    return REGISTRY.gauge(name, help, labelnames)


def histogram(name: str, help: str = "", labelnames=(),
              buckets: tuple[float, ...] = LATENCY_BUCKETS) -> Histogram:
    return REGISTRY.histogram(name, help, labelnames, buckets)


def snapshot() -> dict:
    return REGISTRY.snapshot()


def render_text() -> str:
    return REGISTRY.render_text()


def reset() -> None:
    REGISTRY.reset()


def dump(path) -> None:
    """Write the snapshot JSON (evidence files / obs_report input)."""
    with open(path, "w") as f:  # diskio: exempt — exit-time snapshot
        json.dump(snapshot(), f, indent=2)
