"""Exchange/overlap attribution: halo bytes and exchange-vs-compute split.

ROADMAP items 1 and 3 are judged on instrumentation this module owns:

* "Persistent and Partitioned MPI for Stencil Communication" (PAPERS.md)
  demonstrates its overlap wins through per-phase exchange-vs-interior
  timing — so every step/bench/serving artifact now carries an
  ``exchange_fraction`` attribution (the roofline model's exchange term
  over its total, a pure function of the decomposition);
* "Efficient Process-to-Node Mapping Algorithms for Stencil
  Computations" (PAPERS.md) validates layouts via per-direction halo
  *byte* accounting — :func:`halo_bytes_per_round` is that accounting as
  an analytic formula of (grid, block, radius, fuse, dtype, boundary),
  tested against an independent derivation in ``tests/test_obs.py``.

The byte formula mirrors ``parallel/halo.halo_exchange`` exactly:

* phase 1 (rows): each sending device moves a ``channels × d × bw`` slab
  per direction, ``d = radius*fuse`` (temporal fusion widens the ghost
  band); with zero boundaries only ``R-1`` of the ``R`` rows send each
  way, with periodic all ``R`` do — and a 1-long axis moves NOTHING
  (``halo._shift`` short-circuits to zeros/self, no collective);
* phase 2 (cols): slabs are cut from the already row-padded block, so
  their height is ``bh + 2d`` — the corner bytes ride the column phase,
  which is exactly how the two-hop corner propagation pays for skipping
  the reference's diagonal messages.

The same ghost bands (same depth, same directions) are what the RDMA
kernels DMA in-kernel, so the accounting is backend-independent by
construction: it prices the *decomposition*, not the transport.

jax-free: everything here is arithmetic over ints, reusing the tuning
cost model's calibrated constants for the time split.
"""

from __future__ import annotations

import time

from parallel_convolution_tpu.obs import events, metrics, trace
from parallel_convolution_tpu.tuning import costmodel

__all__ = [
    "exchange_rounds", "halo_bytes_per_round", "halo_bytes_total",
    "predicted_exchange_fraction", "predicted_exchange_split",
    "record_drift", "record_step", "volume_face_bytes_per_round",
]

DIRECTIONS = ("north", "south", "east", "west")


def halo_bytes_per_round(grid: tuple[int, int], block_hw: tuple[int, int],
                         radius: int, fuse: int, channels: int,
                         storage: str, boundary: str = "zero") -> dict:
    """Per-direction bytes crossing device links in ONE exchange round,
    summed over the whole mesh.

    A "round" is one ``halo_exchange`` at ghost depth ``d = radius*fuse``
    (the fused-chunk exchange).  Directions name where the data travels:
    ``south`` = toward higher row index, ``east`` = toward higher column
    index.  Zero-boundary edges send nothing outward (there is no
    neighbor); periodic boundaries close the ring — except on a 1-long
    axis, where the wrap is the identity and no collective exists.
    """
    R, C = (int(g) for g in grid)
    bh, bw = (int(b) for b in block_hw)
    d = int(radius) * max(1, int(fuse))
    B = costmodel.STORAGE_BYTES[storage]
    periodic = boundary == "periodic"
    row_senders = (R if periodic else R - 1) if R > 1 else 0
    col_senders = (C if periodic else C - 1) if C > 1 else 0
    row_slab = channels * d * bw * B          # phase 1: (C, d, bw)
    col_slab = channels * d * (bh + 2 * d) * B  # phase 2: row-padded height
    out = {
        "south": row_senders * C * row_slab,
        "north": row_senders * C * row_slab,
        "east": col_senders * R * col_slab,
        "west": col_senders * R * col_slab,
    }
    out["total"] = sum(out.values())
    return out


def volume_face_bytes_per_round(grid: tuple[int, int],
                                block_hw: tuple[int, int], depth: int,
                                radius: int, fuse: int, fields: int = 2,
                                storage: str = "f32",
                                boundary: str = "zero") -> dict:
    """Per-direction bytes of ONE rank-3 6-face ghost exchange.

    The ±D faces are a LOCAL pad (the depth axis is resident —
    ``volumes.halo3``), so only the ±H/±W face slabs cross links, and
    each slab carries the whole depth-padded field column: the rank-2
    slab arithmetic at an effective channel count of
    ``fields * (depth + 2d)``.  Same direction naming, same
    zero-vs-periodic sender rule, same 1-long-axis elision as
    :func:`halo_bytes_per_round`."""
    d = int(radius) * max(1, int(fuse))
    ch = max(1, int(fields)) * (max(1, int(depth)) + 2 * d)
    return halo_bytes_per_round(grid, block_hw, radius, fuse, ch,
                                storage, boundary)


def exchange_rounds(iters: int, fuse: int) -> tuple[int, int]:
    """``(full_rounds, tail_iters)`` of the fused iteration schedule: the
    runner exchanges once per ``fuse``-iteration chunk plus once for the
    remainder chunk (at its own shallower depth)."""
    fuse = max(1, min(int(fuse), max(1, int(iters))))
    return int(iters) // fuse, int(iters) % fuse


def halo_bytes_total(grid, block_hw, radius: int, fuse: int, iters: int,
                     channels: int, storage: str,
                     boundary: str = "zero") -> dict:
    """Per-direction bytes for a whole ``iters``-iteration run — full
    fused rounds at depth ``radius*fuse`` plus the tail round at its own
    depth (``radius * (iters % fuse)``), exactly the schedule
    ``step._build_iterate`` compiles."""
    full, rem = exchange_rounds(iters, fuse)
    total = {d: 0 for d in (*DIRECTIONS, "total")}
    per = halo_bytes_per_round(grid, block_hw, radius, fuse, channels,
                               storage, boundary)
    for k in total:
        total[k] += full * per[k]
    if rem:
        tail = halo_bytes_per_round(grid, block_hw, radius, rem, channels,
                                    storage, boundary)
        for k in total:
            total[k] += tail[k]
    total["rounds"] = full + (1 if rem else 0)
    return total


def predicted_exchange_split(
        grid, block_hw, radius: int, fuse: int, *, backend: str,
        storage: str, shape: tuple[int, int, int],
        tile: tuple[int, int] | None = None, quantize: bool = True,
        separable: bool = False, platform: str = "cpu",
        device_kind: str = "", overlap: bool = False,
        col_mode: str = "packed") -> dict:
    """Exchange-vs-compute attribution of one iteration's roofline time,
    overlap-adjusted.

    Returns::

      {"exchange_fraction":        exposed exchange / total wall,
       "exchange_hidden_fraction": hidden exchange / total exchange,
       "exchange_hidden_of_total": hidden exchange / total wall,
       "overlap":                  the caller's compiled-form knob}

    ``overlap`` is reported back VERBATIM (callers pass the knob the
    executable compiled with, so events and rows agree by construction);
    the max() *adjustment* applies only where the pipeline can actually
    hide bytes (``costmodel.overlap_legal`` — a degenerate all-rim block
    or a 1x1 grid computes in serialized order even inside the
    overlapped program, so it is priced serialized with hidden = 0).

    Serialized arithmetic: total = compute + exchange, nothing hidden,
    ``exchange_fraction`` is exactly the pre-overlap series.
    Overlapped: the interior-first pipeline rides the exchange under
    the compute roof, so ``hidden = min(exchange, compute)`` and only
    the remainder is exposed over ``total = max(compute, exchange)`` —
    the "hidden vs. exposed exchange time" reading the overlapped-halo
    ROADMAP item is judged by.  Pure model attribution: the interpret
    penalty scales all terms, so the fractions are penalty-invariant; a
    1x1 grid is exactly 0 / 0.
    """
    hw = costmodel.hardware_for(platform, device_kind)
    T = max(1, int(fuse))
    k = 2 * int(radius) + 1
    ov = bool(overlap) and costmodel.overlap_legal(
        backend, tuple(grid), tuple(block_hw), int(radius), T)
    out = {"exchange_fraction": 0.0, "exchange_hidden_fraction": 0.0,
           "exchange_hidden_of_total": 0.0, "overlap": bool(overlap)}
    persistent = backend in costmodel.PERSISTENT_BACKENDS
    ex = costmodel.exchange_seconds_per_px_iter(
        tuple(grid), tuple(block_hw), int(radius), T, storage, hw,
        persistent=persistent,
        col_mode=col_mode if persistent else "packed")
    if ex == 0.0:
        return out
    tile_eff = costmodel.effective_tile(backend, tile)
    rim_tile = tile_eff if tile_eff is not None else tuple(block_hw)
    if backend == "pallas_rdma" and not costmodel.rdma_is_tiled(
            tuple(shape), tuple(block_hw), int(radius), T, storage,
            col_mode=col_mode, grid=tuple(grid)):
        rim_tile = tuple(block_hw)
    sep = separable and backend in ("separable", "pallas_sep")
    t_hbm = costmodel.hbm_bytes_per_px_iter(
        backend, storage, T, tile, tuple(block_hw), int(radius),
        tuple(shape)) / (hw.hbm_gbps * 1e9)
    t_flop = costmodel.flops_per_px_iter(
        k, sep, quantize, T, rim_tile, int(radius)) / (hw.flop_gops * 1e9)
    roof = max(t_hbm, t_flop)
    if ov:
        hidden = min(ex, roof)
        exposed = ex - hidden
        total = max(roof, ex)
        out["exchange_hidden_fraction"] = min(1.0, hidden / ex)
        if total > 0:
            out["exchange_hidden_of_total"] = min(1.0, hidden / total)
    else:
        exposed, total = ex, roof + ex
    if total > 0:
        out["exchange_fraction"] = min(1.0, exposed / total)
    return out


def predicted_exchange_fraction(
        grid, block_hw, radius: int, fuse: int, *, backend: str,
        storage: str, shape: tuple[int, int, int],
        tile: tuple[int, int] | None = None, quantize: bool = True,
        separable: bool = False, platform: str = "cpu",
        device_kind: str = "", overlap: bool = False) -> float:
    """The (exposed) exchange share of one iteration, in [0, 1] — the
    ``exchange_fraction`` member of :func:`predicted_exchange_split`,
    kept as the scalar surface existing callers/series use.  With
    ``overlap=False`` the values are identical to the pre-overlap
    series (compute + exchange decomposition)."""
    return predicted_exchange_split(
        grid, block_hw, radius, fuse, backend=backend, storage=storage,
        shape=shape, tile=tile, quantize=quantize, separable=separable,
        platform=platform, device_kind=device_kind,
        overlap=overlap)["exchange_fraction"]


# -- the step-level recorder (metrics + event, one helper, two callers) ----
# parallel/step.iterate_prepared and serving/engine.run_batch both drive
# compiled runners; both call record_step so exchange attribution lands in
# the same series regardless of the entry point.

def _m():
    """Metric handles, created lazily through the global registry (so a
    registry reset in tests re-creates them on next use)."""
    return (
        metrics.histogram(
            "pctpu_step_seconds",
            "wall of one compiled iterate call (all fused blocks)",
            ("backend",)),
        metrics.counter(
            "pctpu_exchange_seconds_total",
            "model-attributed EXPOSED exchange share of step walls",
            ("backend",)),
        metrics.counter(
            "pctpu_compute_seconds_total",
            "model-attributed compute share of step walls", ("backend",)),
        metrics.counter(
            "pctpu_exchange_hidden_seconds_total",
            "model-attributed exchange time hidden under compute by the "
            "overlapped pipeline", ("backend",)),
        metrics.counter(
            "pctpu_halo_bytes_total",
            "analytic ghost-band bytes moved, per direction",
            ("backend", "direction")),
        metrics.counter(
            "pctpu_halo_rounds_total", "halo exchange rounds executed",
            ("backend",)),
        metrics.counter(
            "pctpu_iterations_total", "stencil iterations executed",
            ("backend",)),
    )


def record_step(*, backend: str, grid, block_hw, radius: int, fuse: int,
                iters: int, channels: int, storage: str, boundary: str,
                wall_s: float | None, shape, quantize: bool = True,
                tile=None, platform: str = "cpu", device_kind: str = "",
                source: str = "step", overlap: bool = False,
                col_mode: str = "packed",
                mg_level: int | None = None) -> dict | None:
    """Record one compiled-iterate call: wall, halo bytes, exchange split.

    ``col_mode`` (round 16) stamps the resolved column-slab transport
    into the exchange event; the per-slab wait series
    ``pctpu_halo_slab_wait_seconds{direction, which}`` attributes the
    exchange wall across the four slab channels by their byte share,
    split exposed-vs-hidden — the partitioned-completion analogue of
    the r12 hidden/exposed split, per slab instead of per phase.

    ``mg_level`` (round 15) attributes the call to one multigrid grid
    level: the exchange event carries the level and the sweep counter
    gains the ``pctpu_mg_level`` label, so per-level exchange/compute
    cost is a label filter away (level 0 = the fine grid).

    ``wall_s=None`` means the caller dispatched asynchronously and has no
    honest device wall (``iterate_prepared`` — fencing there would
    silently serialize the library's async iterate path): the byte/round
    counters and the event still land, but the wall histogram and the
    exchange/compute second split are skipped rather than fed a
    dispatch-only wall.  Callers that already fence (bench, the serving
    device phase, the convergence path's count readback) pass the real
    wall.

    Returns the attribution dict (halo bytes + fraction) for callers that
    stamp rows, or None when obs is disabled (nothing computed — the
    arithmetic itself is the overhead being avoided).
    """
    if not metrics.enabled():
        return None
    sep = backend in ("separable", "pallas_sep")
    by = halo_bytes_total(grid, block_hw, radius, fuse, iters, channels,
                          storage, boundary)
    split = predicted_exchange_split(
        grid, block_hw, radius, fuse, backend=backend, storage=storage,
        shape=shape, tile=tile, quantize=quantize, separable=sep,
        platform=platform, device_kind=device_kind, overlap=overlap,
        col_mode=col_mode)
    frac = split["exchange_fraction"]
    hidden_of_ex = split["exchange_hidden_fraction"]
    wall, ex_s, comp_s, hid_s, hbytes, rounds, iters_m = _m()
    if wall_s is not None:
        wall.observe(wall_s, backend=backend)
        ex_s.inc(wall_s * frac, backend=backend)
        comp_s.inc(wall_s * (1.0 - frac), backend=backend)
        if split["exchange_hidden_of_total"] > 0.0:
            # Exchange time the pipeline rode under the compute share —
            # informational (it overlaps compute seconds, not additive).
            hid_s.inc(wall_s * split["exchange_hidden_of_total"],
                      backend=backend)
        if by["total"] > 0:
            # Per-slab wait attribution (round 16, partitioned
            # completion): the exposed and hidden exchange walls spread
            # across the four slab channels by byte share — the series
            # that says WHICH ghost direction a decomposition waits on.
            slab = metrics.counter(
                "pctpu_halo_slab_wait_seconds",
                "model-attributed exchange wall per halo slab channel, "
                "exposed vs hidden-under-compute",
                ("backend", "direction", "which"))
            exposed_s = wall_s * frac
            hidden_s = wall_s * split["exchange_hidden_of_total"]
            for d in DIRECTIONS:
                share = by[d] / by["total"]
                if share <= 0.0:
                    continue
                slab.inc(exposed_s * share, backend=backend, direction=d,
                         which="exposed")
                if hidden_s > 0.0:
                    slab.inc(hidden_s * share, backend=backend,
                             direction=d, which="hidden")
    for d in DIRECTIONS:
        hbytes.inc(by[d], backend=backend, direction=d)
    rounds.inc(by["rounds"], backend=backend)
    iters_m.inc(iters, backend=backend)
    if mg_level is not None:
        # Per-level multigrid attribution: sweeps executed at each grid
        # level, labeled so one series shows where cycle time goes.
        metrics.counter(
            "pctpu_mg_sweeps_total",
            "multigrid smoothing sweeps executed per grid level",
            ("backend", "pctpu_mg_level")).inc(
            iters, backend=backend, pctpu_mg_level=str(int(mg_level)))
    events.emit(
        "exchange", source=source, backend=backend,
        grid=f"{grid[0]}x{grid[1]}", block=list(block_hw),
        radius=int(radius), fuse=int(fuse), iters=int(iters),
        storage=storage, boundary=boundary, rounds=by["rounds"],
        halo_bytes={d: by[d] for d in DIRECTIONS},
        exchange_fraction=round(frac, 4),
        overlap=bool(split["overlap"]),
        col_mode=str(col_mode),
        exchange_hidden_fraction=round(hidden_of_ex, 4),
        **({"mg_level": int(mg_level)} if mg_level is not None else {}),
        **({"wall_s": round(wall_s, 6)} if wall_s is not None else {}))
    # Trace attribution (round 13): when this step runs under an active
    # span (the serving device span, a traced converge call), split the
    # measured wall into model-attributed exchange / compute CHILD spans
    # — the reference's per-phase MPI_Wtime breakdown made first-class.
    # The exchange span's dur is the EXPOSED share; the hidden-under-
    # compute share (r12 overlap) rides as an attribute because it
    # overlaps the compute span's interval rather than adding to it.
    ctx = trace.current()
    if ctx is not None and wall_s is not None and wall_s > 0:
        ex_s = wall_s * frac
        comp_s = wall_s - ex_s
        t0 = time.time() - wall_s
        trace.emit_span(
            "exchange", trace_id=ctx.trace_id, parent_id=ctx.span_id,
            start_ts=t0, dur_s=ex_s, backend=backend, source=source,
            overlap=bool(split["overlap"]),
            hidden_s=round(wall_s * split["exchange_hidden_of_total"], 6),
            halo_bytes=by["total"], rounds=by["rounds"])
        trace.emit_span(
            "compute", trace_id=ctx.trace_id, parent_id=ctx.span_id,
            start_ts=t0 + ex_s, dur_s=comp_s, backend=backend,
            source=source, iters=int(iters))
    return {"halo_bytes": by, "exchange_fraction": frac,
            "exchange_hidden_fraction": hidden_of_ex,
            "overlap": bool(split["overlap"])}


def record_drift(plan_key: str, backend: str, predicted_gpx: float | None,
                 measured_gpx: float | None) -> None:
    """The predicted-vs-measured Gpx/s/chip drift series per plan key —
    ROADMAP 5a's recalibration input, fed by BOTH the serving engine and
    ``bench_iterate`` through this one helper so the series can never
    desynchronize between producers."""
    if (not metrics.enabled() or not predicted_gpx
            or measured_gpx is None or measured_gpx <= 0):
        return
    g = metrics.gauge(
        "pctpu_plan_gpx_per_chip",
        "per-plan-key Gpx/s/chip, predicted vs measured",
        ("key", "backend", "which"))
    g.set(round(predicted_gpx, 6), key=plan_key, backend=backend,
          which="predicted")
    g.set(round(measured_gpx, 6), key=plan_key, backend=backend,
          which="measured")
    metrics.gauge(
        "pctpu_plan_drift_ratio",
        "measured/predicted Gpx/s per plan key (1.0 = calibrated)",
        ("key", "backend")).set(
        round(measured_gpx / predicted_gpx, 6), key=plan_key,
        backend=backend)
