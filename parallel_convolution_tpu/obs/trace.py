"""Causal request tracing: trace_id / span_id / parent_id spans.

The r11 spine records *that* things happened (metrics) and *what*
happened (flat events); it cannot follow ONE request through
frontend → admission → queue → batch → compile/device/copy.  This module
is the causal layer: a span is a named, timed interval with

* ``trace_id``  — 32-hex id of the whole request's causal tree;
* ``span_id``   — 16-hex id of this interval;
* ``parent_id`` — the enclosing span's id ("" for the root);
* ``links``     — EXTRA causal edges that are not parent/child: a batch
  span links every co-batched request's context (one batch, N request
  parents — the batch-join semantics), and a single-flight compile
  *waiter* links the leader's build span (who actually paid);
* ``attrs``     — free-form JSON-safe labels.

Spans are emitted on END as one ``span`` event into the r11 event log
(:mod:`obs.events`) — same file, same rotation, same schema discipline —
so a trace is just a filtered view of the timeline every other subsystem
already writes to.  ``scripts/trace_report.py`` reconstructs the trees,
computes batch critical paths, and renders Chrome ``trace_event`` JSON.

Context propagation is ``contextvars``-based (thread- and
task-correct): :func:`span` makes its context current for the enclosed
code; worker threads that pick a request up later re-enter its context
via the explicit :func:`attach` (the context travels in the batcher
payload).  Across transports the context rides a W3C
``traceparent``-style string (``00-<trace>-<span>-01``): an HTTP header
on the frontend, an explicit body field on the in-process client.

The reference C code's per-phase ``MPI_Wtime`` breakdown (compute vs
Isend/Irecv exchange vs allreduce check) is exactly what the span tree
makes first-class: :func:`obs.attribution.record_step` emits
``exchange`` / ``compute`` child spans under the device span, splitting
the measured wall by the roofline attribution (including the r12
hidden-vs-exposed overlap split).

Disabled mode (``PCTPU_OBS=0``, the metrics switch): :func:`span`
returns a shared no-op context manager after one load + one branch —
the ``fault_point`` contract, perf-guarded in ``tests/test_trace.py``.
With obs ON but no event log installed, contexts and ids still
propagate (responses carry a ``trace_id``) and only the span *records*
are dropped (``events.emit`` no-ops).

stdlib-only, jax-free.
"""

from __future__ import annotations

import contextlib
import contextvars
import time
import uuid
from typing import NamedTuple

from parallel_convolution_tpu.obs import events as _events, metrics as _metrics

__all__ = [
    "SpanContext", "add_link", "attach", "build_trees", "current",
    "emit_span", "format_traceparent", "new_span_id", "new_trace_id",
    "parse_traceparent", "span", "span_records",
]

TRACEPARENT_VERSION = "00"


class SpanContext(NamedTuple):
    """The propagatable identity of one span: (trace_id, span_id)."""

    trace_id: str
    span_id: str

    @property
    def ref(self) -> dict:
        """The JSON shape links/events carry."""
        return {"trace_id": self.trace_id, "span_id": self.span_id}


# Current context (what new spans parent to) and current live Span object
# (what add_link attaches to).  Two vars: attach() restores only the
# context — a worker re-entering a request's context must not be able to
# mutate a span that already ended on another thread.
_CTX: contextvars.ContextVar[SpanContext | None] = contextvars.ContextVar(
    "pctpu_trace_ctx", default=None)
_SPAN: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "pctpu_trace_span", default=None)


def new_trace_id() -> str:
    return uuid.uuid4().hex                  # 32 hex chars


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]             # 16 hex chars


def current() -> SpanContext | None:
    """The context new spans would parent to (None = no active trace)."""
    return _CTX.get()


# -- traceparent codec ------------------------------------------------------

def format_traceparent(ctx: SpanContext) -> str:
    """``00-<trace_id>-<span_id>-01`` (the W3C shape; flags always 01)."""
    return f"{TRACEPARENT_VERSION}-{ctx.trace_id}-{ctx.span_id}-01"


def parse_traceparent(header) -> SpanContext | None:
    """Parse a ``traceparent`` string; None on anything malformed.

    Tolerant by design (a bad header must degrade to 'start a fresh
    trace', never to a 400): wrong field count, wrong hex widths,
    non-hex bytes, and the all-zero ids the spec forbids all yield None.
    """
    if not isinstance(header, str):
        return None
    parts = header.strip().lower().split("-")
    if len(parts) != 4:
        return None
    ver, tid, sid, flags = parts
    if len(ver) != 2 or len(tid) != 32 or len(sid) != 16 or len(flags) != 2:
        return None
    try:
        int(ver, 16), int(tid, 16), int(sid, 16), int(flags, 16)
    except ValueError:
        return None
    if tid == "0" * 32 or sid == "0" * 16:
        return None
    return SpanContext(tid, sid)


# -- the span context manager ----------------------------------------------

def _norm_link(link) -> dict | None:
    if link is None:
        return None
    if isinstance(link, SpanContext):
        return link.ref
    if isinstance(link, dict) and link.get("trace_id") and link.get(
            "span_id"):
        return {"trace_id": str(link["trace_id"]),
                "span_id": str(link["span_id"])}
    return None


class Span:
    """One live span; also its own context manager.

    Mutators (:meth:`set`, :meth:`link`) are called from the owning
    thread between ``__enter__`` and ``__exit__`` — the record is built
    and emitted once, at exit.
    """

    __slots__ = ("name", "context", "parent_id", "links", "attrs",
                 "status", "start_ts", "_start_perf", "_ctx_token",
                 "_span_token")

    def __init__(self, name: str, context: SpanContext, parent_id: str,
                 links: list[dict], attrs: dict):
        self.name = name
        self.context = context
        self.parent_id = parent_id
        self.links = links
        self.attrs = attrs
        self.status = "ok"
        self.start_ts = 0.0
        self._start_perf = 0.0
        self._ctx_token = None
        self._span_token = None

    @property
    def ref(self) -> dict:
        return self.context.ref

    def set(self, **attrs) -> None:
        """Attach/overwrite attributes on the eventual record."""
        self.attrs.update(attrs)

    def link(self, ref, **attrs) -> None:
        """Add a causal link (a SpanContext or a ``{trace_id, span_id}``
        dict); extra kwargs annotate the edge (e.g. ``kind=...``)."""
        r = _norm_link(ref)
        if r is not None:
            if attrs:
                r = {**r, **attrs}
            self.links.append(r)

    def __enter__(self) -> "Span":
        self.start_ts = time.time()
        self._start_perf = time.perf_counter()
        self._ctx_token = _CTX.set(self.context)
        self._span_token = _SPAN.set(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        dur = time.perf_counter() - self._start_perf
        _SPAN.reset(self._span_token)
        _CTX.reset(self._ctx_token)
        if exc_type is not None:
            self.status = "error"
            self.attrs.setdefault("error", repr(exc)[:200])
        _emit_record(self.name, self.context, self.parent_id,
                     self.start_ts, dur, self.status, self.links,
                     self.attrs)
        return False


class _NullSpan:
    """The disabled-mode singleton: a reentrant no-op Span look-alike
    (stateless, so one shared instance is safe under any nesting)."""

    __slots__ = ()
    name = ""
    context = None
    parent_id = ""
    status = "ok"
    ref = None

    def set(self, **attrs) -> None:
        pass

    def link(self, ref, **attrs) -> None:
        pass

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False


NULL_SPAN = _NullSpan()

_INHERIT = object()   # sentinel: span() parents to the current context


def span(name: str, *, parent=_INHERIT, links=(), **attrs):
    """Open a span: ``with trace.span("device", backend=b) as sp: ...``

    ``parent`` defaults to the current context (nesting); pass an
    explicit :class:`SpanContext` to parent across threads (the batch
    span parents to a request enqueued on another thread), or ``None``
    to force a new root trace.  ``links`` are extra causal edges
    (contexts or ref dicts).  With obs disabled this is one load + one
    branch returning the shared no-op span.
    """
    if not _metrics.enabled():
        return NULL_SPAN
    pctx = _CTX.get() if parent is _INHERIT else parent
    tid = pctx.trace_id if pctx is not None else new_trace_id()
    ctx = SpanContext(tid, new_span_id())
    lk = [r for r in (_norm_link(l) for l in links) if r is not None]
    return Span(name, ctx, pctx.span_id if pctx is not None else "",
                lk, dict(attrs))


@contextlib.contextmanager
def attach(ctx: SpanContext | None):
    """Make ``ctx`` current WITHOUT opening a span — worker threads
    resuming a request's causal context (the batcher payload carries
    it), or telemetry emitted after a span already closed (the engine
    attaches the device span's context to parent the exchange/compute
    attribution spans)."""
    if ctx is None or not _metrics.enabled():
        yield
        return
    token = _CTX.set(ctx)
    try:
        yield
    finally:
        _CTX.reset(token)


def add_link(ref, **attrs) -> None:
    """Link ``ref`` onto the innermost live span of THIS thread (no-op
    without one) — deep code annotating its caller's span, e.g. a
    single-flight waiter linking the leader's compile span."""
    sp = _SPAN.get()
    if sp is not None:
        sp.link(ref, **attrs)


def emit_span(name: str, *, trace_id: str, parent_id: str = "",
              start_ts: float | None = None, dur_s: float = 0.0,
              links=(), status: str = "ok", **attrs) -> str | None:
    """Emit a SYNTHETIC span whose timing was measured externally —
    the queue span (enqueue → batch collect, measured by the batcher's
    clocks) and the model-attributed exchange/compute split.  Returns
    the new span_id (None when obs is disabled)."""
    if not _metrics.enabled():
        return None
    sid = new_span_id()
    _emit_record(name, SpanContext(trace_id, sid), parent_id,
                 time.time() if start_ts is None else start_ts,
                 dur_s, status,
                 [r for r in (_norm_link(l) for l in links)
                  if r is not None],
                 attrs)
    return sid


def _emit_record(name, ctx, parent_id, start_ts, dur_s, status, links,
                 attrs) -> None:
    extra = {}
    if links:
        extra["links"] = links
    if attrs:
        extra["attrs"] = attrs
    _events.emit(
        "span", name=name, trace_id=ctx.trace_id, span_id=ctx.span_id,
        parent_id=parent_id, start_ts=round(float(start_ts), 6),
        dur_s=round(float(dur_s), 6), status=status, **extra)


# -- reconstruction (shared by scripts/trace_report.py and tests) -----------

def span_records(recs: list[dict]) -> list[dict]:
    """The span events of a parsed timeline (obs.events.read_events)."""
    return [r for r in recs if r.get("kind") == "span"]


def build_trees(spans: list[dict]) -> dict[str, dict]:
    """Group span records per trace and wire up the trees.

    Returns ``{trace_id: {"spans": {span_id: rec}, "roots": [span_id],
    "children": {span_id: [span_id]}, "orphans": [span_id]}}``.

    * a **root** has ``parent_id == ""`` — or a parent marked
      ``attrs.remote_parent`` that is absent from the log: a request
      admitted under an upstream ``traceparent`` parents to a span in
      the CALLER's process, which is a local root here, not a loss;
    * an **orphan** names a (local) parent that does not exist in its
      own trace — a lost span line (or a bug in the propagation),
      exactly what the smoke leg gates on;
    * children are sorted by ``start_ts`` so reports read in time order.

    Spans are emitted at END, so children precede parents in the log —
    reconstruction is order-independent by design.
    """
    out: dict[str, dict] = {}
    for r in spans:
        tid, sid = r.get("trace_id"), r.get("span_id")
        if not tid or not sid:
            continue
        t = out.setdefault(tid, {"spans": {}, "roots": [], "children": {},
                                 "orphans": []})
        t["spans"][sid] = r
    for tid, t in out.items():
        for sid, r in t["spans"].items():
            pid = r.get("parent_id", "")
            if not pid:
                t["roots"].append(sid)
            elif pid in t["spans"]:
                t["children"].setdefault(pid, []).append(sid)
            elif r.get("attrs", {}).get("remote_parent"):
                t["roots"].append(sid)
            else:
                t["orphans"].append(sid)
        for kids in t["children"].values():
            kids.sort(key=lambda s: t["spans"][s].get("start_ts", 0.0))
        t["roots"].sort(key=lambda s: t["spans"][s].get("start_ts", 0.0))
    return out
