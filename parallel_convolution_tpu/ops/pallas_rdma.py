"""Fused remote-DMA halo + stencil kernel (SURVEY.md §7 frontier).

The standard path (``parallel/halo.py``) rides XLA ``collective-permute``:
edge slabs are ppermuted, concatenated into a padded block *outside* the
kernel, and the Pallas kernel then re-reads the padded block from HBM.
This module is the design SURVEY.md §7 names as the halo-latency
optimization: ONE kernel per device per iteration that

1. pushes its edge slabs straight into its neighbors' VMEM with
   ``pltpu.make_async_remote_copy`` (RDMA over ICI — the reference's
   ``MPI_Isend`` with the network card writing into the remote ghost ring,
   except here it is the TPU's own DMA engines, no copy through XLA), and
2. computes the stencil level in the same program once its own ghosts
   arrive — no HBM round trip between exchange and compute.

Temporal fusion (``fuse=T``): both kernels also amortize the exchange
itself — the ghost transfers widen to depth ``T*r`` and T level-shrinking
stencil iterations run in-kernel (the shared
``pallas_stencil._iterate_levels`` loop, so quantize/round/tap threading
is identical to the ppermute fused path).  One barrier + one exchange +
one launch per T iterations is the persistent/partitioned-communication
recipe for latency-bound stencils (PAPERS.md: persistent MPI stencils;
the Cerebras wafer-scale in-fabric neighbor transfer), i.e. this tier's
reason to exist at small blocks.  See DESIGN.md "RDMA temporal fusion"
for the band-depth math and the win/retire decision rule.

Overlapped pipeline (``overlap=True``, round 12): both kernels can run
interior-first — start the ghost-band DMAs, compute every output pixel
whose level-0 window needs no ghost byte while they fly, and retire each
phase's receive semaphores immediately before the first compute that
reads them (monolithic: the ``overlap_regions`` 5-region schedule;
tiled: rim-last rotated traversal + an SMEM deferred-wait ledger).
Byte-identical to the serialized order by construction — see DESIGN.md
"Overlapped halo pipeline" and tests/test_overlap.py.

Corner propagation uses the same two-phase trick as halo.py: column slabs
are sent at full padded height *after* the row-ghost receive semaphores
fire, so corners take two hops and no diagonal messages exist.  Ghost
regions with no inbound copy (image boundary, zero mode) are zeroed
locally — writes and inbound RDMA targets are disjoint by construction, so
there is no initialization race (checked by the interpreter's race
detector in tests/test_rdma.py).

Cross-invocation safety: within one invocation, waits on both the send and
receive semaphores retire every DMA before the kernel exits — but back-to-
back invocations (the fori_loop iteration driver) add a hazard the
per-invocation race detector cannot see: a fast device entering iteration
N+1 could push ghost bytes into a slow neighbor's scratch while the
neighbor still computes iteration N.  ``_neighbor_barrier`` closes it with
the canonical start-of-kernel rendezvous on the collective barrier
semaphore: no remote copy is issued until every RDMA partner has entered
the current invocation (tests/test_rdma.py::test_rdma_back_to_back_race
runs the multi-invocation protocol under the race detector).

STATUS: functionally validated — bit-exact against the oracle on the
multi-device CPU mesh under TPU interpret mode (which simulates remote
DMAs, semaphores, and the barrier).  On the one real chip available here
the monolithic kernel compiles via Mosaic and runs in its degenerate 1×1
local form, bit-exact vs the oracle (recorded in BASELINE.md "RDMA on
silicon"), and since round 5 the tiled variant runs on silicon too via
the operand-backed pad (``pad_operand``; the HBM *scratch* form is what
crashes this tunnel's chipless compile helper — see fused_rdma_step's
docstring and BASELINE.md "Round-5 chip session"); multi-chip ICI perf
remains unvalidated — no such hardware exists in this environment.

VMEM budget: the monolithic kernel holds the whole (C, h+2r, w+2r) f32
padded block plus the (C, h, w) output in VMEM (~16 MB limit ≈ 1400²
grey f32 for the pair).  Blocks beyond ``_TILED_VMEM_BYTES`` auto-select
``_rdma_tiled_kernel``: the padded buffer moves to HBM scratch (storage
dtype), the exchange uses tiling-aligned band transfers, and compute
runs the same double-buffered windowed-DMA grid as ``_stencil_kernel``
— per-program VMEM is two ~1 MB window slots regardless of block size
(tests: test_rdma_auto_tiles_beyond_vmem_bound and the forced-tiled
corner/periodic/radius-2 suite).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from parallel_convolution_tpu.ops.collective_ids import collective_id
from parallel_convolution_tpu.ops.filters import Filter
from parallel_convolution_tpu.parallel import channels
from parallel_convolution_tpu.ops.pallas_stencil import (
    DEFAULT_TILE, _from_f32, _iterate_levels, _prefetch_window,
    _round_mode_for, _round_up, _sublane, _to_f32, on_tpu,
)
from parallel_convolution_tpu.utils.jax_compat import (
    hbm_scratch, shape_struct, tpu_compiler_params, tpu_interpret_params,
    vma_of,
)

# Semaphore slots: one (send, recv) pair per direction.
_UP, _DOWN, _LEFT, _RIGHT = 0, 1, 2, 3


def _when(pred):
    """``pl.when`` that statically elides python-bool predicates.

    ``_topology`` reports extent-1 axes as python ``False`` (and periodic
    multi-device axes as python ``True``); resolving those here keeps dead
    guarded ops — remote-copy starts/waits that can never run — out of
    the program entirely instead of emitting always-false branches.  The
    degenerate single-device grid then contains no RDMA constructs at
    all, which is also what lets it run under interpreters that lack the
    remote-DMA/semaphore simulation.
    """
    if isinstance(pred, bool):
        return (lambda f: f()) if pred else (lambda f: None)
    return pl.when(pred)


def _unless(pred):
    """``pl.when(not pred)`` with the same static-bool elision."""
    if isinstance(pred, bool):
        return (lambda f: None) if pred else (lambda f: f())
    return pl.when(jnp.logical_not(pred))


def _neighbor_barrier(up_in, down_in, left_in, right_in, nbr):
    """Start-of-kernel rendezvous with every RDMA partner.

    Arguments are ``_topology``'s returns.  Each device signals the
    global barrier semaphore of every
    existing neighbor, then waits until all of ITS neighbors have signaled
    it.  This closes the cross-invocation race the per-invocation race
    detector cannot see: without it, a fast device's iteration-N+1 remote
    copy could land in a slow neighbor's scratch while that neighbor is
    still computing iteration N.  After the barrier, every partner has
    entered the current invocation — and kernel invocations serialize on a
    core, so all of its previous-invocation reads have retired before any
    new ghost bytes arrive.

    Skew safety: a neighbor can run at most one invocation ahead, because
    completing invocation N+1 requires its own ``wait_recv`` on ghosts we
    only send after passing this barrier — so the wait below can never be
    satisfied by two signals from one fast neighbor standing in for a slow
    one.  Leftover signals (a neighbor already in N+2's barrier) simply
    pre-credit the next wait; counts stay balanced.
    """
    if all(isinstance(e, bool) and not e
           for e in (up_in, down_in, left_in, right_in)):
        # No RDMA partner exists at all (single-device grid, or a torus
        # of pure self-wrap axes): the rendezvous is vacuous — emit no
        # barrier-semaphore traffic.
        return
    dirs = [(up_in, nbr(-1, 0)), (down_in, nbr(+1, 0)),
            (left_in, nbr(0, -1)), (right_in, nbr(0, +1))]
    bsem = pltpu.get_barrier_semaphore()
    n_wait = jnp.int32(0)
    for exists, dev in dirs:
        if isinstance(exists, bool):
            if not exists:
                continue
            pltpu.semaphore_signal(bsem, inc=1, device_id=dev)
            n_wait = n_wait + 1
        else:
            @pl.when(exists)
            def _(dev=dev):
                pltpu.semaphore_signal(bsem, inc=1, device_id=dev)

            n_wait = n_wait + exists.astype(jnp.int32)
    pltpu.semaphore_wait(bsem, n_wait)


def _topology(R, Cc, periodic):
    """Shared neighbor scaffolding: existence predicates + wrap helper.

    Returns ``(up_in, down_in, left_in, right_in, nbr)`` for the calling
    device.  Predicates are python bools when static (periodic axes);
    a periodic self-wrap axis (extent 1) reports False — the kernels
    handle it with local copies, not remote sends.
    """
    x = lax.axis_index("x")
    y = lax.axis_index("y")
    if periodic:
        up_in = down_in = R > 1
        left_in = right_in = Cc > 1
    else:
        # Extent-1 axes have statically no neighbor: report python False
        # (not the always-false traced `x > 0`) so `_when` can elide the
        # dead exchange ops entirely.
        up_in = (x > 0) if R > 1 else False
        down_in = (x < R - 1) if R > 1 else False
        left_in = (y > 0) if Cc > 1 else False
        right_in = (y < Cc - 1) if Cc > 1 else False

    def nbr(dx, dy):
        if periodic:
            return (lax.rem(x + dx + R, R), lax.rem(y + dy + Cc, Cc))
        return (x + dx, y + dy)

    return up_in, down_in, left_in, right_in, nbr


def overlap_regions(h: int, w: int, d: int):
    """The interior-first output partition of one (h, w) block at ghost
    depth ``d``, as ``(interior, row_bands, col_bands)`` — each a list of
    half-open ``(r0, r1, c0, c1)`` output rectangles (empties dropped).

    * ``interior`` needs NO ghost data (its level-0 window is the local
      block) — computed while the row DMAs are in flight;
    * ``row_bands`` (top/bottom strips restricted to interior columns)
      need the ROW ghosts only — computed while the column DMAs fly;
    * ``col_bands`` (full-height left/right strips) read column ghosts
      (and, via the full padded height, the two-hop corners) — computed
      after the column receive semaphores clear.

    The three groups tile the block exactly (no overlap, no gap) for any
    geometry, including degenerate blocks where ``min(h, w) <= 2*d``
    (interior empties out and the bands absorb everything).  Shared by
    the monolithic kernel and the cost model's legality predicate; unit
    pinned in tests/test_overlap.py.
    """
    t, b = min(d, h), max(h - d, min(d, h))
    l, rt = min(d, w), max(w - d, min(d, w))
    interior = [(t, b, l, rt)]
    row_bands = [(0, t, l, rt), (b, h, l, rt)]
    col_bands = [(0, h, 0, l), (0, h, rt, w)]
    keep = lambda rs: [x for x in rs if x[0] < x[1] and x[2] < x[3]]
    return keep(interior), keep(row_bands), keep(col_bands)


def overlap_region_slabs(h: int, w: int, d: int):
    """The labeled interior-first partition with each region's SLAB WAIT
    SET: ``[(label, (r0, r1, c0, c1), frozenset(directions))]`` in the
    partitioned schedule's compute order (interior, top, bottom, left,
    right; empties dropped).

    A region's wait set is exactly the slab channels whose inbound write
    rectangle its ``(rows + 2d, cols + 2d)`` pad-coordinate read window
    overlaps — derived here by interval intersection against the ghost
    write rects (row slabs write interior columns only; column slabs
    write the FULL padded height, which is how the two-hop corners
    ride them).  Shared by the monolithic kernel's per-slab schedule and
    the soundness property test in tests/test_channels.py: no missed
    wait (a race), no extra wait (lost overlap).
    """
    interior, row_bands, col_bands = overlap_regions(h, w, d)
    # Inbound write rectangles per slab channel, in pad coordinates.
    writes = {
        "up": ((0, d), (d, d + w)),
        "down": ((h + d, h + 2 * d), (d, d + w)),
        "left": ((0, h + 2 * d), (0, d)),
        "right": ((0, h + 2 * d), (w + d, w + 2 * d)),
    }

    def waits(rect):
        r0, r1, c0, c1 = rect
        rr, cc = (r0, r1 + 2 * d), (c0, c1 + 2 * d)
        return frozenset(
            name for name, (gr, gc) in writes.items()
            if rr[0] < gr[1] and gr[0] < rr[1]
            and cc[0] < gc[1] and gc[0] < cc[1])

    out = [("interior", rect, waits(rect)) for rect in interior]
    for rect in row_bands:
        out.append(("top" if rect[0] == 0 else "bottom", rect, waits(rect)))
    for rect in col_bands:
        out.append(("left" if rect[2] == 0 else "right", rect, waits(rect)))
    return out


def tiled_window_hazards(wi, wj, *, th, tw, h, w, sub_v, lane=128):
    """Per-slab hazard geometry of one tiled-kernel window: whether the
    ``(wi, wj)`` window's ``(th + 2*sub_v, tw + 2*lane)`` read region
    overlaps each direction's transferred band (the region an in-flight
    slab DMA writes).  Pure geometry — existence predicates (is there a
    neighbor?) are applied by the caller.  Works on python ints (the
    soundness property test) AND traced values (the kernel's deferred-
    wait guards), so the two can never drift.
    """
    ext_h, ext_w = th + 2 * sub_v, tw + 2 * lane
    return {"up": wi == 0, "down": wi * th + ext_h > h + sub_v,
            "left": wj == 0, "right": wj * tw + ext_w > w + lane}


# Packed column-transport staging slots (both kernels, one convention):
# 0/1 = my contiguous outbound left/right slab; 2 = inbound payload for
# my RIGHT ghost (the right neighbor's "left" channel lands here, SPMD
# symmetry); 3 = inbound payload for my LEFT ghost.
_PK_SEND = {"left": 0, "right": 1}
_PK_LAND = {"left": 2, "right": 3}   # where MY channel lands on the receiver
_PK_GHOST = {"right": 2, "left": 3}  # the slot holding MY side's ghost bytes


def _packed_slab_copy(cstage, s, send_sem, recv_sem, nbr):
    """The packed transport's ONE dense stage→stage RDMA for column slab
    ``s`` — shared by both kernels so the staging-slot convention and
    the semaphore pairing can never desynchronize between kernel forms
    (the strided gather/scatter moves to local pack/unpack copies)."""
    return pltpu.make_async_remote_copy(
        cstage.at[_PK_SEND[s.direction]], cstage.at[_PK_LAND[s.direction]],
        send_sem.at[s.sem], recv_sem.at[s.sem], device_id=nbr(*s.nbr))


def _rdma_kernel(in_ref, out_ref, pad, send_sem, recv_sem, *scr, plan,
                 taps, sep, k, r, T, C, h, w, R, Cc, periodic, quantize,
                 convex, round_mode, valid_hw, overlap=False,
                 partitioned=True):
    """One device's program: exchange T·r-deep ghosts in-kernel, then run
    T stencil levels (temporal fusion — ONE exchange buys T iterations).

    ``pad`` is the (C, h+2d, w+2d) f32 working buffer, d = r*T; interior =
    my block, ghost ring = RDMA'd from neighbors (or zeros at a
    non-periodic image boundary).  All slab math mirrors
    halo.halo_exchange at depth d.  The compute is the shared
    level-shrinking loop (``pallas_stencil._iterate_levels``): for T > 1,
    ``valid_hw`` re-zeroes out-of-image positions after every level — the
    oracle's ghost ring at each intermediate — so results stay bit-exact
    with T single-exchange steps.  ``valid_hw=None`` (fuse=1, or the
    periodic torus) statically drops the masks: the validated
    single-level protocol is byte-identical to before.

    ``overlap=True`` is the interior-first pipeline (ROADMAP item 1, the
    persistent/partitioned-MPI overlap recipe): compute is split into the
    :func:`overlap_regions` partition and interleaved with the exchange.
    ``partitioned=True`` (the round-16 default) retires each SLAB
    independently — every region waits on exactly the channels whose
    inbound write rect its read window overlaps
    (:func:`overlap_region_slabs`), so a band computes the moment ITS
    OWN ghosts land; ``partitioned=False`` keeps the r12 phase-granular
    order (both row slabs, then both column slabs) as the A/B reference.
    Bit-exact vs the serialized order either way because every output
    pixel's level chain is a pure function of its own level-0 dependency
    cone, which each region's window contains by construction; the only
    reordering is BETWEEN independent pixels.  Safe vs the in-flight
    DMAs because each region reads only pad cells that are either local
    or already received (inbound ghost writes are disjoint from the
    region reads until their semaphore is waited — the wait-set
    derivation IS that disjointness proof, pinned by the soundness test).

    ``plan`` is the bound channel structure (``parallel.channels``):
    slab rectangles, partners, and semaphore pairing come from the
    cached per-identity plan instead of inline arithmetic.  A plan with
    packed columns (``plan.packed_cols``) receives the staging scratch
    as ``scr[0]`` and moves each column slab as pack → one dense RDMA →
    unpack; the strided plan issues the direct strided copy.  Byte-
    identical by construction — the unpack writes exactly the ghost
    cells the strided copy would.
    """
    cstage = scr[0] if scr else None
    d = r * T
    # Interior + boundary-ghost initialization.  Inbound RDMA targets are
    # exactly the ghost regions owned by an existing neighbor (packed
    # columns land in the staging scratch first), so local writes below
    # never overlap a remote write (no ordering needed).
    pad[:, d : d + h, d : d + w] = _to_f32(in_ref[...])

    up_in, down_in, left_in, right_in, nbr = _topology(R, Cc, periodic)
    exists = {"up": up_in, "down": down_in,
              "left": left_in, "right": right_in}

    zero_row = jnp.zeros((C, d, w), jnp.float32)
    zero_col = jnp.zeros((C, h + 2 * d, d), jnp.float32)

    @_unless(up_in)
    def _():
        pad[:, 0:d, d : d + w] = zero_row

    @_unless(down_in)
    def _():
        pad[:, h + d : h + 2 * d, d : d + w] = zero_row

    if plan.row_wrap:
        # Torus of height 1: my own opposite edge wraps to me (static).
        pad[:, 0:d, d : d + w] = pad[:, h : h + d, d : d + w]
        pad[:, h + d : h + 2 * d, d : d + w] = pad[:, d : 2 * d, d : d + w]

    # Cross-invocation safety: no remote copy may be issued until every
    # RDMA partner has entered THIS invocation (see _neighbor_barrier).
    # Self-wrap axes (periodic R==1 / Cc==1) have python-False predicates
    # and drop out statically.
    _neighbor_barrier(up_in, down_in, left_in, right_in, nbr)

    def compute(regions):
        """T stencil levels for each output rectangle (shared level loop
        — identical op order / quantize / tap threading to the ppermute
        fused path and to the serialized whole-block call; a region's
        level-0 window is the same pad cells the whole-block window
        reads for those pixels, so bytes cannot differ).  Level-0
        out-of-image positions are already exact zeros (boundary ghosts
        zeroed above; the pad-to-multiple rim is zero by the iterate's
        masking invariant), so no level-0 select tier is needed — only
        the per-level rank-1 re-zeroing against the region-shifted
        global-coordinate iotas."""
        for (r0, r1, c0, c1) in regions:
            rows0 = cols0 = None
            if valid_hw is not None:
                rows0 = (lax.axis_index("x") * h - d + r0
                         + lax.broadcasted_iota(
                             jnp.int32, (r1 - r0 + 2 * d, 1), 0))
                cols0 = (lax.axis_index("y") * w - d + c0
                         + lax.broadcasted_iota(
                             jnp.int32, (1, c1 - c0 + 2 * d), 1))
            for c in range(C):
                acc = _iterate_levels(
                    pad[c, r0 : r1 + 2 * d, c0 : c1 + 2 * d],
                    taps=taps, sep=sep, k=k, r=r, T=T,
                    out_hw=(r1 - r0, c1 - c0),
                    quantize=quantize, convex=convex,
                    round_mode=round_mode,
                    rows0=rows0, cols0=cols0, valid_hw=valid_hw)
                out_ref[c, r0:r1, c0:c1] = _from_f32(acc, out_ref.dtype)

    # --- Channel descriptors, bound from the PLAN's slab table.  On a
    # degenerate axis the plan simply has no slab — not even the
    # descriptor is constructed, so the 1x1 program is the serialized
    # local program verbatim, independent of col_mode.
    def _slab_copy(s):
        if s.direction in ("left", "right") and cstage is not None:
            return _packed_slab_copy(cstage, s, send_sem, recv_sem, nbr)
        return pltpu.make_async_remote_copy(
            pad.at[:, s.src_rows[0] : s.src_rows[1],
                   s.src_cols[0] : s.src_cols[1]],
            pad.at[:, s.dst_rows[0] : s.dst_rows[1],
                   s.dst_cols[0] : s.dst_cols[1]],
            send_sem.at[s.sem], recv_sem.at[s.sem], device_id=nbr(*s.nbr))

    copies = {s.direction: _slab_copy(s) for s in plan.slabs()}

    def retire(direction):
        """Retire ONE slab channel: wait my outbound send plus the
        inbound ghost write (the OPPOSITE channel's recv semaphore —
        SPMD symmetry: my top ghost is written by the upper neighbor's
        "down" channel), and unpack the staged payload for packed
        columns.  No-op for directions with no channel."""
        if direction not in copies:
            return
        g = exists[direction]
        _when(g)(copies[direction].wait_send)
        _when(g)(copies[channels.OPPOSITE[direction]].wait_recv)
        if cstage is not None and direction == "left":
            @_when(g)
            def _():
                pad[:, :, 0:d] = cstage[_PK_GHOST["left"]]
        if cstage is not None and direction == "right":
            @_when(g)
            def _():
                pad[:, :, w + d : w + 2 * d] = cstage[_PK_GHOST["right"]]

    def start_cols():
        # Phase 2: column channels at FULL padded height — they carry
        # the just-arrived row ghosts, so corners propagate in two hops
        # exactly as in halo.py.  Callable only after both row slabs
        # retired (the schedules below guarantee it).
        if plan.col_wrap:
            pad[:, :, 0:d] = pad[:, :, w : w + d]
            pad[:, :, w + d : w + 2 * d] = pad[:, :, d : 2 * d]
            return

        @_unless(left_in)
        def _():
            pad[:, :, 0:d] = zero_col

        @_unless(right_in)
        def _():
            pad[:, :, w + d : w + 2 * d] = zero_col

        if cstage is not None:
            # Pack: gather each strided column slab into its contiguous
            # send slot so the RDMA below is one dense descriptor.
            @_when(left_in)
            def _():
                cstage[_PK_SEND["left"]] = pad[:, :, d : 2 * d]

            @_when(right_in)
            def _():
                cstage[_PK_SEND["right"]] = pad[:, :, w : w + d]

        for s in plan.col_slabs:
            _when(exists[s.direction])(copies[s.direction].start)

    # --- Phase 1: rows.  My top d interior rows -> upper neighbor's
    # bottom ghost; my bottom d interior rows -> lower neighbor's top
    # ghost (d <= h, enforced at the launch).
    for s in plan.row_slabs:
        _when(exists[s.direction])(copies[s.direction].start)

    # --- Schedule.  Each region computes after exactly its wait set has
    # retired; the first column-ghost reader starts phase 2 (which
    # itself requires both row slabs landed — full-height column slabs
    # read the row ghosts).
    regions = (overlap_region_slabs(h, w, d) if overlap
               else [("whole", (0, h, 0, w),
                      frozenset(("up", "down", "left", "right")))])
    retired: set = set()
    cols_started = [False]

    def ensure(waits):
        for direction in ("up", "down"):
            if direction in waits and direction not in retired:
                retire(direction)
                retired.add(direction)
        # Start the column phase the MOMENT both row slabs have retired
        # (full-height column slabs read the row ghosts — the corner
        # dependency), not only when a column reader appears: the
        # regions computed between here and the first column reader
        # (the bottom band, in the partitioned schedule) then run under
        # the in-flight column DMAs.
        if not cols_started[0] and {"up", "down"} <= retired:
            start_cols()
            cols_started[0] = True
        if waits & {"left", "right"} and not cols_started[0]:
            for direction in ("up", "down"):
                if direction not in retired:
                    retire(direction)
                    retired.add(direction)
            start_cols()
            cols_started[0] = True
        for direction in ("left", "right"):
            if direction in waits and direction not in retired:
                retire(direction)
                retired.add(direction)

    if overlap and partitioned:
        # Per-slab: interior under the in-flight row DMAs (empty wait
        # set), each band the moment its own ghosts land, the bottom
        # band under the in-flight column DMAs.
        for _label, rect, waits in regions:
            ensure(waits)
            compute([rect])
    elif overlap:
        # r12 phase-granular order (the A/B reference): interior under
        # the row DMAs, both row slabs retire together, the row bands
        # hide the column phase, both column slabs retire together.
        compute([rect for _l, rect, ws in regions if not ws])
        ensure(frozenset(("up", "down")))  # retires rows AND starts cols
        compute([rect for _l, rect, ws in regions
                 if ws and not ws & {"left", "right"}])
        ensure(frozenset(("left", "right")))
        compute([rect for _l, rect, ws in regions
                 if ws & {"left", "right"}])
    else:
        # Serialized: the whole exchange completes before the one
        # whole-block compute — the validated pre-overlap protocol
        # (ensure() starts the column phase once both row slabs retire).
        ensure(frozenset(("up", "down")))
        ensure(frozenset(("left", "right")))
        compute([rect for _l, rect, _w in regions])

    # Channel hygiene: every live slab's semaphores retire before exit
    # even when its band was empty (degenerate geometry can drop a band
    # whose channel still flew); ensure() starts the column phase here
    # if nothing did earlier.
    ensure(frozenset(("up", "down")))
    ensure(frozenset(("left", "right")))


# ---------------------------------------------------------------------------
# Tiled variant: HBM-resident padded buffer + windowed-DMA compute grid.
# ---------------------------------------------------------------------------
#
# The monolithic kernel above holds the whole (C, h+2r, w+2r) f32 block in
# VMEM — a hard ~16 MB bound (≈2048² grey f32).  The tiled variant lifts
# it: the padded buffer lives in HBM scratch (storage dtype, not f32), the
# ghost exchange lands there, and the compute phase is the same
# double-buffered windowed-DMA grid as ``_stencil_kernel``.  Two design
# points keep HBM DMA *starts* tiling-aligned (Mosaic requires aligned
# slice starts; interpret mode does not check — see ``_sublane``):
#
# 1. **Aligned-band transfers.**  Ghost slabs are r*T wide (T = temporal
#    fusion depth), which is never aligned.  Instead each transfer moves
#    a full (sublane, 128)-aligned band — ``sub_v`` rows / 128 cols of
#    interior — whose LAST (first) r*T rows/cols land exactly on the
#    receiver's ghost positions (hence the r*T <= min(sub_v, 128)
#    constraint); the rest of the band falls on never-read buffer and is
#    masked at compute.
# 2. **No ghost zeroing.**  Image-boundary ghosts stay uninitialized in
#    HBM; every compute window applies one select against the block's
#    valid [row_lo, row_hi) × [col_lo, col_hi) box (which also kills any
#    non-finite DMA garbage — a multiplicative mask would leak NaN).
#
# VMEM per program: 2 window slots of (th + 2·sub_v, tw + 256) storage
# dtype — ~1.7 MB at the 256×512 f32 default, independent of block size.
#
# Honesty note on alignment coverage: the scheme is FULLY aligned
# (every start and every extent) precisely when the block shape itself
# is (sub_v, 128)-aligned — then the h/w-derived starts (row h, h+sub_v;
# col w, w+LANE) and the orthogonal extents (h, w) are all multiples.
# For non-multiple blocks, both those starts and extents are raw h/w,
# and whether real Mosaic constrains HBM↔HBM copies that way cannot be
# validated in this environment (the tiled path's multi-chip form only
# runs under the interpreter; single-chip silicon runs the degenerate
# no-exchange form — same standing caveat as the monolithic STATUS).
# If silicon rejects raw-h/w transfers, the fix is at the CALLER: pad
# the global image so blocks are (sub_v, 128)-multiples — the framework
# already pads to mesh multiples (`parallel/step._prepare`) and the
# valid-box mask here already ignores rim, so widening that padding is
# a one-line change with no kernel edits.

_TILED_VMEM_BYTES = 10 * 2**20  # monolithic-kernel budget before auto-tiling


def _and2(a, b):
    """``a & b`` with python-bool static folding on either side."""
    if isinstance(a, bool):
        return b if a else False
    if isinstance(b, bool):
        return a if b else False
    return jnp.logical_and(a, b)


def _or2(a, b):
    """``a | b`` with python-bool static folding on either side."""
    if isinstance(a, bool):
        return True if a else b
    if isinstance(b, bool):
        return True if b else a
    return jnp.logical_or(a, b)


def _rdma_tiled_kernel(in_ref, out_ref, pad, *rest, plan, taps, sep, k, r,
                       T, C, h, w, R, Cc, periodic, quantize, convex, th,
                       tw, sub_v, round_mode, valid_hw, overlap=False,
                       partitioned=True):
    """HBM-pad windowed variant; ``overlap=True`` is the interior-first
    pipeline at window granularity.

    Serialized (``overlap=False``): the step-0 program completes the
    whole two-phase exchange before any window is copied — the validated
    protocol, byte-identical to before this knob existed.

    Overlapped: step 0 only STARTS the row-band DMAs; the window
    traversal is rotated by one on both grid axes so the rim windows
    (the only ones whose (ext_h, ext_w) read window reaches a ghost
    band) are visited last, and an SMEM ledger defers every semaphore
    wait to the first window whose read window actually overlaps a
    still-pending transfer — interior windows stream and compute under
    the in-flight exchange.  ``partitioned=True`` (round 16) is the
    PER-SLAB ledger: one flag per slab channel (up/down/left/right —
    the fused ghost depth rides each band's geometry) plus a
    column-phase-started flag, so a window waits on exactly the slabs
    its read region overlaps (:func:`tiled_window_hazards`) and a tile
    computes the moment ITS OWN ghosts land.  ``partitioned=False``
    keeps the r12 3-state phase ledger (``flags[0]``: 0 = rows in
    flight, 1 = rows done + columns in flight, 2 = all landed) as the
    A/B reference.  Sound either way because grid programs run
    sequentially on one core with shared scratch (the same property the
    step-0-exchange design already relies on), waits recreate the
    identical copy descriptors from the bound channel plan, the ledger
    transitions are monotonic, and the rim windows that trigger each
    retirement provably exist in every grid (window row 0 / last row,
    column 0 / last column).  The column phase still starts only after
    BOTH row receives (its full-height bands carry the two-hop corner
    bytes), so the exchange protocol — order, slabs, semaphore pairing
    — is unchanged; only the waits move later and split finer.

    ``plan`` is the bound channel structure (``parallel.channels``).
    ``plan.packed_cols`` receives the HBM staging scratch as ``rest[0]``
    and moves each column band as pack → one dense RDMA → unpack
    (byte-identical: the unpack writes exactly the band the strided
    copy would); the strided plan issues the direct strided band copy.
    """
    if plan.packed_cols:
        cstage, win, wsems, xsem, send_sem, recv_sem, flags = rest
    else:
        win, wsems, xsem, send_sem, recv_sem, flags = rest
        cstage = None
    LANE = 128
    d = r * T  # ghost depth; <= min(sub_v, LANE) so one band carries it
    ext_h, ext_w = th + 2 * sub_v, tw + 2 * LANE
    c, vi, vj = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    ni, nj = pl.num_programs(1), pl.num_programs(2)
    step = (c * ni + vi) * nj + vj

    up_in, down_in, left_in, right_in, nbr = _topology(R, Cc, periodic)
    exists = {"up": up_in, "down": down_in,
              "left": left_in, "right": right_in}

    row_remote = R > 1   # remote row-band DMAs exist in this program
    col_remote = Cc > 1  # remote column-band DMAs exist
    # Periodic self-wrap columns on a multi-row grid: the local wrap
    # copies read the FULL padded height, so under overlap they must
    # run after the row receives — i.e. at the column-phase transition,
    # not at step 0 — and windows reading column ghosts must wait on
    # that transition even though no remote column DMA exists.
    col_wrap_deferred = periodic and Cc == 1 and row_remote
    engage = overlap and (row_remote or col_remote)
    # Window (wi, wj) this program computes: the rotated traversal
    # visits rim windows last under the overlapped pipeline.  The out
    # BlockSpec index map applies the SAME rotation (fused_rdma_step).
    if engage:
        i, j = lax.rem(vi + 1, ni), lax.rem(vj + 1, nj)
    else:
        i, j = vi, vj

    # Ledger slots in the SMEM scratch (shared across the sequential
    # grid programs of one core): slot 0 is the r12 3-state phase
    # ledger; slots 1..5 are the per-slab map — each slab's landed flag
    # plus the column-phase-started flag.
    F_PHASE, F_UP, F_DOWN, F_COL, F_LEFT, F_RIGHT = 0, 1, 2, 3, 4, 5

    # -- exchange pieces, each buildable at any program (descriptors are
    # bound from the PLAN's slab table; a wait only needs the semaphore).
    def _local_row_wrap():
        for src, dst, sl in (((sub_v, 2 * sub_v),
                              (h + sub_v, h + 2 * sub_v), _UP),
                             ((h, h + sub_v), (0, sub_v), _DOWN)):
            cp = pltpu.make_async_copy(
                pad.at[:, src[0] : src[1], LANE : LANE + w],
                pad.at[:, dst[0] : dst[1], LANE : LANE + w],
                send_sem.at[sl])
            cp.start()
            cp.wait()

    def _local_col_wrap():
        for src, dst, sl in (((LANE, 2 * LANE),
                              (w + LANE, w + 2 * LANE), _LEFT),
                             ((w, w + LANE), (0, LANE), _RIGHT)):
            cp = pltpu.make_async_copy(
                pad.at[:, :, src[0] : src[1]],
                pad.at[:, :, dst[0] : dst[1]],
                send_sem.at[sl])
            cp.start()
            cp.wait()

    def _slab_copy(s):
        if s.direction in ("left", "right") and cstage is not None:
            return _packed_slab_copy(cstage, s, send_sem, recv_sem, nbr)

        def ref(rows, cols):
            if rows is None:  # column bands run the full padded height
                return pad.at[:, :, cols[0] : cols[1]]
            return pad.at[:, rows[0] : rows[1], cols[0] : cols[1]]

        return pltpu.make_async_remote_copy(
            ref(s.src_rows, s.src_cols), ref(s.dst_rows, s.dst_cols),
            send_sem.at[s.sem], recv_sem.at[s.sem], device_id=nbr(*s.nbr))

    def _row_copies():
        return _slab_copy(plan.slab("up")), _slab_copy(plan.slab("down"))

    def _col_copies():
        return _slab_copy(plan.slab("left")), _slab_copy(plan.slab("right"))

    def _pack_cols():
        # Gather each strided column band into its contiguous send slot
        # (aligned local HBM copies) so the RDMA is one dense descriptor.
        for direction, src in (("left", pad.at[:, :, LANE : 2 * LANE]),
                               ("right", pad.at[:, :, w : w + LANE])):
            @_when(exists[direction])
            def _(direction=direction, src=src):
                cp = pltpu.make_async_copy(
                    src, cstage.at[_PK_SEND[direction]], xsem)
                cp.start()
                cp.wait()

    def _unpack_col(direction):
        dst = (pad.at[:, :, 0:LANE] if direction == "left"
               else pad.at[:, :, w + LANE : w + 2 * LANE])
        cp = pltpu.make_async_copy(
            cstage.at[_PK_GHOST[direction]], dst, xsem)
        cp.start()
        cp.wait()

    def _start_rows():
        su, sd = _row_copies()
        _when(up_in)(su.start)
        _when(down_in)(sd.start)

    def _retire_up():
        # My top ghost is written by my upper neighbor's "down" channel
        # (it signals MY recv_sem[_DOWN]) — SPMD symmetry; pairing my
        # outbound up-send's wait here keeps each slab's semaphore
        # hygiene self-contained.  No row channels (R==1) = statically
        # nothing to retire: these helpers are traced inside guards
        # whose predicates can be dynamic (the legacy phase ledger's
        # need_any), so they must be constructible on ANY grid — the
        # same rule the monolithic kernel's copies-dict lookup applies.
        if not plan.row_slabs:
            return
        su, sd = _row_copies()
        _when(up_in)(su.wait_send)
        _when(up_in)(sd.wait_recv)

    def _retire_down():
        if not plan.row_slabs:
            return
        su, sd = _row_copies()
        _when(down_in)(sd.wait_send)
        _when(down_in)(su.wait_recv)

    def _wait_rows():
        _retire_up()
        _retire_down()

    def _start_cols():
        # Phase 2 initiation: column bands at FULL padded height — the
        # transferred bands carry the just-arrived row ghosts, so
        # corners propagate in two hops exactly as in halo.py / the
        # monolithic kernel.  Callable only after the row phase landed.
        if periodic and Cc == 1:
            _local_col_wrap()
        elif col_remote:
            if cstage is not None:
                _pack_cols()
            sl_, sr = _col_copies()
            _when(left_in)(sl_.start)
            _when(right_in)(sr.start)

    def _retire_left():
        if not plan.col_slabs:
            return
        sl_, sr = _col_copies()
        _when(left_in)(sl_.wait_send)
        _when(left_in)(sr.wait_recv)
        if cstage is not None:
            _when(left_in)(lambda: _unpack_col("left"))

    def _retire_right():
        if not plan.col_slabs:
            return
        sl_, sr = _col_copies()
        _when(right_in)(sr.wait_send)
        _when(right_in)(sl_.wait_recv)
        if cstage is not None:
            _when(right_in)(lambda: _unpack_col("right"))

    def _wait_cols():
        _retire_left()
        _retire_right()

    @pl.when(step == 0)
    def _exchange():
        # Interior: one aligned HBM->HBM copy (dst starts at (sub_v, 128)).
        intr = pltpu.make_async_copy(
            in_ref, pad.at[:, sub_v : sub_v + h, LANE : LANE + w], xsem)
        intr.start()
        intr.wait()

        _neighbor_barrier(up_in, down_in, left_in, right_in, nbr)

        # Phase 1: row bands (interior cols only; ghost cols not yet
        # live).  Torus of height 1: own opposite edge, local aligned
        # copies — complete synchronously here either way.
        if periodic and R == 1:
            _local_row_wrap()
        if not engage:
            # Serialized: the whole exchange completes before any window.
            if row_remote:
                _start_rows()
                _wait_rows()
            if periodic and Cc == 1:
                _local_col_wrap()
            elif col_remote:
                _start_cols()
                _wait_cols()
        elif not partitioned:
            # r12 phase ledger (the A/B reference).
            if row_remote:
                _start_rows()
                flags[F_PHASE] = jnp.int32(0)
            else:
                # Rows already complete (local wrap / no axis): the
                # column phase can start under the very first windows.
                _start_cols()
                flags[F_PHASE] = jnp.int32(1 if col_remote else 2)
        else:
            # Per-slab ledger: every slab flag initialized here (SMEM
            # scratch is uninitialized and shared across programs).
            if row_remote:
                _start_rows()
                flags[F_UP] = jnp.int32(0)
                flags[F_DOWN] = jnp.int32(0)
                flags[F_COL] = jnp.int32(0)
            else:
                _start_cols()
                flags[F_UP] = jnp.int32(1)
                flags[F_DOWN] = jnp.int32(1)
                flags[F_COL] = jnp.int32(1)
            flags[F_LEFT] = jnp.int32(0 if col_remote else 1)
            flags[F_RIGHT] = jnp.int32(0 if col_remote else 1)

    # -- deferred-wait guard: runs before a window copy is ISSUED, with
    # the window's indices — waits exactly when that window's read
    # region overlaps a still-pending transfer, advancing the ledger.
    def _ensure(wi, wj):
        if not engage:
            return
        # Geometric touch: the (ext_h, ext_w) read window vs the four
        # transferred bands (tiled_window_hazards — shared with the
        # soundness property test); hazardous only where an actual
        # transfer writes (the _in predicates — non-live ghost regions
        # hold garbage the valid-box mask kills, no ordering needed).
        hz = tiled_window_hazards(wi, wj, th=th, tw=tw, h=h, w=w,
                                  sub_v=sub_v)
        top, bot, lef, rig = hz["up"], hz["down"], hz["left"], hz["right"]
        if col_remote:
            need_col = _or2(_and2(lef, left_in), _and2(rig, right_in))
        elif col_wrap_deferred:
            # Self-wrap ghosts are VALID data (periodic valid box), but
            # written only at the column transition — any reader waits.
            need_col = _or2(lef, rig)
        else:
            need_col = False
        if not partitioned:
            # r12 3-state phase ledger (kept as the A/B reference).
            need_row = (_or2(_and2(top, up_in), _and2(bot, down_in))
                        if row_remote else False)
            need_any = _or2(need_row, need_col)

            @_when(_and2(need_any, flags[F_PHASE] == 0))
            def _():
                _wait_rows()
                _start_cols()
                flags[F_PHASE] = jnp.int32(1 if col_remote else 2)

            if col_remote and need_col is not False:
                @_when(_and2(need_col, flags[F_PHASE] == 1))
                def _():
                    _wait_cols()
                    flags[F_PHASE] = jnp.int32(2)
            return
        # Per-slab retirement: each slab the moment a window first
        # overlaps its band — the window computes once ITS OWN ghosts
        # land, not once the whole phase does.
        if row_remote:
            @_when(_and2(top, flags[F_UP] == 0))
            def _():
                _retire_up()
                flags[F_UP] = jnp.int32(1)

            @_when(_and2(bot, flags[F_DOWN] == 0))
            def _():
                _retire_down()
                flags[F_DOWN] = jnp.int32(1)
        if need_col is not False:
            # Column transition: the full-height column bands read the
            # row ghosts, so any still-pending row slab retires first.
            @_when(_and2(need_col, flags[F_COL] == 0))
            def _():
                if row_remote:
                    @_when(flags[F_UP] == 0)
                    def _():
                        _retire_up()

                    @_when(flags[F_DOWN] == 0)
                    def _():
                        _retire_down()

                    flags[F_UP] = jnp.int32(1)
                    flags[F_DOWN] = jnp.int32(1)
                _start_cols()
                flags[F_COL] = jnp.int32(1)
        if col_remote:
            # Per-slab column retirement (guarded on the phase having
            # started — never wait a DMA that was not issued).
            @_when(_and2(lef, _and2(flags[F_COL] == 1,
                                    flags[F_LEFT] == 0)))
            def _():
                _retire_left()
                flags[F_LEFT] = jnp.int32(1)

            @_when(_and2(rig, _and2(flags[F_COL] == 1,
                                    flags[F_RIGHT] == 0)))
            def _():
                _retire_right()
                flags[F_RIGHT] = jnp.int32(1)

    # --- Compute: the _stencil_kernel windowed-DMA grid over the HBM pad.
    def window_copy(cc, ai, aj, s):
        if engage:
            wi, wj = lax.rem(ai + 1, ni), lax.rem(aj + 1, nj)
        else:
            wi, wj = ai, aj
        _ensure(wi, wj)
        return pltpu.make_async_copy(
            pad.at[cc, pl.ds(wi * th, ext_h), pl.ds(wj * tw, ext_w)],
            win.at[s], wsems.at[s])

    slot = _prefetch_window(window_copy)

    # Valid box of the block in padded coords (ghost ring d deep); outside
    # it live image-boundary ghosts (zero semantics) and never-written
    # buffer.  Periodic: EVERY ghost is valid (filled by wrap or remote
    # band) even on a self-wrap axis, where the exchange predicate is
    # False.
    def _i32(p):
        return jnp.int32(p) if isinstance(p, bool) else p.astype(jnp.int32)

    row_lo = sub_v - (d if periodic else d * _i32(up_in))
    row_hi = sub_v + h + (d if periodic else d * _i32(down_in))
    col_lo = LANE - (d if periodic else d * _i32(left_in))
    col_hi = LANE + w + (d if periodic else d * _i32(right_in))

    w0h, w0w = th + 2 * d, tw + 2 * d
    rows = (i * th + (sub_v - d)
            + lax.broadcasted_iota(jnp.int32, (w0h, 1), 0))
    cols = (j * tw + (LANE - d)
            + lax.broadcasted_iota(jnp.int32, (1, w0w), 1))
    ok = (((rows >= row_lo) & (rows < row_hi))
          & ((cols >= col_lo) & (cols < col_hi)))
    cur = _to_f32(win[slot][sub_v - d : sub_v + d + th,
                           LANE - d : LANE + d + tw])
    cur = jnp.where(ok, cur, 0.0)

    # T in-VMEM levels (shared level loop).  For T > 1 the per-level
    # re-zeroing needs GLOBAL image coordinates (the pad-to-multiple rim
    # is in-block but out-of-image); pad row p maps to global row
    # x*h + p - sub_v, so shift the hoisted pad-coordinate iotas.  The
    # tier-1 select above already killed every non-finite DMA garbage
    # value, so the rank-1 multiplies are exact.
    rows0 = cols0 = None
    if valid_hw is not None:
        rows0 = rows + (lax.axis_index("x") * h - sub_v)
        cols0 = cols + (lax.axis_index("y") * w - LANE)
    acc = _iterate_levels(cur, taps=taps, sep=sep, k=k, r=r, T=T,
                          out_hw=(th, tw), quantize=quantize, convex=convex,
                          round_mode=round_mode, rows0=rows0, cols0=cols0,
                          valid_hw=valid_hw)
    out_ref[0] = _from_f32(acc, out_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("filt", "grid", "boundary", "quantize", "out_dtype",
                     "interpret", "tiled", "tile", "pad_operand", "fuse",
                     "valid_hw", "overlap", "col_mode", "partitioned"),
)
def fused_rdma_step(
    block: jnp.ndarray,
    filt: Filter,
    grid: tuple[int, int],
    boundary: str = "zero",
    quantize: bool = True,
    out_dtype=None,
    interpret=None,
    tiled: bool | None = None,
    tile: tuple[int, int] | None = None,
    pad_operand: bool | None = None,
    fuse: int = 1,
    valid_hw: tuple[int, int] | None = None,
    overlap: bool = False,
    col_mode: str = "strided",
    partitioned: bool = True,
) -> jnp.ndarray:
    """``fuse`` halo-fused stencil iterations, entirely inside one kernel.

    Must be called inside ``shard_map`` over the ('x','y') mesh; ``block``
    is the local (C, h, w) tile.  Semantically identical to a depth
    ``r*fuse`` ``halo.halo_exchange`` followed by ``fuse`` level-shrinking
    correlates (+ optional u8 quantization per level) — see
    tests/test_rdma.py for the bit-exactness proof.

    ``fuse=T>1`` is temporal fusion INSIDE the RDMA tier: the ghost
    transfers widen to depth T·r and the kernel runs T stencil levels
    before returning to HBM — one exchange setup, one neighbor barrier,
    one kernel launch per T iterations, which is exactly the lever the
    latency-bound small-block regime this tier exists for needs
    (DESIGN.md "RDMA temporal fusion").  It requires ``valid_hw`` — the
    global (H, W) image extent — for zero boundaries, because each
    intermediate level must re-zero out-of-image positions (the oracle's
    ghost ring); the caller (``parallel/step.py``) threads it
    automatically.  Constraints: ``min(h, w) >= r*fuse`` (monolithic slab
    depth), and for the tiled variant ``r*fuse <= min(sublane, 128)`` so
    the one-tile-deep aligned transfer bands still carry every live ghost
    row/col.

    ``tiled=None`` auto-selects: blocks whose monolithic VMEM footprint
    (f32 padded buffer + output) exceeds ``_TILED_VMEM_BYTES`` use the
    HBM-pad + windowed-DMA variant (``_rdma_tiled_kernel``); small blocks
    keep the all-VMEM kernel (lower latency, no per-window DMA).  ``tile``
    sets the tiled variant's output tile (default ``DEFAULT_TILE``).

    ``overlap=True`` selects the interior-first overlapped pipeline in
    BOTH kernels (see ``_rdma_kernel`` / ``_rdma_tiled_kernel``): the
    ghost-band DMAs fly while ghost-free compute proceeds, and the
    receive waits retire immediately before the first compute that
    reads them — byte-identical to the serialized order for every
    (boundary, fuse, grid, storage) combination, because only
    independent per-pixel work is reordered (proven in
    tests/test_overlap.py; multi-device cells need the faithful
    interpreter or silicon).  The monolithic kernel always emits the
    region-split program when asked (degenerate regions clamp away);
    the tiled kernel engages only when a remote axis exists — on a 1x1
    grid its program is the serialized one verbatim.  The dispatch
    layer (``parallel/step.py``) resolves when this knob is on; callers
    there never pass it blindly.

    ``col_mode`` selects the COLUMN-SLAB transport (round 16, the
    derived-datatypes A/B): ``"strided"`` (the default — the historical
    program) issues the direct strided copy; ``"packed"`` gathers each
    column slab into a contiguous staging buffer (VMEM for the
    monolithic kernel, HBM for the tiled one), moves it with ONE dense
    RDMA, and scatters it into the ghost ring on the receiver —
    byte-identical by construction, a pure descriptor-shape trade the
    cost model prices (``tuning.costmodel.pick_col_mode``; the dispatch
    layer resolves ``"auto"`` before calling here).  On a grid with no
    remote column partner both modes compile the identical statically-
    elided program (no staging scratch is even allocated).

    ``partitioned`` selects the completion granularity of the
    overlapped pipeline (round 16): ``True`` (default) retires each
    ghost slab independently — a region/window computes the moment its
    own ghosts land (``parallel.channels`` per-slab semaphore map);
    ``False`` keeps the r12 phase-granular ledger as the A/B reference.
    Serialized launches (``overlap=False``) ignore it.

    ``pad_operand`` (tiled variant only) chooses how the HBM pad buffer
    is provided.  ``False``: as an ``pltpu.MemorySpace.HBM``
    ``scratch_shapes`` entry — the natural form, but the round-5 probe
    ladder pinned THAT construct as what crashes this tunnel's chipless
    remote compile helper (``scripts/tiled_repro_probe.py`` rung a vs
    a0; ``evidence/tiled_repro_r5.jsonl``).  ``True``: as a second
    ANY-space OUTPUT that the caller discards — allocated uninitialized
    by XLA just like the scratch it replaces (no init cost), and
    nothing the helper rejects is used.  ``None`` resolves to ``True``
    when actually compiling for silicon (``interpret is False``),
    ``False`` under the interpreter — so interpreter tests keep
    covering the scratch form regardless of the process's global
    backend.
    """
    from parallel_convolution_tpu.resilience.faults import fault_point
    from parallel_convolution_tpu.utils.config import BOUNDARIES

    # Trace-time consult: models the in-kernel exchange failing to build
    # (the round-5 tiled-RDMA compile crash class).  Zero overhead when no
    # fault plan is installed, and runs only while tracing — never on the
    # device hot path.
    fault_point("halo_exchange")
    if boundary not in BOUNDARIES:
        raise ValueError(f"boundary must be one of {BOUNDARIES}, got {boundary!r}")
    if col_mode not in channels.COL_MODES:
        raise ValueError(
            f"col_mode must be one of {channels.COL_MODES} at the kernel "
            f"layer ('auto' is resolved by dispatch — "
            f"parallel.step.resolve_col_mode), got {col_mode!r}")
    if interpret is None:
        interpret = not on_tpu()
    if interpret is True:
        # Plain-bool callers (the step builder resolves interpret from the
        # MESH platform) get the DMA-faithful interpreter configuration.
        interpret = tpu_interpret_params(dma_execution_mode="on_wait")
    if out_dtype is None:
        out_dtype = block.dtype
    C, h, w = block.shape
    r, k = filt.radius, filt.size
    T = int(fuse)
    if T < 1:
        raise ValueError(f"fuse must be >= 1, got {fuse}")
    d = r * T
    if min(h, w) < d:
        raise ValueError(
            f"block {(h, w)} smaller than the ghost depth r*fuse = {d} "
            f"(radius {r} x fuse {T}); use a smaller fuse or coarser mesh")
    periodic = boundary == "periodic"
    if T > 1 and not periodic and valid_hw is None:
        raise ValueError(
            "fuse > 1 with a zero boundary needs valid_hw — the global "
            "(H, W) image extent — so every intermediate level can re-zero "
            "its out-of-image positions (the oracle's ghost ring)")
    # Normalized static mask key for the kernels: None statically drops
    # per-level masking (single level, or the torus where every position
    # is valid).
    kern_valid = (None if (T == 1 or periodic)
                  else (int(valid_hw[0]), int(valid_hw[1])))
    sep = None  # rank-1 split saves little at one level; keep 2D order
    taps = tuple(float(t) for t in filt.taps.reshape(-1))
    vma = vma_of(block)
    cparams = tpu_compiler_params(
        collective_id=collective_id("rdma_halo_stencil"),
        has_side_effects=True,
    )

    sub_v = _sublane(block.dtype)
    if tiled is None:
        mono_bytes = (C * (h + 2 * d) * (w + 2 * d) * 4
                      + C * h * w * jnp.dtype(out_dtype).itemsize)
        if col_mode == "packed" and grid[1] > 1:
            # The packed transport's 4 f32 staging slots live in VMEM
            # for the monolithic kernel — they count against the same
            # budget (mirrored in costmodel.rdma_is_tiled).
            mono_bytes += 4 * C * (h + 2 * d) * d * 4
        tiled = mono_bytes > _TILED_VMEM_BYTES
        if tiled and (d > min(sub_v, 128) or h < sub_v or w < 128):
            # Silently falling back to the monolithic kernel here would
            # trade this clear error for an opaque Mosaic VMEM failure.
            raise ValueError(
                f"block {(C, h, w)} needs ~{mono_bytes >> 20} MB of VMEM "
                f"(over the {_TILED_VMEM_BYTES >> 20} MB monolithic "
                f"budget) but the tiled kernel requires ghost depth "
                f"r*fuse <= {min(sub_v, 128)} (got {d}) and blocks >= "
                f"({sub_v}, 128); use a finer or differently-shaped mesh, "
                "or a shallower fuse")

    # interpret here is False (silicon) or InterpretParams — the barrier
    # form is needed exactly when XLA (not Mosaic) executes the kernel.
    # round_mode is dead when not quantizing: skip the selector (and the
    # compiled-probe guard it consults on silicon) entirely.
    round_mode = (_round_mode_for(taps, interpret is not False)
                  if quantize else "rint")
    # The persistent channel plan: descriptor geometry bound ONCE per
    # exchange identity (parallel.channels) and fetched from the
    # process-global cache by every trace that shares it — fused
    # iteration chunks, converge chunks, multigrid V-cycle levels.
    ckey = channels.ChannelKey(
        grid=(int(grid[0]), int(grid[1])), block_hw=(h, w), radius=r,
        fuse=T, dtype=str(jnp.dtype(block.dtype).name), boundary=boundary,
        kernel="tiled" if tiled else "monolithic", col_mode=col_mode)
    plan = channels.plan_for(ckey)
    if not tiled:
        kernel = functools.partial(
            _rdma_kernel, plan=plan, taps=taps, sep=sep, k=k, r=r, T=T,
            C=C, h=h, w=w, R=grid[0], Cc=grid[1], periodic=periodic,
            quantize=quantize, convex=filt.convex, round_mode=round_mode,
            valid_hw=kern_valid, overlap=bool(overlap),
            partitioned=bool(partitioned),
        )
        scratch = [
            pltpu.VMEM((C, h + 2 * d, w + 2 * d), jnp.float32),
            pltpu.SemaphoreType.DMA((4,)),
            pltpu.SemaphoreType.DMA((4,)),
        ]
        if plan.packed_cols:
            # Column staging: 2 contiguous outbound + 2 inbound slots
            # (the dense-RDMA endpoints of the packed transport).
            scratch.append(
                pltpu.VMEM((4, C, h + 2 * d, d), jnp.float32))
        return pl.pallas_call(
            kernel,
            out_shape=shape_struct((C, h, w), out_dtype, vma),
            scratch_shapes=scratch,
            compiler_params=cparams,
            interpret=interpret,
        )(block)

    # ---- tiled variant ----
    if d > min(sub_v, 128):
        raise ValueError(
            f"tiled RDMA kernel needs ghost depth r*fuse <= "
            f"{min(sub_v, 128)} (the aligned transfer bands are one "
            f"({sub_v}, 128) tile deep and their trailing/leading r*fuse "
            f"rows/cols must all be live ghosts), got r*fuse = {d}")
    if h < sub_v or w < 128:
        # A band narrower than the block would make src/dst of the band
        # copies overlap (undefined for real DMA engines even though the
        # interpreter's atomic copies happen to produce the right bytes).
        raise ValueError(
            f"tiled RDMA kernel needs blocks >= ({sub_v}, 128) for "
            f"non-overlapping band transfers, got {(h, w)}; blocks this "
            "small fit the monolithic kernel (tiled=False) unless the "
            "other dimension is huge — then reshape the mesh")
    LANE = 128
    t0, t1 = tile if tile is not None else DEFAULT_TILE
    th = min(_round_up(t0, sub_v), _round_up(h, sub_v))
    tw = min(_round_up(t1, LANE), _round_up(w, LANE))
    gh, gw = -(-h // th), -(-w // tw)
    ext_h, ext_w = th + 2 * sub_v, tw + 2 * LANE
    # Pad buffer: interior at (sub_v, LANE); sized so the LAST window
    # [gh-1·th, +ext_h) fits — any rim beyond the ghost ring is never
    # valid (masked) and never sent (transfers address interior/ghost
    # coordinates only).
    h_pad = max((gh - 1) * th + ext_h, h + 2 * sub_v)
    w_pad = max((gw - 1) * tw + ext_w, w + 2 * LANE)

    kernel = functools.partial(
        _rdma_tiled_kernel, plan=plan, taps=taps, sep=sep, k=k, r=r, T=T,
        C=C, h=h, w=w, R=grid[0], Cc=grid[1], periodic=periodic,
        quantize=quantize, convex=filt.convex, th=th, tw=tw, sub_v=sub_v,
        round_mode=round_mode, valid_hw=kern_valid, overlap=bool(overlap),
        partitioned=bool(partitioned),
    )
    # Rim-last traversal under the overlapped pipeline: the out index
    # map applies the same +1 rotation the kernel applies to its window
    # indices, so program p's out block IS the window it computed.
    engage = bool(overlap) and (grid[0] > 1 or grid[1] > 1)
    vmem_scratch = [
        pltpu.VMEM((2, ext_h, ext_w), block.dtype),
        pltpu.SemaphoreType.DMA((2,)),
        pltpu.SemaphoreType.DMA(()),
        pltpu.SemaphoreType.DMA((4,)),
        pltpu.SemaphoreType.DMA((4,)),
        pltpu.SMEM((8,), jnp.int32),  # deferred-wait ledger: slot 0 =
        #                               r12 phase state, 1..5 = per-slab
        #                               map (partitioned, round 16)
    ]
    # Packed column staging (HBM, full padded height x one lane band
    # per slot): the dense-RDMA endpoints of the packed transport.
    stage_shape = (4, C, h_pad, LANE)
    if engage:
        out_idx = lambda c, a, b: (c, (a + 1) % gh, (b + 1) % gw)
    else:
        out_idx = lambda c, i, j: (c, i, j)
    if pad_operand is None:
        # Resolve from the EXECUTION mode already decided above, not the
        # global backend: a TPU-default process driving a forced-CPU mesh
        # passes interpret=True and must keep the scratch form covered.
        pad_operand = interpret is False
    if pad_operand:
        # Operand-backed pad: identical kernel body, but the HBM buffer
        # is a second OUTPUT (discarded) instead of a scratch entry (the
        # construct the chipless compile helper rejects — probe rung a
        # vs a0).  An output-only buffer is allocated uninitialized by
        # XLA, exactly like the scratch it replaces — no zero-fill tax —
        # and exactly as safe: the kernel overwrites the interior and
        # every ghost band it reads, and masks everything else
        # (the `ok` window mask).  The packed staging buffer rides the
        # same trick as a third discarded output.
        # (inputs, outputs, scratch) positional order makes the operand
        # form's ref list identical to the scratch form's signature —
        # the same kernel serves both.
        outs = (pl.BlockSpec((1, th, tw), out_idx),
                pl.BlockSpec(memory_space=pl.ANY))
        shapes = (shape_struct((C, gh * th, gw * tw), out_dtype, vma),
                  shape_struct((C, h_pad, w_pad), block.dtype, vma))
        if plan.packed_cols:
            outs = outs + (pl.BlockSpec(memory_space=pl.ANY),)
            shapes = shapes + (shape_struct(stage_shape, block.dtype, vma),)
        out = pl.pallas_call(
            kernel,
            grid=(C, gh, gw),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=outs,
            out_shape=shapes,
            scratch_shapes=vmem_scratch,
            compiler_params=cparams,
            interpret=interpret,
        )(block)[0]
        return out[:, :h, :w]
    hbm = [hbm_scratch((C, h_pad, w_pad), block.dtype)]
    if plan.packed_cols:
        hbm.append(hbm_scratch(stage_shape, block.dtype))
    out = pl.pallas_call(
        kernel,
        grid=(C, gh, gw),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((1, th, tw), out_idx),
        out_shape=shape_struct((C, gh * th, gw * tw), out_dtype, vma),
        scratch_shapes=hbm + vmem_scratch,
        compiler_params=cparams,
        interpret=interpret,
    )(block)
    return out[:, :h, :w]
