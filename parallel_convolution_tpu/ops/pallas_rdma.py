"""Fused remote-DMA halo + stencil kernel (SURVEY.md §7 frontier).

The standard path (``parallel/halo.py``) rides XLA ``collective-permute``:
edge slabs are ppermuted, concatenated into a padded block *outside* the
kernel, and the Pallas kernel then re-reads the padded block from HBM.
This module is the design SURVEY.md §7 names as the halo-latency
optimization: ONE kernel per device per iteration that

1. pushes its edge slabs straight into its neighbors' VMEM with
   ``pltpu.make_async_remote_copy`` (RDMA over ICI — the reference's
   ``MPI_Isend`` with the network card writing into the remote ghost ring,
   except here it is the TPU's own DMA engines, no copy through XLA), and
2. computes the stencil level in the same program once its own ghosts
   arrive — no HBM round trip between exchange and compute.

Temporal fusion (``fuse=T``): both kernels also amortize the exchange
itself — the ghost transfers widen to depth ``T*r`` and T level-shrinking
stencil iterations run in-kernel (the shared
``pallas_stencil._iterate_levels`` loop, so quantize/round/tap threading
is identical to the ppermute fused path).  One barrier + one exchange +
one launch per T iterations is the persistent/partitioned-communication
recipe for latency-bound stencils (PAPERS.md: persistent MPI stencils;
the Cerebras wafer-scale in-fabric neighbor transfer), i.e. this tier's
reason to exist at small blocks.  See DESIGN.md "RDMA temporal fusion"
for the band-depth math and the win/retire decision rule.

Overlapped pipeline (``overlap=True``, round 12): both kernels can run
interior-first — start the ghost-band DMAs, compute every output pixel
whose level-0 window needs no ghost byte while they fly, and retire each
phase's receive semaphores immediately before the first compute that
reads them (monolithic: the ``overlap_regions`` 5-region schedule;
tiled: rim-last rotated traversal + an SMEM deferred-wait ledger).
Byte-identical to the serialized order by construction — see DESIGN.md
"Overlapped halo pipeline" and tests/test_overlap.py.

Corner propagation uses the same two-phase trick as halo.py: column slabs
are sent at full padded height *after* the row-ghost receive semaphores
fire, so corners take two hops and no diagonal messages exist.  Ghost
regions with no inbound copy (image boundary, zero mode) are zeroed
locally — writes and inbound RDMA targets are disjoint by construction, so
there is no initialization race (checked by the interpreter's race
detector in tests/test_rdma.py).

Cross-invocation safety: within one invocation, waits on both the send and
receive semaphores retire every DMA before the kernel exits — but back-to-
back invocations (the fori_loop iteration driver) add a hazard the
per-invocation race detector cannot see: a fast device entering iteration
N+1 could push ghost bytes into a slow neighbor's scratch while the
neighbor still computes iteration N.  ``_neighbor_barrier`` closes it with
the canonical start-of-kernel rendezvous on the collective barrier
semaphore: no remote copy is issued until every RDMA partner has entered
the current invocation (tests/test_rdma.py::test_rdma_back_to_back_race
runs the multi-invocation protocol under the race detector).

STATUS: functionally validated — bit-exact against the oracle on the
multi-device CPU mesh under TPU interpret mode (which simulates remote
DMAs, semaphores, and the barrier).  On the one real chip available here
the monolithic kernel compiles via Mosaic and runs in its degenerate 1×1
local form, bit-exact vs the oracle (recorded in BASELINE.md "RDMA on
silicon"), and since round 5 the tiled variant runs on silicon too via
the operand-backed pad (``pad_operand``; the HBM *scratch* form is what
crashes this tunnel's chipless compile helper — see fused_rdma_step's
docstring and BASELINE.md "Round-5 chip session"); multi-chip ICI perf
remains unvalidated — no such hardware exists in this environment.

VMEM budget: the monolithic kernel holds the whole (C, h+2r, w+2r) f32
padded block plus the (C, h, w) output in VMEM (~16 MB limit ≈ 1400²
grey f32 for the pair).  Blocks beyond ``_TILED_VMEM_BYTES`` auto-select
``_rdma_tiled_kernel``: the padded buffer moves to HBM scratch (storage
dtype), the exchange uses tiling-aligned band transfers, and compute
runs the same double-buffered windowed-DMA grid as ``_stencil_kernel``
— per-program VMEM is two ~1 MB window slots regardless of block size
(tests: test_rdma_auto_tiles_beyond_vmem_bound and the forced-tiled
corner/periodic/radius-2 suite).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from parallel_convolution_tpu.ops.collective_ids import collective_id
from parallel_convolution_tpu.ops.filters import Filter
from parallel_convolution_tpu.ops.pallas_stencil import (
    DEFAULT_TILE, _from_f32, _iterate_levels, _prefetch_window,
    _round_mode_for, _round_up, _sublane, _to_f32, on_tpu,
)
from parallel_convolution_tpu.utils.jax_compat import (
    hbm_scratch, shape_struct, tpu_compiler_params, tpu_interpret_params,
    vma_of,
)

# Semaphore slots: one (send, recv) pair per direction.
_UP, _DOWN, _LEFT, _RIGHT = 0, 1, 2, 3


def _when(pred):
    """``pl.when`` that statically elides python-bool predicates.

    ``_topology`` reports extent-1 axes as python ``False`` (and periodic
    multi-device axes as python ``True``); resolving those here keeps dead
    guarded ops — remote-copy starts/waits that can never run — out of
    the program entirely instead of emitting always-false branches.  The
    degenerate single-device grid then contains no RDMA constructs at
    all, which is also what lets it run under interpreters that lack the
    remote-DMA/semaphore simulation.
    """
    if isinstance(pred, bool):
        return (lambda f: f()) if pred else (lambda f: None)
    return pl.when(pred)


def _unless(pred):
    """``pl.when(not pred)`` with the same static-bool elision."""
    if isinstance(pred, bool):
        return (lambda f: None) if pred else (lambda f: f())
    return pl.when(jnp.logical_not(pred))


def _neighbor_barrier(up_in, down_in, left_in, right_in, nbr):
    """Start-of-kernel rendezvous with every RDMA partner.

    Arguments are ``_topology``'s returns.  Each device signals the
    global barrier semaphore of every
    existing neighbor, then waits until all of ITS neighbors have signaled
    it.  This closes the cross-invocation race the per-invocation race
    detector cannot see: without it, a fast device's iteration-N+1 remote
    copy could land in a slow neighbor's scratch while that neighbor is
    still computing iteration N.  After the barrier, every partner has
    entered the current invocation — and kernel invocations serialize on a
    core, so all of its previous-invocation reads have retired before any
    new ghost bytes arrive.

    Skew safety: a neighbor can run at most one invocation ahead, because
    completing invocation N+1 requires its own ``wait_recv`` on ghosts we
    only send after passing this barrier — so the wait below can never be
    satisfied by two signals from one fast neighbor standing in for a slow
    one.  Leftover signals (a neighbor already in N+2's barrier) simply
    pre-credit the next wait; counts stay balanced.
    """
    if all(isinstance(e, bool) and not e
           for e in (up_in, down_in, left_in, right_in)):
        # No RDMA partner exists at all (single-device grid, or a torus
        # of pure self-wrap axes): the rendezvous is vacuous — emit no
        # barrier-semaphore traffic.
        return
    dirs = [(up_in, nbr(-1, 0)), (down_in, nbr(+1, 0)),
            (left_in, nbr(0, -1)), (right_in, nbr(0, +1))]
    bsem = pltpu.get_barrier_semaphore()
    n_wait = jnp.int32(0)
    for exists, dev in dirs:
        if isinstance(exists, bool):
            if not exists:
                continue
            pltpu.semaphore_signal(bsem, inc=1, device_id=dev)
            n_wait = n_wait + 1
        else:
            @pl.when(exists)
            def _(dev=dev):
                pltpu.semaphore_signal(bsem, inc=1, device_id=dev)

            n_wait = n_wait + exists.astype(jnp.int32)
    pltpu.semaphore_wait(bsem, n_wait)


def _topology(R, Cc, periodic):
    """Shared neighbor scaffolding: existence predicates + wrap helper.

    Returns ``(up_in, down_in, left_in, right_in, nbr)`` for the calling
    device.  Predicates are python bools when static (periodic axes);
    a periodic self-wrap axis (extent 1) reports False — the kernels
    handle it with local copies, not remote sends.
    """
    x = lax.axis_index("x")
    y = lax.axis_index("y")
    if periodic:
        up_in = down_in = R > 1
        left_in = right_in = Cc > 1
    else:
        # Extent-1 axes have statically no neighbor: report python False
        # (not the always-false traced `x > 0`) so `_when` can elide the
        # dead exchange ops entirely.
        up_in = (x > 0) if R > 1 else False
        down_in = (x < R - 1) if R > 1 else False
        left_in = (y > 0) if Cc > 1 else False
        right_in = (y < Cc - 1) if Cc > 1 else False

    def nbr(dx, dy):
        if periodic:
            return (lax.rem(x + dx + R, R), lax.rem(y + dy + Cc, Cc))
        return (x + dx, y + dy)

    return up_in, down_in, left_in, right_in, nbr


def overlap_regions(h: int, w: int, d: int):
    """The interior-first output partition of one (h, w) block at ghost
    depth ``d``, as ``(interior, row_bands, col_bands)`` — each a list of
    half-open ``(r0, r1, c0, c1)`` output rectangles (empties dropped).

    * ``interior`` needs NO ghost data (its level-0 window is the local
      block) — computed while the row DMAs are in flight;
    * ``row_bands`` (top/bottom strips restricted to interior columns)
      need the ROW ghosts only — computed while the column DMAs fly;
    * ``col_bands`` (full-height left/right strips) read column ghosts
      (and, via the full padded height, the two-hop corners) — computed
      after the column receive semaphores clear.

    The three groups tile the block exactly (no overlap, no gap) for any
    geometry, including degenerate blocks where ``min(h, w) <= 2*d``
    (interior empties out and the bands absorb everything).  Shared by
    the monolithic kernel and the cost model's legality predicate; unit
    pinned in tests/test_overlap.py.
    """
    t, b = min(d, h), max(h - d, min(d, h))
    l, rt = min(d, w), max(w - d, min(d, w))
    interior = [(t, b, l, rt)]
    row_bands = [(0, t, l, rt), (b, h, l, rt)]
    col_bands = [(0, h, 0, l), (0, h, rt, w)]
    keep = lambda rs: [x for x in rs if x[0] < x[1] and x[2] < x[3]]
    return keep(interior), keep(row_bands), keep(col_bands)


def _rdma_kernel(in_ref, out_ref, pad, send_sem, recv_sem, *,
                 taps, sep, k, r, T, C, h, w, R, Cc, periodic, quantize,
                 convex, round_mode, valid_hw, overlap=False):
    """One device's program: exchange T·r-deep ghosts in-kernel, then run
    T stencil levels (temporal fusion — ONE exchange buys T iterations).

    ``pad`` is the (C, h+2d, w+2d) f32 working buffer, d = r*T; interior =
    my block, ghost ring = RDMA'd from neighbors (or zeros at a
    non-periodic image boundary).  All slab math mirrors
    halo.halo_exchange at depth d.  The compute is the shared
    level-shrinking loop (``pallas_stencil._iterate_levels``): for T > 1,
    ``valid_hw`` re-zeroes out-of-image positions after every level — the
    oracle's ghost ring at each intermediate — so results stay bit-exact
    with T single-exchange steps.  ``valid_hw=None`` (fuse=1, or the
    periodic torus) statically drops the masks: the validated
    single-level protocol is byte-identical to before.

    ``overlap=True`` is the interior-first pipeline (ROADMAP item 1, the
    persistent/partitioned-MPI overlap recipe): compute is split into the
    :func:`overlap_regions` partition and interleaved with the two
    exchange phases — interior under the in-flight row DMAs, top/bottom
    bands under the column DMAs, left/right bands after the last receive
    semaphore.  Bit-exact vs the serialized order because every output
    pixel's level chain is a pure function of its own level-0 dependency
    cone, which each region's window contains by construction; the only
    reordering is BETWEEN independent pixels.  Safe vs the in-flight
    DMAs because each region reads only pad cells that are either local
    or already received (inbound ghost writes are disjoint from the
    interior/band reads until their semaphore is waited).
    """
    d = r * T
    # Interior + boundary-ghost initialization.  Inbound RDMA targets are
    # exactly the ghost regions owned by an existing neighbor, so local
    # writes below never overlap a remote write (no ordering needed).
    pad[:, d : d + h, d : d + w] = _to_f32(in_ref[...])

    up_in, down_in, left_in, right_in, nbr = _topology(R, Cc, periodic)

    zero_row = jnp.zeros((C, d, w), jnp.float32)
    zero_col = jnp.zeros((C, h + 2 * d, d), jnp.float32)

    @_unless(up_in)
    def _():
        pad[:, 0:d, d : d + w] = zero_row

    @_unless(down_in)
    def _():
        pad[:, h + d : h + 2 * d, d : d + w] = zero_row

    if periodic and R == 1:
        # Torus of height 1: my own opposite edge wraps to me (static).
        pad[:, 0:d, d : d + w] = pad[:, h : h + d, d : d + w]
        pad[:, h + d : h + 2 * d, d : d + w] = pad[:, d : 2 * d, d : d + w]

    # Cross-invocation safety: no remote copy may be issued until every
    # RDMA partner has entered THIS invocation (see _neighbor_barrier).
    # Self-wrap axes (periodic R==1 / Cc==1) have python-False predicates
    # and drop out statically.
    _neighbor_barrier(up_in, down_in, left_in, right_in, nbr)

    def compute(regions):
        """T stencil levels for each output rectangle (shared level loop
        — identical op order / quantize / tap threading to the ppermute
        fused path and to the serialized whole-block call; a region's
        level-0 window is the same pad cells the whole-block window
        reads for those pixels, so bytes cannot differ).  Level-0
        out-of-image positions are already exact zeros (boundary ghosts
        zeroed above; the pad-to-multiple rim is zero by the iterate's
        masking invariant), so no level-0 select tier is needed — only
        the per-level rank-1 re-zeroing against the region-shifted
        global-coordinate iotas."""
        for (r0, r1, c0, c1) in regions:
            rows0 = cols0 = None
            if valid_hw is not None:
                rows0 = (lax.axis_index("x") * h - d + r0
                         + lax.broadcasted_iota(
                             jnp.int32, (r1 - r0 + 2 * d, 1), 0))
                cols0 = (lax.axis_index("y") * w - d + c0
                         + lax.broadcasted_iota(
                             jnp.int32, (1, c1 - c0 + 2 * d), 1))
            for c in range(C):
                acc = _iterate_levels(
                    pad[c, r0 : r1 + 2 * d, c0 : c1 + 2 * d],
                    taps=taps, sep=sep, k=k, r=r, T=T,
                    out_hw=(r1 - r0, c1 - c0),
                    quantize=quantize, convex=convex,
                    round_mode=round_mode,
                    rows0=rows0, cols0=cols0, valid_hw=valid_hw)
                out_ref[c, r0:r1, c0:c1] = _from_f32(acc, out_ref.dtype)

    interior, row_bands, col_bands = (
        overlap_regions(h, w, d) if overlap
        else ([], [], [(0, h, 0, w)]))  # serialized: one whole-block call

    # --- Phase 1: rows.  My top d interior rows -> upper neighbor's
    # bottom ghost; my bottom d interior rows -> lower neighbor's top
    # ghost (d <= h, enforced at the launch).
    send_up = pltpu.make_async_remote_copy(
        pad.at[:, d : 2 * d, d : d + w],
        pad.at[:, h + d : h + 2 * d, d : d + w],
        send_sem.at[_UP], recv_sem.at[_UP], device_id=nbr(-1, 0),
    )
    send_down = pltpu.make_async_remote_copy(
        pad.at[:, h : h + d, d : d + w],
        pad.at[:, 0:d, d : d + w],
        send_sem.at[_DOWN], recv_sem.at[_DOWN], device_id=nbr(+1, 0),
    )
    row_dma = not (periodic and R == 1)
    if row_dma:
        _when(up_in)(send_up.start)
        _when(down_in)(send_down.start)

    # Interior-first: the middle of the block needs no ghost byte — its
    # level-0 window reads only the local interior (which the outbound
    # sends also read, read-vs-read), never a cell an inbound DMA writes.
    compute(interior)

    if row_dma:
        _when(up_in)(send_up.wait_send)
        _when(down_in)(send_down.wait_send)
        # My bottom ghost is written by my lower neighbor's send_up copy,
        # which signals MY recv_sem[_UP] (SPMD symmetry), and vice versa.
        _when(down_in)(send_up.wait_recv)
        _when(up_in)(send_down.wait_recv)

    # --- Phase 2: columns at FULL padded height (includes the row ghosts
    # that just arrived -> corners propagate in two hops, halo.py §order).
    if periodic and Cc == 1:
        pad[:, :, 0:d] = pad[:, :, w : w + d]
        pad[:, :, w + d : w + 2 * d] = pad[:, :, d : 2 * d]
        compute(row_bands)
    else:

        @_unless(left_in)
        def _():
            pad[:, :, 0:d] = zero_col

        @_unless(right_in)
        def _():
            pad[:, :, w + d : w + 2 * d] = zero_col

        send_left = pltpu.make_async_remote_copy(
            pad.at[:, :, d : 2 * d],
            pad.at[:, :, w + d : w + 2 * d],
            send_sem.at[_LEFT], recv_sem.at[_LEFT], device_id=nbr(0, -1),
        )
        send_right = pltpu.make_async_remote_copy(
            pad.at[:, :, w : w + d],
            pad.at[:, :, 0:d],
            send_sem.at[_RIGHT], recv_sem.at[_RIGHT], device_id=nbr(0, +1),
        )
        _when(left_in)(send_left.start)
        _when(right_in)(send_right.start)

        # Top/bottom bands on interior columns read row ghosts (arrived)
        # plus local interior — never a column-ghost cell, so they hide
        # the column phase exactly as the interior hid the row phase.
        compute(row_bands)

        _when(left_in)(send_left.wait_send)
        _when(right_in)(send_right.wait_send)
        _when(right_in)(send_left.wait_recv)
        _when(left_in)(send_right.wait_recv)

    # --- Rim finish (overlap) / whole-block compute (serialized): the
    # full-height left/right bands read the column ghosts and the corner
    # bytes that rode them — everything has landed by now.
    compute(col_bands)


# ---------------------------------------------------------------------------
# Tiled variant: HBM-resident padded buffer + windowed-DMA compute grid.
# ---------------------------------------------------------------------------
#
# The monolithic kernel above holds the whole (C, h+2r, w+2r) f32 block in
# VMEM — a hard ~16 MB bound (≈2048² grey f32).  The tiled variant lifts
# it: the padded buffer lives in HBM scratch (storage dtype, not f32), the
# ghost exchange lands there, and the compute phase is the same
# double-buffered windowed-DMA grid as ``_stencil_kernel``.  Two design
# points keep HBM DMA *starts* tiling-aligned (Mosaic requires aligned
# slice starts; interpret mode does not check — see ``_sublane``):
#
# 1. **Aligned-band transfers.**  Ghost slabs are r*T wide (T = temporal
#    fusion depth), which is never aligned.  Instead each transfer moves
#    a full (sublane, 128)-aligned band — ``sub_v`` rows / 128 cols of
#    interior — whose LAST (first) r*T rows/cols land exactly on the
#    receiver's ghost positions (hence the r*T <= min(sub_v, 128)
#    constraint); the rest of the band falls on never-read buffer and is
#    masked at compute.
# 2. **No ghost zeroing.**  Image-boundary ghosts stay uninitialized in
#    HBM; every compute window applies one select against the block's
#    valid [row_lo, row_hi) × [col_lo, col_hi) box (which also kills any
#    non-finite DMA garbage — a multiplicative mask would leak NaN).
#
# VMEM per program: 2 window slots of (th + 2·sub_v, tw + 256) storage
# dtype — ~1.7 MB at the 256×512 f32 default, independent of block size.
#
# Honesty note on alignment coverage: the scheme is FULLY aligned
# (every start and every extent) precisely when the block shape itself
# is (sub_v, 128)-aligned — then the h/w-derived starts (row h, h+sub_v;
# col w, w+LANE) and the orthogonal extents (h, w) are all multiples.
# For non-multiple blocks, both those starts and extents are raw h/w,
# and whether real Mosaic constrains HBM↔HBM copies that way cannot be
# validated in this environment (the tiled path's multi-chip form only
# runs under the interpreter; single-chip silicon runs the degenerate
# no-exchange form — same standing caveat as the monolithic STATUS).
# If silicon rejects raw-h/w transfers, the fix is at the CALLER: pad
# the global image so blocks are (sub_v, 128)-multiples — the framework
# already pads to mesh multiples (`parallel/step._prepare`) and the
# valid-box mask here already ignores rim, so widening that padding is
# a one-line change with no kernel edits.

_TILED_VMEM_BYTES = 10 * 2**20  # monolithic-kernel budget before auto-tiling


def _and2(a, b):
    """``a & b`` with python-bool static folding on either side."""
    if isinstance(a, bool):
        return b if a else False
    if isinstance(b, bool):
        return a if b else False
    return jnp.logical_and(a, b)


def _or2(a, b):
    """``a | b`` with python-bool static folding on either side."""
    if isinstance(a, bool):
        return True if a else b
    if isinstance(b, bool):
        return True if b else a
    return jnp.logical_or(a, b)


def _rdma_tiled_kernel(in_ref, out_ref, pad, win, wsems, xsem, send_sem,
                       recv_sem, flags, *, taps, sep, k, r, T, C, h, w, R, Cc,
                       periodic, quantize, convex, th, tw, sub_v, round_mode,
                       valid_hw, overlap=False):
    """HBM-pad windowed variant; ``overlap=True`` is the interior-first
    pipeline at window granularity.

    Serialized (``overlap=False``): the step-0 program completes the
    whole two-phase exchange before any window is copied — the validated
    protocol, byte-identical to before this knob existed.

    Overlapped: step 0 only STARTS the row-band DMAs; the window
    traversal is rotated by one on both grid axes so the rim windows
    (the only ones whose (ext_h, ext_w) read window reaches a ghost
    band) are visited last, and a 3-state ledger in SMEM scratch
    (``flags[0]``: 0 = rows in flight, 1 = rows done + columns in
    flight, 2 = all landed) defers every semaphore wait to the first
    window whose read window actually overlaps a still-pending transfer
    — interior windows stream and compute under the in-flight exchange.
    Sound because grid programs run sequentially on one core with
    shared scratch (the same property the step-0-exchange design
    already relies on), waits recreate the identical copy descriptors,
    the ledger transitions are monotonic, and the rim windows that
    trigger each transition provably exist in every grid (window row 0
    / last row, column 0 / last column).  The column phase still starts
    only after the row receives (its full-height bands carry the
    two-hop corner bytes), so the exchange protocol — order, slabs,
    semaphore pairing — is unchanged; only the waits move later.
    """
    LANE = 128
    d = r * T  # ghost depth; <= min(sub_v, LANE) so one band carries it
    ext_h, ext_w = th + 2 * sub_v, tw + 2 * LANE
    c, vi, vj = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    ni, nj = pl.num_programs(1), pl.num_programs(2)
    step = (c * ni + vi) * nj + vj

    up_in, down_in, left_in, right_in, nbr = _topology(R, Cc, periodic)

    row_remote = R > 1   # remote row-band DMAs exist in this program
    col_remote = Cc > 1  # remote column-band DMAs exist
    # Periodic self-wrap columns on a multi-row grid: the local wrap
    # copies read the FULL padded height, so under overlap they must
    # run after the row receives — i.e. at the 0->1 ledger transition,
    # not at step 0 — and windows reading column ghosts must wait on
    # that transition even though no remote column DMA exists.
    col_wrap_deferred = periodic and Cc == 1 and row_remote
    engage = overlap and (row_remote or col_remote)
    # Window (wi, wj) this program computes: the rotated traversal
    # visits rim windows last under the overlapped pipeline.  The out
    # BlockSpec index map applies the SAME rotation (fused_rdma_step).
    if engage:
        i, j = lax.rem(vi + 1, ni), lax.rem(vj + 1, nj)
    else:
        i, j = vi, vj

    # -- exchange pieces, each buildable at any program (descriptors are
    # pure functions of the topology; a wait only needs the semaphore).
    def _local_row_wrap():
        for src, dst, sl in (((sub_v, 2 * sub_v),
                              (h + sub_v, h + 2 * sub_v), _UP),
                             ((h, h + sub_v), (0, sub_v), _DOWN)):
            cp = pltpu.make_async_copy(
                pad.at[:, src[0] : src[1], LANE : LANE + w],
                pad.at[:, dst[0] : dst[1], LANE : LANE + w],
                send_sem.at[sl])
            cp.start()
            cp.wait()

    def _local_col_wrap():
        for src, dst, sl in (((LANE, 2 * LANE),
                              (w + LANE, w + 2 * LANE), _LEFT),
                             ((w, w + LANE), (0, LANE), _RIGHT)):
            cp = pltpu.make_async_copy(
                pad.at[:, :, src[0] : src[1]],
                pad.at[:, :, dst[0] : dst[1]],
                send_sem.at[sl])
            cp.start()
            cp.wait()

    def _row_copies():
        su = pltpu.make_async_remote_copy(
            pad.at[:, sub_v : 2 * sub_v, LANE : LANE + w],
            pad.at[:, h + sub_v : h + 2 * sub_v, LANE : LANE + w],
            send_sem.at[_UP], recv_sem.at[_UP], device_id=nbr(-1, 0),
        )
        sd = pltpu.make_async_remote_copy(
            pad.at[:, h : h + sub_v, LANE : LANE + w],
            pad.at[:, 0:sub_v, LANE : LANE + w],
            send_sem.at[_DOWN], recv_sem.at[_DOWN], device_id=nbr(+1, 0),
        )
        return su, sd

    def _col_copies():
        sl_ = pltpu.make_async_remote_copy(
            pad.at[:, :, LANE : 2 * LANE],
            pad.at[:, :, w + LANE : w + 2 * LANE],
            send_sem.at[_LEFT], recv_sem.at[_LEFT], device_id=nbr(0, -1),
        )
        sr = pltpu.make_async_remote_copy(
            pad.at[:, :, w : w + LANE],
            pad.at[:, :, 0:LANE],
            send_sem.at[_RIGHT], recv_sem.at[_RIGHT], device_id=nbr(0, +1),
        )
        return sl_, sr

    def _start_rows():
        su, sd = _row_copies()
        _when(up_in)(su.start)
        _when(down_in)(sd.start)

    def _wait_rows():
        su, sd = _row_copies()
        _when(up_in)(su.wait_send)
        _when(down_in)(sd.wait_send)
        # My top ghost is written by my upper neighbor's send_down (it
        # signals MY recv_sem[_DOWN]) and vice versa — SPMD symmetry.
        _when(down_in)(su.wait_recv)
        _when(up_in)(sd.wait_recv)

    def _start_cols():
        # Phase 2 initiation: column bands at FULL padded height — the
        # transferred bands carry the just-arrived row ghosts, so
        # corners propagate in two hops exactly as in halo.py / the
        # monolithic kernel.  Callable only after the row phase landed.
        if periodic and Cc == 1:
            _local_col_wrap()
        elif col_remote:
            sl_, sr = _col_copies()
            _when(left_in)(sl_.start)
            _when(right_in)(sr.start)

    def _wait_cols():
        sl_, sr = _col_copies()
        _when(left_in)(sl_.wait_send)
        _when(right_in)(sr.wait_send)
        _when(right_in)(sl_.wait_recv)
        _when(left_in)(sr.wait_recv)

    @pl.when(step == 0)
    def _exchange():
        # Interior: one aligned HBM->HBM copy (dst starts at (sub_v, 128)).
        intr = pltpu.make_async_copy(
            in_ref, pad.at[:, sub_v : sub_v + h, LANE : LANE + w], xsem)
        intr.start()
        intr.wait()

        _neighbor_barrier(up_in, down_in, left_in, right_in, nbr)

        # Phase 1: row bands (interior cols only; ghost cols not yet
        # live).  Torus of height 1: own opposite edge, local aligned
        # copies — complete synchronously here either way.
        if periodic and R == 1:
            _local_row_wrap()
        if not engage:
            # Serialized: the whole exchange completes before any window.
            if row_remote:
                _start_rows()
                _wait_rows()
            if periodic and Cc == 1:
                _local_col_wrap()
            elif col_remote:
                _start_cols()
                _wait_cols()
        else:
            if row_remote:
                _start_rows()
                flags[0] = jnp.int32(0)
            else:
                # Rows already complete (local wrap / no axis): the
                # column phase can start under the very first windows.
                _start_cols()
                flags[0] = jnp.int32(1 if col_remote else 2)

    # -- deferred-wait guard: runs before a window copy is ISSUED, with
    # the window's indices — waits exactly when that window's read
    # region overlaps a still-pending transfer, advancing the ledger.
    def _ensure(wi, wj):
        if not engage:
            return
        # Geometric touch: the (ext_h, ext_w) read window vs the four
        # ghost bands; hazardous only where an actual transfer writes
        # (the _in predicates — non-live ghost regions hold garbage the
        # valid-box mask kills, no ordering needed).
        top, bot = wi == 0, wi * th + ext_h > h + sub_v
        lef, rig = wj == 0, wj * tw + ext_w > w + LANE
        need_row = (_or2(_and2(top, up_in), _and2(bot, down_in))
                    if row_remote else False)
        if col_remote:
            need_col = _or2(_and2(lef, left_in), _and2(rig, right_in))
        elif col_wrap_deferred:
            # Self-wrap ghosts are VALID data (periodic valid box), but
            # written only at the 0->1 transition — any reader waits.
            need_col = _or2(lef, rig)
        else:
            need_col = False
        need_any = _or2(need_row, need_col)

        @_when(_and2(need_any, flags[0] == 0))
        def _():
            _wait_rows()
            _start_cols()
            flags[0] = jnp.int32(1 if col_remote else 2)

        if col_remote and need_col is not False:
            @_when(_and2(need_col, flags[0] == 1))
            def _():
                _wait_cols()
                flags[0] = jnp.int32(2)

    # --- Compute: the _stencil_kernel windowed-DMA grid over the HBM pad.
    def window_copy(cc, ai, aj, s):
        if engage:
            wi, wj = lax.rem(ai + 1, ni), lax.rem(aj + 1, nj)
        else:
            wi, wj = ai, aj
        _ensure(wi, wj)
        return pltpu.make_async_copy(
            pad.at[cc, pl.ds(wi * th, ext_h), pl.ds(wj * tw, ext_w)],
            win.at[s], wsems.at[s])

    slot = _prefetch_window(window_copy)

    # Valid box of the block in padded coords (ghost ring d deep); outside
    # it live image-boundary ghosts (zero semantics) and never-written
    # buffer.  Periodic: EVERY ghost is valid (filled by wrap or remote
    # band) even on a self-wrap axis, where the exchange predicate is
    # False.
    def _i32(p):
        return jnp.int32(p) if isinstance(p, bool) else p.astype(jnp.int32)

    row_lo = sub_v - (d if periodic else d * _i32(up_in))
    row_hi = sub_v + h + (d if periodic else d * _i32(down_in))
    col_lo = LANE - (d if periodic else d * _i32(left_in))
    col_hi = LANE + w + (d if periodic else d * _i32(right_in))

    w0h, w0w = th + 2 * d, tw + 2 * d
    rows = (i * th + (sub_v - d)
            + lax.broadcasted_iota(jnp.int32, (w0h, 1), 0))
    cols = (j * tw + (LANE - d)
            + lax.broadcasted_iota(jnp.int32, (1, w0w), 1))
    ok = (((rows >= row_lo) & (rows < row_hi))
          & ((cols >= col_lo) & (cols < col_hi)))
    cur = _to_f32(win[slot][sub_v - d : sub_v + d + th,
                           LANE - d : LANE + d + tw])
    cur = jnp.where(ok, cur, 0.0)

    # T in-VMEM levels (shared level loop).  For T > 1 the per-level
    # re-zeroing needs GLOBAL image coordinates (the pad-to-multiple rim
    # is in-block but out-of-image); pad row p maps to global row
    # x*h + p - sub_v, so shift the hoisted pad-coordinate iotas.  The
    # tier-1 select above already killed every non-finite DMA garbage
    # value, so the rank-1 multiplies are exact.
    rows0 = cols0 = None
    if valid_hw is not None:
        rows0 = rows + (lax.axis_index("x") * h - sub_v)
        cols0 = cols + (lax.axis_index("y") * w - LANE)
    acc = _iterate_levels(cur, taps=taps, sep=sep, k=k, r=r, T=T,
                          out_hw=(th, tw), quantize=quantize, convex=convex,
                          round_mode=round_mode, rows0=rows0, cols0=cols0,
                          valid_hw=valid_hw)
    out_ref[0] = _from_f32(acc, out_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("filt", "grid", "boundary", "quantize", "out_dtype",
                     "interpret", "tiled", "tile", "pad_operand", "fuse",
                     "valid_hw", "overlap"),
)
def fused_rdma_step(
    block: jnp.ndarray,
    filt: Filter,
    grid: tuple[int, int],
    boundary: str = "zero",
    quantize: bool = True,
    out_dtype=None,
    interpret=None,
    tiled: bool | None = None,
    tile: tuple[int, int] | None = None,
    pad_operand: bool | None = None,
    fuse: int = 1,
    valid_hw: tuple[int, int] | None = None,
    overlap: bool = False,
) -> jnp.ndarray:
    """``fuse`` halo-fused stencil iterations, entirely inside one kernel.

    Must be called inside ``shard_map`` over the ('x','y') mesh; ``block``
    is the local (C, h, w) tile.  Semantically identical to a depth
    ``r*fuse`` ``halo.halo_exchange`` followed by ``fuse`` level-shrinking
    correlates (+ optional u8 quantization per level) — see
    tests/test_rdma.py for the bit-exactness proof.

    ``fuse=T>1`` is temporal fusion INSIDE the RDMA tier: the ghost
    transfers widen to depth T·r and the kernel runs T stencil levels
    before returning to HBM — one exchange setup, one neighbor barrier,
    one kernel launch per T iterations, which is exactly the lever the
    latency-bound small-block regime this tier exists for needs
    (DESIGN.md "RDMA temporal fusion").  It requires ``valid_hw`` — the
    global (H, W) image extent — for zero boundaries, because each
    intermediate level must re-zero out-of-image positions (the oracle's
    ghost ring); the caller (``parallel/step.py``) threads it
    automatically.  Constraints: ``min(h, w) >= r*fuse`` (monolithic slab
    depth), and for the tiled variant ``r*fuse <= min(sublane, 128)`` so
    the one-tile-deep aligned transfer bands still carry every live ghost
    row/col.

    ``tiled=None`` auto-selects: blocks whose monolithic VMEM footprint
    (f32 padded buffer + output) exceeds ``_TILED_VMEM_BYTES`` use the
    HBM-pad + windowed-DMA variant (``_rdma_tiled_kernel``); small blocks
    keep the all-VMEM kernel (lower latency, no per-window DMA).  ``tile``
    sets the tiled variant's output tile (default ``DEFAULT_TILE``).

    ``overlap=True`` selects the interior-first overlapped pipeline in
    BOTH kernels (see ``_rdma_kernel`` / ``_rdma_tiled_kernel``): the
    ghost-band DMAs fly while ghost-free compute proceeds, and the
    receive waits retire immediately before the first compute that
    reads them — byte-identical to the serialized order for every
    (boundary, fuse, grid, storage) combination, because only
    independent per-pixel work is reordered (proven in
    tests/test_overlap.py; multi-device cells need the faithful
    interpreter or silicon).  The monolithic kernel always emits the
    region-split program when asked (degenerate regions clamp away);
    the tiled kernel engages only when a remote axis exists — on a 1x1
    grid its program is the serialized one verbatim.  The dispatch
    layer (``parallel/step.py``) resolves when this knob is on; callers
    there never pass it blindly.

    ``pad_operand`` (tiled variant only) chooses how the HBM pad buffer
    is provided.  ``False``: as an ``pltpu.MemorySpace.HBM``
    ``scratch_shapes`` entry — the natural form, but the round-5 probe
    ladder pinned THAT construct as what crashes this tunnel's chipless
    remote compile helper (``scripts/tiled_repro_probe.py`` rung a vs
    a0; ``evidence/tiled_repro_r5.jsonl``).  ``True``: as a second
    ANY-space OUTPUT that the caller discards — allocated uninitialized
    by XLA just like the scratch it replaces (no init cost), and
    nothing the helper rejects is used.  ``None`` resolves to ``True``
    when actually compiling for silicon (``interpret is False``),
    ``False`` under the interpreter — so interpreter tests keep
    covering the scratch form regardless of the process's global
    backend.
    """
    from parallel_convolution_tpu.resilience.faults import fault_point
    from parallel_convolution_tpu.utils.config import BOUNDARIES

    # Trace-time consult: models the in-kernel exchange failing to build
    # (the round-5 tiled-RDMA compile crash class).  Zero overhead when no
    # fault plan is installed, and runs only while tracing — never on the
    # device hot path.
    fault_point("halo_exchange")
    if boundary not in BOUNDARIES:
        raise ValueError(f"boundary must be one of {BOUNDARIES}, got {boundary!r}")
    if interpret is None:
        interpret = not on_tpu()
    if interpret is True:
        # Plain-bool callers (the step builder resolves interpret from the
        # MESH platform) get the DMA-faithful interpreter configuration.
        interpret = tpu_interpret_params(dma_execution_mode="on_wait")
    if out_dtype is None:
        out_dtype = block.dtype
    C, h, w = block.shape
    r, k = filt.radius, filt.size
    T = int(fuse)
    if T < 1:
        raise ValueError(f"fuse must be >= 1, got {fuse}")
    d = r * T
    if min(h, w) < d:
        raise ValueError(
            f"block {(h, w)} smaller than the ghost depth r*fuse = {d} "
            f"(radius {r} x fuse {T}); use a smaller fuse or coarser mesh")
    periodic = boundary == "periodic"
    if T > 1 and not periodic and valid_hw is None:
        raise ValueError(
            "fuse > 1 with a zero boundary needs valid_hw — the global "
            "(H, W) image extent — so every intermediate level can re-zero "
            "its out-of-image positions (the oracle's ghost ring)")
    # Normalized static mask key for the kernels: None statically drops
    # per-level masking (single level, or the torus where every position
    # is valid).
    kern_valid = (None if (T == 1 or periodic)
                  else (int(valid_hw[0]), int(valid_hw[1])))
    sep = None  # rank-1 split saves little at one level; keep 2D order
    taps = tuple(float(t) for t in filt.taps.reshape(-1))
    vma = vma_of(block)
    cparams = tpu_compiler_params(
        collective_id=collective_id("rdma_halo_stencil"),
        has_side_effects=True,
    )

    sub_v = _sublane(block.dtype)
    if tiled is None:
        mono_bytes = (C * (h + 2 * d) * (w + 2 * d) * 4
                      + C * h * w * jnp.dtype(out_dtype).itemsize)
        tiled = mono_bytes > _TILED_VMEM_BYTES
        if tiled and (d > min(sub_v, 128) or h < sub_v or w < 128):
            # Silently falling back to the monolithic kernel here would
            # trade this clear error for an opaque Mosaic VMEM failure.
            raise ValueError(
                f"block {(C, h, w)} needs ~{mono_bytes >> 20} MB of VMEM "
                f"(over the {_TILED_VMEM_BYTES >> 20} MB monolithic "
                f"budget) but the tiled kernel requires ghost depth "
                f"r*fuse <= {min(sub_v, 128)} (got {d}) and blocks >= "
                f"({sub_v}, 128); use a finer or differently-shaped mesh, "
                "or a shallower fuse")

    # interpret here is False (silicon) or InterpretParams — the barrier
    # form is needed exactly when XLA (not Mosaic) executes the kernel.
    # round_mode is dead when not quantizing: skip the selector (and the
    # compiled-probe guard it consults on silicon) entirely.
    round_mode = (_round_mode_for(taps, interpret is not False)
                  if quantize else "rint")
    if not tiled:
        kernel = functools.partial(
            _rdma_kernel, taps=taps, sep=sep, k=k, r=r, T=T, C=C, h=h, w=w,
            R=grid[0], Cc=grid[1], periodic=periodic, quantize=quantize,
            convex=filt.convex, round_mode=round_mode, valid_hw=kern_valid,
            overlap=bool(overlap),
        )
        return pl.pallas_call(
            kernel,
            out_shape=shape_struct((C, h, w), out_dtype, vma),
            scratch_shapes=[
                pltpu.VMEM((C, h + 2 * d, w + 2 * d), jnp.float32),
                pltpu.SemaphoreType.DMA((4,)),
                pltpu.SemaphoreType.DMA((4,)),
            ],
            compiler_params=cparams,
            interpret=interpret,
        )(block)

    # ---- tiled variant ----
    if d > min(sub_v, 128):
        raise ValueError(
            f"tiled RDMA kernel needs ghost depth r*fuse <= "
            f"{min(sub_v, 128)} (the aligned transfer bands are one "
            f"({sub_v}, 128) tile deep and their trailing/leading r*fuse "
            f"rows/cols must all be live ghosts), got r*fuse = {d}")
    if h < sub_v or w < 128:
        # A band narrower than the block would make src/dst of the band
        # copies overlap (undefined for real DMA engines even though the
        # interpreter's atomic copies happen to produce the right bytes).
        raise ValueError(
            f"tiled RDMA kernel needs blocks >= ({sub_v}, 128) for "
            f"non-overlapping band transfers, got {(h, w)}; blocks this "
            "small fit the monolithic kernel (tiled=False) unless the "
            "other dimension is huge — then reshape the mesh")
    LANE = 128
    t0, t1 = tile if tile is not None else DEFAULT_TILE
    th = min(_round_up(t0, sub_v), _round_up(h, sub_v))
    tw = min(_round_up(t1, LANE), _round_up(w, LANE))
    gh, gw = -(-h // th), -(-w // tw)
    ext_h, ext_w = th + 2 * sub_v, tw + 2 * LANE
    # Pad buffer: interior at (sub_v, LANE); sized so the LAST window
    # [gh-1·th, +ext_h) fits — any rim beyond the ghost ring is never
    # valid (masked) and never sent (transfers address interior/ghost
    # coordinates only).
    h_pad = max((gh - 1) * th + ext_h, h + 2 * sub_v)
    w_pad = max((gw - 1) * tw + ext_w, w + 2 * LANE)

    kernel = functools.partial(
        _rdma_tiled_kernel, taps=taps, sep=sep, k=k, r=r, T=T, C=C, h=h,
        w=w, R=grid[0], Cc=grid[1], periodic=periodic, quantize=quantize,
        convex=filt.convex, th=th, tw=tw, sub_v=sub_v,
        round_mode=round_mode, valid_hw=kern_valid, overlap=bool(overlap),
    )
    # Rim-last traversal under the overlapped pipeline: the out index
    # map applies the same +1 rotation the kernel applies to its window
    # indices, so program p's out block IS the window it computed.
    engage = bool(overlap) and (grid[0] > 1 or grid[1] > 1)
    vmem_scratch = [
        pltpu.VMEM((2, ext_h, ext_w), block.dtype),
        pltpu.SemaphoreType.DMA((2,)),
        pltpu.SemaphoreType.DMA(()),
        pltpu.SemaphoreType.DMA((4,)),
        pltpu.SemaphoreType.DMA((4,)),
        pltpu.SMEM((1,), jnp.int32),  # deferred-wait ledger (overlap)
    ]
    if engage:
        out_idx = lambda c, a, b: (c, (a + 1) % gh, (b + 1) % gw)
    else:
        out_idx = lambda c, i, j: (c, i, j)
    if pad_operand is None:
        # Resolve from the EXECUTION mode already decided above, not the
        # global backend: a TPU-default process driving a forced-CPU mesh
        # passes interpret=True and must keep the scratch form covered.
        pad_operand = interpret is False
    if pad_operand:
        # Operand-backed pad: identical kernel body, but the HBM buffer
        # is a second OUTPUT (discarded) instead of a scratch entry (the
        # construct the chipless compile helper rejects — probe rung a
        # vs a0).  An output-only buffer is allocated uninitialized by
        # XLA, exactly like the scratch it replaces — no zero-fill tax —
        # and exactly as safe: the kernel overwrites the interior and
        # every ghost band it reads, and masks everything else
        # (the `ok` window mask).
        # (inputs, outputs, scratch) positional order makes the operand
        # form's ref list identical to the scratch form's signature —
        # the same kernel serves both.
        out, _ = pl.pallas_call(
            kernel,
            grid=(C, gh, gw),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=(pl.BlockSpec((1, th, tw), out_idx),
                       pl.BlockSpec(memory_space=pl.ANY)),
            out_shape=(shape_struct((C, gh * th, gw * tw), out_dtype, vma),
                       shape_struct((C, h_pad, w_pad), block.dtype, vma)),
            scratch_shapes=vmem_scratch,
            compiler_params=cparams,
            interpret=interpret,
        )(block)
        return out[:, :h, :w]
    out = pl.pallas_call(
        kernel,
        grid=(C, gh, gw),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((1, th, tw), out_idx),
        out_shape=shape_struct((C, gh * th, gw * tw), out_dtype, vma),
        scratch_shapes=[hbm_scratch((C, h_pad, w_pad),
                                    block.dtype)] + vmem_scratch,
        compiler_params=cparams,
        interpret=interpret,
    )(block)
    return out[:, :h, :w]
