"""Fused remote-DMA halo + stencil kernel (SURVEY.md §7 frontier).

The standard path (``parallel/halo.py``) rides XLA ``collective-permute``:
edge slabs are ppermuted, concatenated into a padded block *outside* the
kernel, and the Pallas kernel then re-reads the padded block from HBM.
This module is the design SURVEY.md §7 names as the halo-latency
optimization: ONE kernel per device per iteration that

1. pushes its edge slabs straight into its neighbors' VMEM with
   ``pltpu.make_async_remote_copy`` (RDMA over ICI — the reference's
   ``MPI_Isend`` with the network card writing into the remote ghost ring,
   except here it is the TPU's own DMA engines, no copy through XLA), and
2. computes the stencil level in the same program once its own ghosts
   arrive — no HBM round trip between exchange and compute.

Corner propagation uses the same two-phase trick as halo.py: column slabs
are sent at full padded height *after* the row-ghost receive semaphores
fire, so corners take two hops and no diagonal messages exist.  Ghost
regions with no inbound copy (image boundary, zero mode) are zeroed
locally — writes and inbound RDMA targets are disjoint by construction, so
there is no initialization race (checked by the interpreter's race
detector in tests/test_rdma.py).

Cross-invocation safety: within one invocation, waits on both the send and
receive semaphores retire every DMA before the kernel exits — but back-to-
back invocations (the fori_loop iteration driver) add a hazard the
per-invocation race detector cannot see: a fast device entering iteration
N+1 could push ghost bytes into a slow neighbor's scratch while the
neighbor still computes iteration N.  ``_neighbor_barrier`` closes it with
the canonical start-of-kernel rendezvous on the collective barrier
semaphore: no remote copy is issued until every RDMA partner has entered
the current invocation (tests/test_rdma.py::test_rdma_back_to_back_race
runs the multi-invocation protocol under the race detector).

STATUS: functionally validated — bit-exact against the oracle on the
multi-device CPU mesh under TPU interpret mode (which simulates remote
DMAs, semaphores, and the barrier).  On the one real chip available here
the kernel compiles via Mosaic and runs in its degenerate 1×1 local form,
bit-exact vs the oracle (recorded in BASELINE.md "RDMA on silicon");
multi-chip ICI perf remains unvalidated — no such hardware exists in this
environment.  VMEM budget: the whole (C, h+2r, w+2r) f32 padded block is
held in VMEM scratch, so per-device blocks are bounded by ~16 MB/f32 ≈
2048×2048 grey; larger blocks need the windowed-DMA tiling of
``_stencil_kernel`` (a fori_loop over window copies between the exchange
and the store) — left for when real multi-chip hardware can measure it.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from parallel_convolution_tpu.ops.collective_ids import collective_id
from parallel_convolution_tpu.ops.filters import Filter
from parallel_convolution_tpu.ops.pallas_stencil import (
    _correlate_window, _from_f32, _to_f32, on_tpu,
)

# Semaphore slots: one (send, recv) pair per direction.
_UP, _DOWN, _LEFT, _RIGHT = 0, 1, 2, 3


def _neighbor_barrier(dirs):
    """Start-of-kernel rendezvous with every RDMA partner.

    ``dirs`` is [(exists, (x, y) device id)] for the four cardinal
    neighbors.  Each device signals the global barrier semaphore of every
    existing neighbor, then waits until all of ITS neighbors have signaled
    it.  This closes the cross-invocation race the per-invocation race
    detector cannot see: without it, a fast device's iteration-N+1 remote
    copy could land in a slow neighbor's scratch while that neighbor is
    still computing iteration N.  After the barrier, every partner has
    entered the current invocation — and kernel invocations serialize on a
    core, so all of its previous-invocation reads have retired before any
    new ghost bytes arrive.

    Skew safety: a neighbor can run at most one invocation ahead, because
    completing invocation N+1 requires its own ``wait_recv`` on ghosts we
    only send after passing this barrier — so the wait below can never be
    satisfied by two signals from one fast neighbor standing in for a slow
    one.  Leftover signals (a neighbor already in N+2's barrier) simply
    pre-credit the next wait; counts stay balanced.
    """
    bsem = pltpu.get_barrier_semaphore()
    n_wait = jnp.int32(0)
    for exists, dev in dirs:
        if isinstance(exists, bool):
            if not exists:
                continue
            pltpu.semaphore_signal(bsem, inc=1, device_id=dev)
            n_wait = n_wait + 1
        else:
            @pl.when(exists)
            def _(dev=dev):
                pltpu.semaphore_signal(bsem, inc=1, device_id=dev)

            n_wait = n_wait + exists.astype(jnp.int32)
    pltpu.semaphore_wait(bsem, n_wait)


def _rdma_kernel(in_ref, out_ref, pad, send_sem, recv_sem, *,
                 taps, sep, k, r, C, h, w, R, Cc, periodic, quantize):
    """One device's program: exchange ghosts in-kernel, then stencil.

    ``pad`` is the (C, h+2r, w+2r) f32 working buffer; interior = my block,
    ghost ring = RDMA'd from neighbors (or zeros at a non-periodic image
    boundary).  All slab math mirrors halo.halo_exchange exactly.
    """
    x = lax.axis_index("x")
    y = lax.axis_index("y")

    # Interior + boundary-ghost initialization.  Inbound RDMA targets are
    # exactly the ghost regions owned by an existing neighbor, so local
    # writes below never overlap a remote write (no ordering needed).
    pad[:, r : r + h, r : r + w] = _to_f32(in_ref[...])

    up_in = (x > 0) if not periodic else (R > 1)
    down_in = (x < R - 1) if not periodic else (R > 1)
    left_in = (y > 0) if not periodic else (Cc > 1)
    right_in = (y < Cc - 1) if not periodic else (Cc > 1)

    zero_row = jnp.zeros((C, r, w), jnp.float32)
    zero_col = jnp.zeros((C, h + 2 * r, r), jnp.float32)

    @pl.when(jnp.logical_not(up_in))
    def _():
        pad[:, 0:r, r : r + w] = zero_row

    @pl.when(jnp.logical_not(down_in))
    def _():
        pad[:, h + r : h + 2 * r, r : r + w] = zero_row

    if periodic and R == 1:
        # Torus of height 1: my own opposite edge wraps to me (static).
        pad[:, 0:r, r : r + w] = pad[:, h : h + r, r : r + w]
        pad[:, h + r : h + 2 * r, r : r + w] = pad[:, r : 2 * r, r : r + w]

    def nbr(dx, dy):
        if periodic:
            return (lax.rem(x + dx + R, R), lax.rem(y + dy + Cc, Cc))
        return (x + dx, y + dy)

    # Cross-invocation safety: no remote copy may be issued until every
    # RDMA partner has entered THIS invocation (see _neighbor_barrier).
    # Self-wrap axes (periodic R==1 / Cc==1) have python-False predicates
    # and drop out statically.
    _neighbor_barrier([
        (up_in, nbr(-1, 0)), (down_in, nbr(+1, 0)),
        (left_in, nbr(0, -1)), (right_in, nbr(0, +1)),
    ])

    # --- Phase 1: rows.  My top interior rows -> upper neighbor's bottom
    # ghost; my bottom interior rows -> lower neighbor's top ghost.
    send_up = pltpu.make_async_remote_copy(
        pad.at[:, r : 2 * r, r : r + w],
        pad.at[:, h + r : h + 2 * r, r : r + w],
        send_sem.at[_UP], recv_sem.at[_UP], device_id=nbr(-1, 0),
    )
    send_down = pltpu.make_async_remote_copy(
        pad.at[:, h : h + r, r : r + w],
        pad.at[:, 0:r, r : r + w],
        send_sem.at[_DOWN], recv_sem.at[_DOWN], device_id=nbr(+1, 0),
    )
    if not (periodic and R == 1):
        pl.when(up_in)(send_up.start)
        pl.when(down_in)(send_down.start)
        pl.when(up_in)(send_up.wait_send)
        pl.when(down_in)(send_down.wait_send)
        # My bottom ghost is written by my lower neighbor's send_up copy,
        # which signals MY recv_sem[_UP] (SPMD symmetry), and vice versa.
        pl.when(down_in)(send_up.wait_recv)
        pl.when(up_in)(send_down.wait_recv)

    # --- Phase 2: columns at FULL padded height (includes the row ghosts
    # that just arrived -> corners propagate in two hops, halo.py §order).
    if periodic and Cc == 1:
        pad[:, :, 0:r] = pad[:, :, w : w + r]
        pad[:, :, w + r : w + 2 * r] = pad[:, :, r : 2 * r]
    else:

        @pl.when(jnp.logical_not(left_in))
        def _():
            pad[:, :, 0:r] = zero_col

        @pl.when(jnp.logical_not(right_in))
        def _():
            pad[:, :, w + r : w + 2 * r] = zero_col

        send_left = pltpu.make_async_remote_copy(
            pad.at[:, :, r : 2 * r],
            pad.at[:, :, w + r : w + 2 * r],
            send_sem.at[_LEFT], recv_sem.at[_LEFT], device_id=nbr(0, -1),
        )
        send_right = pltpu.make_async_remote_copy(
            pad.at[:, :, w : w + r],
            pad.at[:, :, 0:r],
            send_sem.at[_RIGHT], recv_sem.at[_RIGHT], device_id=nbr(0, +1),
        )
        pl.when(left_in)(send_left.start)
        pl.when(right_in)(send_right.start)
        pl.when(left_in)(send_left.wait_send)
        pl.when(right_in)(send_right.wait_send)
        pl.when(right_in)(send_left.wait_recv)
        pl.when(left_in)(send_right.wait_recv)

    # --- Compute: one stencil level on the fully-padded block.
    for c in range(C):
        acc = _correlate_window(pad[c], taps, sep, k, h, w)
        if quantize:
            acc = jnp.clip(jnp.rint(acc), 0.0, 255.0)
        out_ref[c] = _from_f32(acc, out_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("filt", "grid", "boundary", "quantize", "out_dtype",
                     "interpret"),
)
def fused_rdma_step(
    block: jnp.ndarray,
    filt: Filter,
    grid: tuple[int, int],
    boundary: str = "zero",
    quantize: bool = True,
    out_dtype=None,
    interpret=None,
) -> jnp.ndarray:
    """One halo-exchange + stencil iteration, entirely inside one kernel.

    Must be called inside ``shard_map`` over the ('x','y') mesh; ``block``
    is the local (C, h, w) tile.  Semantically identical to
    ``halo.halo_exchange`` followed by the one-step correlate (+ optional
    u8 quantization) — see tests/test_rdma.py for the bit-exactness proof.
    """
    if boundary not in ("zero", "periodic"):
        raise ValueError(f"boundary must be zero|periodic, got {boundary!r}")
    if interpret is None:
        interpret = (False if on_tpu()
                     else pltpu.InterpretParams(dma_execution_mode="on_wait"))
    if out_dtype is None:
        out_dtype = block.dtype
    C, h, w = block.shape
    r, k = filt.radius, filt.size
    if min(h, w) < r:
        raise ValueError(f"block {(h, w)} smaller than filter radius {r}")
    sep = None  # rank-1 split saves little at one level; keep 2D order
    taps = tuple(float(t) for t in filt.taps.reshape(-1))

    kernel = functools.partial(
        _rdma_kernel, taps=taps, sep=sep, k=k, r=r, C=C, h=h, w=w,
        R=grid[0], Cc=grid[1], periodic=boundary == "periodic",
        quantize=quantize,
    )
    vma = getattr(jax.typeof(block), "vma", frozenset())
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((C, h, w), out_dtype, vma=vma),
        scratch_shapes=[
            pltpu.VMEM((C, h + 2 * r, w + 2 * r), jnp.float32),
            pltpu.SemaphoreType.DMA((4,)),
            pltpu.SemaphoreType.DMA((4,)),
        ],
        compiler_params=pltpu.CompilerParams(
            collective_id=collective_id("rdma_halo_stencil"),
            has_side_effects=True,
        ),
        interpret=interpret,
    )(block)
