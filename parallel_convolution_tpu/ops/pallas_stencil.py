"""Pallas TPU stencil kernel (reference component C2 + C9, SURVEY.md §2).

The reference's hot loop is a per-pixel k×k multiply-add nest, multithreaded
with ``#pragma omp parallel for`` in the hybrid build.  Its TPU-native
equivalent is this Pallas kernel: the image block lives in HBM, a grid of
programs each DMAs one overlapping ``(TH+2r, TW+2r)`` window into VMEM
scratch, and the VPU (8×128 lanes — the OpenMP thread pool analog) computes
the same fixed-order shifted multiply-add the oracle defines, writing a
``(TH, TW)`` output tile.

Overlapping input windows cannot be expressed with blocked ``BlockSpec``
index maps (block start = index × block size), so the input uses
``memory_space=ANY`` and the kernel issues explicit ``make_async_copy``
windows — double-buffered across grid steps so the next tile's DMA overlaps
the current tile's compute (the reference's comm/compute-overlap idiom,
SURVEY.md §3.2, reborn on-chip).

Semantics contract: identical op order to ``ops.oracle.correlate_once`` /
``ops.conv.correlate_padded`` → float32 results are bit-identical, so the
kernel drops into the sharded step as a backend with no semantic change.
"""

from __future__ import annotations

import functools
import warnings

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from parallel_convolution_tpu.ops.filters import Filter
from parallel_convolution_tpu.utils.jax_compat import shape_struct, vma_of

# Default output-tile shapes: multiples of the f32 (8, 128) VMEM tile.
# Two defaults because Mosaic's scoped-VMEM stack scales differently per
# kernel form: the 2D tap loop keeps ~k² live (th, tw) f32 temporaries, so
# big tiles blow the 16 MB scoped limit (1024×512 f32 → 25.3 MB compile
# error on v5e); the separable form reuses one acc1/acc pair and takes
# large tiles fine.  Values chosen by scripts/tune_pallas.py on a real
# v5e (2026-07-29, tile threaded as an explicit static arg: 1024×512
# fuse32 123.8 Gpx/s vs 256×512 fuse32 116.8 — tile is a ~6% lever,
# fusion depth the ~4× one; 512×2048 fails Mosaic compile).
DEFAULT_TILE = (256, 512)
SEP_TILE = (1024, 512)


def _default_tile(sep) -> tuple[int, int]:
    return SEP_TILE if sep is not None else DEFAULT_TILE


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def _sublane(dtype) -> int:
    """Second-minor HBM/VMEM tile extent for a dtype (f32: 8, bf16: 16, u8: 32).

    Real Mosaic requires HBM DMA slice starts AND shapes aligned to the
    (sublane, 128) tiling — interpret mode does not enforce this, so every
    window extent below is rounded to it (first observed as a compile
    failure on silicon: "Slice shape along dimension 1 must be aligned to
    tiling (8), but is 258").
    """
    return 32 // jnp.dtype(dtype).itemsize


# Canonical implementation lives in utils.platform; re-exported here because
# kernel call sites (and the driver bench) historically import it from ops.
from parallel_convolution_tpu.utils.platform import on_tpu  # noqa: E402


def _to_f32(v):
    """Dtype-to-f32 inside a kernel; Mosaic (this jaxlib) has no direct
    u8↔f32 cast, so uint8 hops through int32 (exact for 0..255)."""
    if v.dtype == jnp.uint8:
        v = v.astype(jnp.int32)
    return v.astype(jnp.float32)


def _from_f32(v, dtype):
    """f32-to-storage-dtype inside a kernel (same Mosaic u8 hop)."""
    if jnp.dtype(dtype) == jnp.uint8:
        return v.astype(jnp.int32).astype(jnp.uint8)
    return v.astype(dtype)


def _sep_taps(filt: Filter, separable: bool):
    """Static (col_taps, row_taps) float tuples, or None if not requested
    or the filter has no exact rank-1 factorization."""
    if not separable:
        return None
    sep = filt.separable()
    if sep is None:
        return None
    col, row = sep
    return (tuple(float(t) for t in col), tuple(float(t) for t in row))


def _correlate_window(win, taps, sep, k, th, tw):
    """Stencil a (th+2r, tw+2r)+ f32-castable window down to (th, tw) f32.

    ``sep = (col_taps, row_taps)`` switches to the rank-1 two-pass form —
    2k MACs/px instead of k² (ops/conv.correlate_padded_separable's op
    order: full-height row pass, then column pass), bit-identical to the
    2D order for dyadic factors over u8-range values.  ``sep=None`` is the
    normative row-major 2D multiply-add.
    """
    if sep is not None:
        colt, rowt = sep
        acc1 = jnp.zeros((th + k - 1, tw), jnp.float32)
        for dx in range(k):
            acc1 = acc1 + jnp.float32(rowt[dx]) * _to_f32(
                win[: th + k - 1, dx : dx + tw])
        acc = jnp.zeros((th, tw), jnp.float32)
        for dy in range(k):
            acc = acc + jnp.float32(colt[dy]) * acc1[dy : dy + th, :]
        return acc
    acc = jnp.zeros((th, tw), jnp.float32)
    idx = 0
    for dy in range(k):
        for dx in range(k):
            # f32 accumulation even for narrow storage (cast is VPU-free-ish).
            w = _to_f32(win[dy : dy + th, dx : dx + tw])
            acc = acc + jnp.float32(taps[idx]) * w
            idx += 1
    return acc


def _prefetch_window(window_copy):
    """Double-buffered window pipeline shared by every gridded kernel.

    ``window_copy(cc, ii, jj, slot)`` must return the async copy of grid
    program (cc, ii, jj)'s window into scratch ``slot``.  Program n waits
    on the window it prefetched during program n-1 and starts program
    n+1's copy before computing (slot = parity of the linearized step);
    the first program primes the pipeline with its own window.  Returns
    the slot holding the current program's window.
    """
    c, i, j = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    ni, nj = pl.num_programs(1), pl.num_programs(2)
    step = (c * ni + i) * nj + j
    slot = jax.lax.rem(step, 2)

    @pl.when(step == 0)
    def _():
        window_copy(c, i, j, slot).start()

    last = step == pl.num_programs(0) * ni * nj - 1

    @pl.when(jnp.logical_not(last))
    def _():
        nstep = step + 1
        nc = nstep // (ni * nj)
        nij = jax.lax.rem(nstep, ni * nj)
        window_copy(nc, nij // nj, jax.lax.rem(nij, nj), 1 - slot).start()

    window_copy(c, i, j, slot).wait()
    return slot


# 1.5 * 2**23: adding it to an f32 x with |x| < 2**22 forces the mantissa
# to integer precision (ulp = 1), i.e. the hardware rounds x to the nearest
# integer half-to-even; subtracting recovers that integer losslessly.  Two
# f32 adds == jnp.rint, bit for bit, on the whole quantize-mode range.
_MAGIC = 12582912.0


def _round_mode_for(taps, interpret) -> str:
    """Pick the rint implementation for a kernel build.

    Mosaic lowers ``jnp.rint`` to a multi-op sequence; replacing it with
    the two-add magic-number form measured **+15.6% on the u8 flagship /
    +12.6% bf16** on real v5e silicon, byte-identical
    (``evidence/round_mode_ab_r5.jsonl``, 2026-07-31).  Exactness needs
    |acc| < 2**22; every quantize-mode accumulator is bounded by
    255 * L1(taps), so filters with L1 < 2**21/255 (every shipped filter
    by orders of magnitude) qualify — anything larger falls back to
    ``rint``.

    Interpret-mode kernels run through XLA:CPU, whose algebraic
    simplifier FOLDS ``(x + C) - C`` to ``x`` (measured: the round
    disappears entirely) — there the adds are pinned with
    ``lax.optimization_barrier``.  Mosaic neither folds (the silicon
    byte-proof above) nor implements the barrier primitive, so compiled
    kernels use the bare form.

    Because "Mosaic never folds" has no semantic guarantee, the first
    compiled (non-interpret) quantized build in a process runs a one-time
    byte-guard — a tiny compiled kernel vs the NumPy oracle
    (``_compiled_magic_ok``, ADVICE r5).  On mismatch every compiled
    kernel falls back to ``rint`` with a loud warning, so CLI/library
    users on a future jax/Mosaic upgrade lose ~14% throughput, never
    correct bytes.
    """
    l1 = sum(abs(float(t)) for t in taps)
    if 255.0 * l1 >= 2.0**21:  # 2x safety margin under the 2**22 bound
        return "rint"
    if interpret:
        return "magic_barrier"
    return "magic" if _compiled_magic_ok() else "rint"


# Process-wide magic-round guard state: ``ok`` None = not yet probed;
# ``probing`` breaks the probe's own recursion into _round_mode_for (the
# probe kernel must build the very form under test); ``cause`` records
# WHY ok went False — "mismatch" (the compiler really folds the round; a
# terminal condition for automation) vs "probe-error" (the probe itself
# crashed; retryable — same conservative rint fallback, different verdict).
_MAGIC_GUARD = {"ok": None, "probing": False, "cause": None}


def _probe_magic_round() -> bool:
    """Byte-compare ONE tiny compiled quantized kernel vs the NumPy oracle.

    Two chained quantized blur3 steps on a deterministic 16×128 grey
    plane — enough to catch a compiler that folds the two-add round (the
    rounding then vanishes and bytes diverge on the first store-back).
    Runs exactly once per process, on the first compiled quantized kernel
    build (sub-second next to any real workload's compile).
    """
    import numpy as np

    from parallel_convolution_tpu.ops import oracle
    from parallel_convolution_tpu.ops.filters import get_filter

    filt = get_filter("blur3")
    rng = np.random.default_rng(12)
    img = rng.integers(0, 256, size=(16, 128)).astype(np.uint8)
    want = oracle.run_serial_u8(img, filt, 2)
    # The selector — and hence this probe — is reached from INSIDE the
    # caller's jit trace (every quantized entry point is @jax.jit), where
    # np.asarray(got) would see a tracer and kill the probe on every
    # compiled build (reproduced: TracerArrayConversionError -> permanent
    # rint fallback).  jax trace state is thread-local, so a worker
    # thread starts from the eval trace — escaping the ambient trace
    # while keeping the probe's own inner jit/pallas compile intact
    # (ensure_compile_time_eval would instead disable the inner jit and
    # eval the pallas_call eagerly, which has no eval rules).
    import concurrent.futures

    def run():
        got = jnp.asarray(img[None], jnp.float32)
        for _ in range(2):
            got = correlate_shifted_pallas(got, filt, quantize=True,
                                           interpret=False)
        return np.asarray(got)

    with concurrent.futures.ThreadPoolExecutor(max_workers=1) as ex:
        got = ex.submit(run).result()
    return bool(np.array_equal(got[0].astype(np.uint8), want))


def _compiled_magic_ok() -> bool:
    """One-time compiled-magic-round byte-guard, cached per process.

    True → compiled kernels keep the two-add magic round.  False (byte
    mismatch, or the probe itself failed) → fall back to ``jnp.rint``
    with a RuntimeWarning: correctness must not hinge on an unverified
    compiler non-folding guarantee.  The driver bench's end-to-end guard
    (bench.py ``magic_round_guard``) stays as the independent second
    layer; this one protects CLI/library entry points too.
    """
    st = _MAGIC_GUARD
    if st["probing"]:
        return True  # the probe's own kernel builds the form under test
    if st["ok"] is None:
        st["probing"] = True
        try:
            st["ok"] = _probe_magic_round()
            if not st["ok"]:
                st["cause"] = "mismatch"
                warnings.warn(
                    "magic-round byte-guard MISMATCH: a compiled quantized "
                    "kernel diverged from the oracle (the compiler may now "
                    "fold the two-add round) — falling back to jnp.rint "
                    "for all compiled kernels this process",
                    RuntimeWarning, stacklevel=3)
        except Exception as e:  # probe failure: bytes unverified
            st["ok"] = False
            st["cause"] = "probe-error"
            warnings.warn(
                f"magic-round byte-guard probe failed ({e!r}) — falling "
                "back to jnp.rint for all compiled kernels this process",
                RuntimeWarning, stacklevel=3)
        finally:
            st["probing"] = False
    return st["ok"]


def _quantize_acc(acc, convex, round_mode):
    """In-kernel u8 store-back on an f32 acc: rint, then clip — except the
    clip is elided for convex filters, where it is provably the identity
    (``Filter.convex``); results are bit-identical either way.

    ``round_mode`` selects the rint implementation (see
    ``_round_mode_for``); all three compute the same function."""
    if round_mode == "magic":
        acc = (acc + _MAGIC) - _MAGIC
    elif round_mode == "magic_barrier":
        acc = jax.lax.optimization_barrier(acc + _MAGIC) - _MAGIC
    else:
        acc = jnp.rint(acc)
    if not convex:
        acc = jnp.clip(acc, 0.0, 255.0)
    return acc


def _stencil_kernel(hbm_ref, out_ref, scratch, sems, *, taps, sep, k, r, th,
                    tw, ext_h, ext_w, quantize, convex, round_mode):
    """One grid program: DMA window c,i,j → VMEM, stencil it, emit tile.

    ``scratch`` holds two (ext_h, ext_w) slots — the (th+2r, tw+2r)
    stencil window rounded up to the HBM tiling (see ``_sublane``); the
    alignment rim is DMA'd but never read.
    """

    def window_copy(cc, ii, jj, slot):
        return pltpu.make_async_copy(
            hbm_ref.at[cc, pl.ds(ii * th, ext_h), pl.ds(jj * tw, ext_w)],
            scratch.at[slot],
            sems.at[slot],
        )

    slot = _prefetch_window(window_copy)

    acc = _correlate_window(scratch[slot], taps, sep, k, th, tw)
    if quantize:
        # Fused u8 store-back: saves one full HBM round trip per iteration
        # vs quantizing in a separate XLA fusion after the kernel.
        acc = _quantize_acc(acc, convex, round_mode)
    out_ref[0] = _from_f32(acc, out_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("filt", "tile", "interpret", "quantize", "out_dtype",
                     "separable"),
)
def correlate_padded_pallas(
    padded: jnp.ndarray,
    filt: Filter,
    tile: tuple[int, int] | None = None,
    interpret: bool | None = None,
    quantize: bool = False,
    out_dtype=None,
    separable: bool = False,
) -> jnp.ndarray:
    """Stencil an already-padded (C, H+2r, W+2r) block → (C, H, W).

    Drop-in replacement for ``ops.conv.correlate_padded`` (same normative op
    order).  ``interpret=None`` auto-selects the Pallas interpreter off-TPU
    so the kernel is testable on the forced-CPU mesh.

    ``quantize=True`` fuses the u8 store-back into the kernel;
    ``out_dtype`` (default: input dtype if quantizing, else f32) enables
    bf16 storage — quantized values are exact integers ≤ 255, which bf16
    represents exactly, so bf16 carries halve HBM/ICI traffic with no
    semantic change.

    ``separable=True`` uses the rank-1 two-pass form when the filter has an
    exact dyadic factorization (2k MACs/px instead of k² — the VPU-bound
    fused path's main cost); silently falls back to 2D otherwise.  Same
    exactness contract as the XLA 'separable' backend: bit-identical in
    quantize mode, a rounding-order change in float mode.
    """
    if interpret is None:
        interpret = not on_tpu()
    if out_dtype is None:
        out_dtype = padded.dtype if quantize else jnp.float32
    sep = _sep_taps(filt, separable)
    if tile is None:
        tile = _default_tile(sep)
    r = filt.radius
    k = filt.size
    C, Hp, Wp = padded.shape
    H, W = Hp - 2 * r, Wp - 2 * r

    sub = _sublane(padded.dtype)
    th, tw, gh, gw = fused_tile_grid((H, W), padded.dtype, tile, sep)
    # Tile-aligned DMA window: starts i*th / j*tw are aligned because
    # th % sub == 0 and tw % 128 == 0; extents rounded up from th+2r.
    ext_h, ext_w = th + _round_up(2 * r, sub), tw + _round_up(2 * r, 128)
    # Round the compute domain up to whole tiles plus the alignment rim;
    # the rim is garbage-over-zeros, never read, and sliced off below.
    eh = (gh - 1) * th + ext_h - Hp
    ew = (gw - 1) * tw + ext_w - Wp
    if eh > 0 or ew > 0:
        padded = jnp.pad(padded, ((0, 0), (0, max(eh, 0)), (0, max(ew, 0))))

    taps = tuple(float(t) for t in filt.taps.reshape(-1))
    kernel = functools.partial(
        _stencil_kernel, taps=taps, sep=sep,
        k=k, r=r, th=th, tw=tw, ext_h=ext_h, ext_w=ext_w, quantize=quantize,
        convex=filt.convex,
        round_mode=(_round_mode_for(taps, interpret) if quantize
                    else "rint"),  # unused when not quantizing: skip the
                                   # compiled-probe guard a float build
                                   # would otherwise pay for nothing
    )
    # Propagate varying-mesh-axes so the kernel composes under shard_map
    # (check_vma needs the out type to declare what it varies over).
    vma = vma_of(padded)
    out = pl.pallas_call(
        kernel,
        grid=(C, gh, gw),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((1, th, tw), lambda c, i, j: (c, i, j)),
        out_shape=shape_struct((C, gh * th, gw * tw), out_dtype, vma),
        scratch_shapes=[
            pltpu.VMEM((2, ext_h, ext_w), padded.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=interpret,
    )(padded)
    return out[:, :H, :W]


def correlate_shifted_pallas(x: jnp.ndarray, filt: Filter, **kw) -> jnp.ndarray:
    """Zero-padded stencil step on unpadded (C, H, W) via the Pallas kernel."""
    r = filt.radius
    return correlate_padded_pallas(
        jnp.pad(x, ((0, 0), (r, r), (r, r))), filt, **kw
    )


# ---------------------------------------------------------------------------
# Temporal fusion kernel: T stencil iterations per HBM round trip.
# ---------------------------------------------------------------------------


def _norm_block_off(block_off):
    """Normalize a static block offset to ((r0_lo, r0_hi), (c0_lo, c0_hi)).

    Accepts the exact-offset shorthand ``(r0, c0)`` or the range form — the
    range form exists because one SPMD program can serve a *class* of
    device positions (e.g. every non-edge row of a grid), whose offsets
    share interior geometry without being a single static value.
    """
    r, c = block_off
    r = (int(r), int(r)) if not hasattr(r, "__len__") else (int(r[0]), int(r[1]))
    c = (int(c), int(c)) if not hasattr(c, "__len__") else (int(c[0]), int(c[1]))
    return r, c


def _interior_range(valid_hw, tile_hw, depth, grid_hw, block_off=(0, 0)):
    """Inclusive (i, j) grid ranges whose level-0 windows sit fully inside
    the image, for a block at static global offset ``block_off``.

    Tile (i, j) of a block at offset (r0, c0) covers image rows
    [r0 + i*th - depth, r0 + i*th + th + depth); it is interior iff that
    range lies in [0, H) (ditto columns).  ``block_off`` components may be
    (lo, hi) ranges — the bounds are then conservative over every offset
    in the range (lo decides the low edge, hi the high edge), so one
    result serves a whole class of device positions.  Returns None when no
    tile qualifies (then the split is pointless).
    """
    H, W = valid_hw
    th, tw = tile_hw
    gh, gw = grid_hw
    (r0l, r0h), (c0l, c0h) = _norm_block_off(block_off)
    i_lo = max(0, -(-(depth - r0l) // th))   # smallest i: r0 + i*th >= depth
    i_hi = (H - r0h - th - depth) // th      # largest i: end <= H
    j_lo = max(0, -(-(depth - c0l) // tw))
    j_hi = (W - c0h - tw - depth) // tw
    i_hi, j_hi = min(i_hi, gh - 1), min(j_hi, gw - 1)
    if i_lo > i_hi or j_lo > j_hi:
        return None
    return (i_lo, i_hi), (j_lo, j_hi)


def fused_tile_grid(valid_hw, dtype, tile, sep=None):
    """Static (th, tw, gh, gw) the fused launch uses for a block of valid
    extent ``valid_hw``: the requested tile rounded to the dtype's
    (sublane, 128) tiling and clamped to the block, and the resulting
    tile-grid shape.  Shared between ``fused_iterate_pallas`` and the
    geometry-prediction tooling (scripts/profile_flagship.py) so a
    prediction can never drift from the real launch."""
    h, w = valid_hw
    if tile is None:
        tile = _default_tile(sep)
    sub = _sublane(dtype)
    th = min(_round_up(tile[0], sub), _round_up(h, sub))
    tw = min(_round_up(tile[1], 128), _round_up(w, 128))
    return th, tw, -(-h // th), -(-w // tw)


def split_patches(split, grid_hw):
    """The 9-patch launch plan for an interior box: a list of
    ``((i0, i1), (j0, j1), (mask_rows, mask_cols))`` patches partitioning
    the ``grid_hw`` tile grid (half-open band bounds), with per-patch
    static mask axes — the middle×middle patch unmasked, pure-edge
    patches keeping only the axis their tiles can cross, corners both.

    Single source of truth shared by the ``fused_iterate_pallas`` launch
    and the geometry-prediction tooling (scripts/profile_flagship.py), so
    the op-ledger prediction cannot drift from the real launch (the
    5-strip → 9-patch refinement did exactly that to the old inline
    formula)."""
    (i_lo, i_hi), (j_lo, j_hi) = split
    gh, gw = grid_hw
    row_bands = [(0, i_lo, True), (i_lo, i_hi + 1, False),
                 (i_hi + 1, gh, True)]
    col_bands = [(0, j_lo, True), (j_lo, j_hi + 1, False),
                 (j_hi + 1, gw, True)]
    return [((r0, r1), (c0, c1), (mr, mc))
            for r0, r1, mr in row_bands if r1 > r0
            for c0, c1, mc in col_bands if c1 > c0]


def axis_offset_classes(n_dev: int, block: int):
    """Static block-offset classes along one grid axis, as (lo, hi) ranges.

    Under shard_map a device's block offset ``a * block`` is dynamic, but
    its *interior geometry* only depends on which image edges the block
    can touch — so devices collapse into at most three static classes per
    axis: first row (offset exactly 0), last row (exactly (n-1)*block),
    and the middle band (offsets in [block, (n-2)*block], which
    ``_interior_range`` treats conservatively).  The caller dispatches on
    the dynamic axis index (``step._axis_class_index``) to the per-class
    specialized launch; this is what makes the unmasked-interior split
    reachable on any grid, not just 1×1.
    """
    if n_dev == 1:
        return [(0, 0)]
    if n_dev == 2:
        return [(0, 0), (block, block)]
    last = (n_dev - 1) * block
    return [(0, 0), (block, last - block), (last, last)]


def _iterate_levels(cur, *, taps, sep, k, r, T, out_hw, quantize, convex,
                    round_mode, rows0=None, cols0=None, valid_hw=None):
    """T level-shrinking stencil levels: (oh + 2rT, ow + 2rT) f32 → (oh, ow).

    The single source of the temporal-fusion compute shape, shared by the
    ppermute fused kernel (``_fused_kernel``) and both RDMA fuse>1 kernels
    (``ops/pallas_rdma.py``) so the quantize path — magic round included —
    and the tap chain (2D or separable ``sep``) are threaded identically
    everywhere.

    Per level the window shrinks by r; ``rows0``/``cols0`` are the hoisted
    GLOBAL-coordinate iotas of the level-0 window ((w0h, 1) / (1, w0w));
    when present, out-of-``valid_hw`` positions of every level are
    re-zeroed with the cheap rank-1 broadcast multiplies — exactly the
    oracle's ghost ring at every intermediate level.  ``None`` statically
    drops that mask axis (periodic torus, or a provably-interior launch).
    Every level-0 value must already be finite (the caller's select tier)
    — a multiplicative mask would leak NaN otherwise.
    """
    oh, ow = out_hw
    H, W = valid_hw if valid_hw is not None else (None, None)
    for s in range(1, T + 1):
        ch, cw = oh + 2 * r * (T - s), ow + 2 * r * (T - s)
        acc = _correlate_window(cur, taps, sep, k, ch, cw)
        if quantize:
            acc = _quantize_acc(acc, convex, round_mode)
        # Level-s window starts r*s deeper; slice the hoisted iotas.
        if rows0 is not None:
            rows = rows0[r * s : r * s + ch, :]
            acc = acc * ((rows >= 0) & (rows < H)).astype(jnp.float32)
        if cols0 is not None:
            cols = cols0[:, r * s : r * s + cw]
            acc = acc * ((cols >= 0) & (cols < W)).astype(jnp.float32)
        cur = acc
    return cur


def _fused_kernel(off_ref, hbm_ref, out_ref, scratch, sems, *,
                  taps, sep, k, r, T, th, tw, ext_h, ext_w, valid_hw,
                  quantize, convex, round_mode, grid_off=(0, 0),
                  mask_rows=True, mask_cols=True):
    """T in-VMEM stencil levels on one (th + 2rT, tw + 2rT) window.

    The window shrinks by r per level; after each level, positions outside
    the valid global image are re-zeroed (the oracle's ghost ring at every
    intermediate level) using the shard's global offset from SMEM.  One HBM
    read + one HBM write buy T iterations — the bandwidth analog of the
    fuse=T collective saving.

    ``mask_rows`` / ``mask_cols`` statically drop one masking axis for
    launches whose tiles provably cannot cross that pair of image edges
    (the 9-patch interior split): a top-band middle tile needs only row
    masking, a left-band middle only column masking.  Sound for the same
    reason the fully-unmasked interior is: the skipped mask is the
    identity there.
    """
    gi0, gj0 = grid_off
    i, j = pl.program_id(1) + gi0, pl.program_id(2) + gj0

    def window_copy(cc, ii, jj, slot):
        return pltpu.make_async_copy(
            hbm_ref.at[cc, pl.ds((ii + gi0) * th, ext_h),
                       pl.ds((jj + gj0) * tw, ext_w)],
            scratch.at[slot],
            sems.at[slot],
        )

    slot = _prefetch_window(window_copy)

    # Global coords of the window's top-left at level 0.  The scratch slot
    # is the (th+2rT, tw+2rT) stencil window plus an alignment rim (bottom/
    # right) that is DMA'd but dropped here.
    row0 = off_ref[0] - r * T + i * th
    col0 = off_ref[1] - r * T + j * tw
    cur = _to_f32(scratch[slot][: th + 2 * r * T, : tw + 2 * r * T])
    mask_rows = mask_rows and valid_hw is not None
    mask_cols = mask_cols and valid_hw is not None
    rows0 = cols0 = None
    if mask_rows or mask_cols:
        # Ghost-ring masking in two tiers (no tier at all = periodic
        # torus or a provably-interior launch):
        #
        # 1. ONE select on the level-0 window: out-of-image positions
        #    (halo beyond the image edge, pad rim) are forced to exactly 0,
        #    so any non-finite garbage the DMA may have carried dies here
        #    (a multiplicative mask alone would leak it: 0 * NaN = NaN).
        #    Restricted to the statically-live axes: a skipped axis is
        #    provably in-image, hence genuine finite data.
        # 2. Per level, the cheap rank-1 form: the out-of-image region of
        #    any level's window is a row band ⊗ column band, so re-zeroing
        #    is one broadcast multiply per live axis (~1 VPU op/px each).
        #    Exact because tier 1 guarantees every intermediate is finite.
        #    Measured on v5e: per-level 2D select instead cost ~20%
        #    throughput at fuse=16 AND ~2× Mosaic compile time per
        #    doubling of T.
        #
        # Branching around the mask for interior tiles is NOT worth it:
        # one lax.cond per program measured 40% slower on Mosaic than
        # unconditional masking (it stalls the DMA/compute pipeline) —
        # the launch split exists precisely to make this static.
        H, W = valid_hw
        w0h, w0w = th + 2 * r * T, tw + 2 * r * T
        ok0 = None
        if mask_rows:
            rows0 = row0 + jax.lax.broadcasted_iota(jnp.int32, (w0h, 1), 0)
            ok0 = (rows0 >= 0) & (rows0 < H)
        if mask_cols:
            cols0 = col0 + jax.lax.broadcasted_iota(jnp.int32, (1, w0w), 1)
            okc0 = (cols0 >= 0) & (cols0 < W)
            ok0 = okc0 if ok0 is None else (ok0 & okc0)
        cur = jnp.where(ok0, cur, 0.0)
    cur = _iterate_levels(cur, taps=taps, sep=sep, k=k, r=r, T=T,
                          out_hw=(th, tw), quantize=quantize, convex=convex,
                          round_mode=round_mode, rows0=rows0, cols0=cols0,
                          valid_hw=valid_hw)
    out_ref[0] = _from_f32(cur, out_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("filt", "T", "valid_hw", "tile", "interpret",
                     "quantize", "out_dtype", "separable", "interior_split",
                     "block_off"),
)
def fused_iterate_pallas(
    padded: jnp.ndarray,
    offsets: jnp.ndarray,
    filt: Filter,
    T: int,
    valid_hw: tuple[int, int],
    tile: tuple[int, int] | None = None,
    interpret: bool | None = None,
    quantize: bool = True,
    out_dtype=None,
    separable: bool = False,
    interior_split: bool = False,
    block_off: tuple | None = None,
) -> jnp.ndarray:
    """T stencil iterations of a deep-padded (C, h+2rT, w+2rT) block.

    ``padded`` comes from a depth-``r*T`` halo exchange; ``offsets`` is a
    (2,) int32 array holding the block's global (row0, col0) — dynamic under
    shard_map — used for per-level ghost-ring masking against ``valid_hw``.
    Bit-exact with T applications of the one-step kernel (same op order,
    intermediates at full f32 in VMEM).

    ``interior_split=True`` splits the launch into a 9-patch: an UNMASKED
    interior call, pure-edge band calls that statically keep only ONE
    mask axis (a top-band middle tile can only cross the top edge, so
    column masking is provably the identity there — rows-only; left/right
    middles mirror it cols-only), and corner patches keeping both.
    Interior tiles skip the per-level ghost-ring multiplies (~2 of ~9
    VPU ops/px/level) and the level-0 select; pure-edge tiles skip one.
    It requires ``block_off`` — the STATIC global offset of this block,
    either exact ``(r0, c0)`` or per-component ``(lo, hi)`` ranges
    covering every offset one SPMD program may see (see
    ``axis_offset_classes``); the runtime ``offsets`` array must lie
    within it.  Raises ValueError if ``block_off`` is missing, so a
    caller on a sharded layout cannot silently skip ghost-ring masking
    with offsets the classification never saw.  The masked border calls
    keep using the dynamic ``offsets``, so offset *ranges* are exact, not
    approximate.  Bit-identical by construction (the masks it skips are
    the identity there); measured on its own bench row before ever
    becoming a default.
    """
    if interpret is None:
        interpret = not on_tpu()
    if out_dtype is None:
        out_dtype = padded.dtype
    sep = _sep_taps(filt, separable)
    if tile is None:
        tile = _default_tile(sep)
    r, k = filt.radius, filt.size
    C, Hp, Wp = padded.shape
    h, w = Hp - 2 * r * T, Wp - 2 * r * T

    sub = _sublane(padded.dtype)
    th, tw, gh, gw = fused_tile_grid((h, w), padded.dtype, tile, sep)
    ext_h = th + _round_up(2 * r * T, sub)
    ext_w = tw + _round_up(2 * r * T, 128)
    eh = (gh - 1) * th + ext_h - Hp
    ew = (gw - 1) * tw + ext_w - Wp
    if eh > 0 or ew > 0:
        padded = jnp.pad(padded, ((0, 0), (0, max(eh, 0)), (0, max(ew, 0))))

    taps = tuple(float(t) for t in filt.taps.reshape(-1))
    vma = vma_of(padded)
    off32 = offsets.astype(jnp.int32)

    def call(grid_hw, grid_off, mask_axes=(True, True)):
        mr, mc = mask_axes
        kernel = functools.partial(
            _fused_kernel, taps=taps, sep=sep,
            k=k, r=r, T=T, th=th, tw=tw, ext_h=ext_h, ext_w=ext_w,
            valid_hw=(tuple(valid_hw)
                      if (mr or mc) and valid_hw is not None else None),
            quantize=quantize, convex=filt.convex,
            round_mode=(_round_mode_for(taps, interpret) if quantize
                        else "rint"),  # dead when not quantizing
            grid_off=grid_off,
            mask_rows=mr, mask_cols=mc,
        )
        cgh, cgw = grid_hw
        return pl.pallas_call(
            kernel,
            grid=(C, cgh, cgw),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=pl.BlockSpec((1, th, tw), lambda c, i, j: (c, i, j)),
            out_shape=shape_struct((C, cgh * th, cgw * tw), out_dtype, vma),
            scratch_shapes=[
                pltpu.VMEM((2, ext_h, ext_w), padded.dtype),
                pltpu.SemaphoreType.DMA((2,)),
            ],
            interpret=interpret,
        )(off32, padded)

    split = None
    if interior_split and valid_hw is not None:
        if block_off is None:
            raise ValueError(
                "interior_split requires a static block_off — the global "
                "(row0, col0) of this block, exact or as (lo, hi) ranges; "
                "without it the unmasked-interior classification cannot be "
                "sound for arbitrary runtime offsets"
            )
        split = _interior_range(valid_hw, (th, tw), r * T, (gh, gw),
                                block_off)
    if split is None:
        return call((gh, gw), (0, 0))[:, :h, :w]

    # 9-patch launch (split_patches is the shared plan): the
    # middle×middle patch runs fully unmasked; a pure-edge patch (middle
    # on one axis) statically drops the other axis's mask — its tiles
    # are interior along that axis by the box construction.
    patches = split_patches(split, (gh, gw))
    bands, row_calls, cur_row = [], [], None
    for (r0b, r1b), (c0b, c1b), axes in patches:
        if cur_row is not None and (r0b, r1b) != cur_row:
            bands.append(jnp.concatenate(row_calls, axis=2)
                         if len(row_calls) > 1 else row_calls[0])
            row_calls = []
        cur_row = (r0b, r1b)
        row_calls.append(call((r1b - r0b, c1b - c0b), (r0b, c0b), axes))
    bands.append(jnp.concatenate(row_calls, axis=2)
                 if len(row_calls) > 1 else row_calls[0])
    out = jnp.concatenate(bands, axis=1) if len(bands) > 1 else bands[0]
    return out[:, :h, :w]
