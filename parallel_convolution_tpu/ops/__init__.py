"""Compute layer: filter definitions, serial oracle, lax + Pallas kernels."""
