"""NumPy serial oracle (reference components C1/C2, SURVEY.md §2–§3.1).

The reference repo validates its MPI / MPI+OpenMP variants by byte-comparing
their output ``.raw`` images against the serial binary's output.  This module
*is* that serial binary for the new framework: a single-threaded NumPy
implementation whose behavior is the specification every JAX / Pallas /
sharded path must match **bit-exactly**.

Semantics specification (normative — the reference mount was empty during the
survey, so per SURVEY.md §7 the oracle defines the spec):

1. The image is zero-padded by the filter radius on all four sides each
   iteration (the reference's ``(rows+2)×(cols+2)`` ghost ring of zeros,
   SURVEY.md §3.1).
2. One iteration computes, per pixel (per channel for RGB), the
   cross-correlation with the filter taps accumulated in **float32** as a
   fixed row-major sequence of shifted multiply-adds:
   ``acc = ((t00*x00 + t01*x01) + t02*x02) + ...`` — the same op/order every
   backend uses, so float32 results are bit-identical across NumPy, XLA:CPU,
   XLA:TPU and Pallas.
3. uint8 mode (image filtering): ``out = uint8(clip(rint(acc), 0, 255))``
   after every iteration — the u8 store-back of the reference's
   ``unsigned char`` buffers.
4. float mode (Jacobi smoothing, BASELINE config 5): no quantization; the
   carry stays float32.
5. Double buffering (C8) is the functional ``src → dst`` of each iteration.

Grayscale images are ``(H, W)`` arrays; RGB are ``(H, W, 3)`` (the channel
axis vectorizes transparently — each channel is convolved independently, as
the reference's stride-3 interleaved loop does).
"""

from __future__ import annotations

import numpy as np

from parallel_convolution_tpu.ops.filters import Filter


def _shifted_windows(padded: np.ndarray, k: int, H: int, W: int):
    """Yield the k*k shifted (H, W[, C]) views of a padded array, row-major."""
    for dy in range(k):
        for dx in range(k):
            yield padded[dy : dy + H, dx : dx + W]


def correlate_once(img_f32: np.ndarray, filt: Filter,
                   boundary: str = "zero") -> np.ndarray:
    """One padded cross-correlation step in float32 (no quantization).

    ``img_f32``: (H, W) or (H, W, C) float32.  Returns same shape float32.
    The accumulation is the normative fixed-order shifted multiply-add,
    where "multiply-add" means numpy's TWO-rounding form: ``tap * win``
    rounds to f32, then ``+=`` rounds again (the C++ serial tier pins the
    same form with ``-ffp-contract=off``).  The accelerator tiers contract
    each tap into a single-rounding FMA (the VPU's native op; verified on
    XLA:CPU — round-5 soak find, DESIGN.md "bit-exactness" note).  The two
    forms are bit-identical wherever every product and partial sum is
    exactly representable — which the u8 quantize-mode semantics guarantee
    at every level, so the byte-compare contract is unaffected — but f32
    float-mode runs diverge by ulps once intermediate mantissas fill
    (observed at iteration >= 3 of gaussian5 on u8-valued inputs).
    ``boundary``: 'zero' (the reference's ghost ring) or 'periodic' (torus
    wrap, the simulation-style ring topology).
    """
    img_f32 = np.ascontiguousarray(img_f32, dtype=np.float32)
    H, W = img_f32.shape[:2]
    k = filt.size
    r = filt.radius
    pad = [(r, r), (r, r)] + [(0, 0)] * (img_f32.ndim - 2)
    mode = {"zero": "constant", "periodic": "wrap"}[boundary]
    padded = np.pad(img_f32, pad, mode=mode)
    taps = filt.taps.reshape(k * k)
    acc = np.zeros_like(img_f32)
    for tap, win in zip(taps, _shifted_windows(padded, k, H, W)):
        # In-place += of tap*win: one multiply-add per tap, fixed order.
        acc += np.float32(tap) * win
    return acc


def quantize_u8(acc_f32: np.ndarray) -> np.ndarray:
    """The normative float32 → uint8 store-back: rint (half-to-even), clip."""
    return np.clip(np.rint(acc_f32), 0.0, 255.0).astype(np.uint8)


def convolve_once_u8(img_u8: np.ndarray, filt: Filter,
                     boundary: str = "zero") -> np.ndarray:
    """One full uint8 iteration: u8 → f32 → correlate → quantize → u8."""
    return quantize_u8(
        correlate_once(img_u8.astype(np.float32), filt, boundary)
    )


def run_serial_u8(img_u8: np.ndarray, filt: Filter, iters: int,
                  boundary: str = "zero") -> np.ndarray:
    """The serial reference run (C1): ``iters`` iterations with u8 store-back.

    Mirrors the reference's hot loop (SURVEY.md §3.1): convolute + buffer
    swap, ``iters`` times.  This is the golden output for every test.
    """
    out = np.asarray(img_u8, dtype=np.uint8)
    for _ in range(iters):
        out = convolve_once_u8(out, filt, boundary)
    return out


def run_serial_f32(img_f32: np.ndarray, filt: Filter, iters: int,
                   boundary: str = "zero") -> np.ndarray:
    """Float-mode serial run (Jacobi smoothing; no per-iteration quantization)."""
    out = np.asarray(img_f32, dtype=np.float32)
    for _ in range(iters):
        out = correlate_once(out, filt, boundary)
    return out


def run_to_convergence_f32(
    img_f32: np.ndarray,
    filt: Filter,
    tol: float,
    max_iters: int,
    check_every: int = 1,
    boundary: str = "zero",
) -> tuple[np.ndarray, int]:
    """Serial run-to-convergence oracle (C6 semantics, BASELINE config 5).

    Convergence means one iteration changes the array by less than ``tol``
    in max-abs norm; the check runs on the last iteration of every
    ``check_every``-sized chunk (mirroring the reference's every-N
    ``MPI_Allreduce``), or until ``max_iters``.  Returns
    ``(result, iterations_run)``.
    """
    cur = np.asarray(img_f32, dtype=np.float32)
    done = 0
    while done < max_iters:
        step = min(check_every, max_iters - done)
        prev = cur
        for _ in range(step):
            prev = cur
            cur = correlate_once(cur, filt, boundary)
        done += step
        diff = float(np.max(np.abs(cur - prev))) if cur.size else 0.0
        if diff < tol:
            break
    return cur, done
