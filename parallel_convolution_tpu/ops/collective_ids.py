"""Static registry of Mosaic ``collective_id`` slots.

Every Pallas kernel that performs cross-device communication (remote DMA,
``get_barrier_semaphore``) must carry a ``collective_id`` in its
``CompilerParams``; kernels sharing an id share the same global barrier
semaphore, so two *different* concurrent collective kernels with the same
id would corrupt each other's barrier counts.  The reference had the same
class of resource (MPI tags); its analog of this table is the implicit
"one communicator, distinct tags per direction" convention.

Ids are assigned statically here — not first-come-first-served at import
time — so that every process in a multi-host program agrees on the
mapping regardless of import order.
"""

from __future__ import annotations

_COLLECTIVE_IDS: dict[str, int] = {
    # The fused remote-DMA halo + stencil kernel (ops/pallas_rdma.py).
    "rdma_halo_stencil": 1,
}


def collective_id(name: str) -> int:
    """Look up a kernel's statically assigned collective id."""
    try:
        return _COLLECTIVE_IDS[name]
    except KeyError:
        raise KeyError(
            f"no collective_id registered for {name!r}; add it to "
            f"ops/collective_ids.py (taken: {_COLLECTIVE_IDS})"
        ) from None
