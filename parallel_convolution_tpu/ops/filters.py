"""Filter registry (reference component C3, SURVEY.md §2).

The reference hard-codes one normalized blur kernel (a float ``h[3][3]``,
expected ``{{1,2,1},{2,4,2},{1,2,1}}/16``) at the top of its kernel file; the
BASELINE configs additionally demand a 5×5 edge-detect.  Here filters are
first-class, named values: any odd ``k×k`` float32 tap array is a valid
filter, and the registry carries the standard image-processing set.

Semantics note: filters are applied as **cross-correlation** (no tap flip),
the convention of essentially all image-processing code.  Every bundled
filter is either symmetric (flip-invariant) or documented.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass(frozen=True, eq=False)
class Filter:
    """An odd-sized square stencil filter.

    Attributes:
      name: registry name (used by the CLI ``--filter`` flag).
      taps: ``(k, k)`` float32 array, already normalized (taps are applied
        as-is; no implicit divisor).
      dyadic: True when every tap is an exact binary fraction with a few
        significand bits, so float32 accumulation over uint8 inputs is exact
        and the oracle⇔TPU comparison is bit-exact by construction (see
        ops/oracle.py for the quantization spec).
    """

    name: str
    taps: np.ndarray
    dyadic: bool = False

    def __post_init__(self) -> None:
        t = np.asarray(self.taps, dtype=np.float32)
        if t.ndim != 2 or t.shape[0] != t.shape[1] or t.shape[0] % 2 == 0:
            raise ValueError(f"filter taps must be odd square, got {t.shape}")
        object.__setattr__(self, "taps", t)

    # Hashable/comparable by value so a Filter can be a static jit argument.
    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Filter)
            and self.name == other.name
            and self.taps.shape == other.taps.shape
            and bool(np.all(self.taps == other.taps))
        )

    def __hash__(self) -> int:
        return hash((self.name, self.taps.shape, self.taps.tobytes()))

    @property
    def size(self) -> int:
        return int(self.taps.shape[0])

    @property
    def radius(self) -> int:
        """Halo width this filter needs on each side (k // 2)."""
        return self.size // 2

    @property
    def convex(self) -> bool:
        """True when the filter provably cannot leave [0, 255] on u8 data.

        All taps non-negative and summing to ≤ 1 (convex combination): an
        accumulate over integer inputs in [0, 255] stays in [0, 255], so
        the quantize-mode ``clip`` after ``rint`` is the identity and the
        kernels may elide it (measured ~2 of ~11 VPU ops/px/level on the
        fused path).  The f32 sum of non-negative products is ≥ 0 and
        ≤ 255·(1+nε), and ``rint`` of anything < 255.5 is ≤ 255 — so the
        1e-6 slack on the tap sum cannot produce an out-of-range byte.
        """
        t = self.taps
        return bool(np.all(t >= 0.0) and float(t.sum()) <= 1.0 + 1e-6)

    def separable(self) -> tuple[np.ndarray, np.ndarray] | None:
        """(col_taps, row_taps) 1D factors with ``outer(col, row) == taps``
        EXACTLY in float32, or None.

        Blur/Gaussian kernels are rank-1: ``taps = c ⊗ r`` lets the stencil
        run as two 1D passes (2k MACs/px instead of k²).  Exactness of the
        factorization (checked bit-for-bit) is what keeps the separable
        path inside the bit-exact regime for dyadic filters.
        """
        t = self.taps
        i0, j0 = np.unravel_index(np.argmax(np.abs(t)), t.shape)
        piv = float(t[i0, j0])
        if piv == 0.0:
            return None
        cands = []
        if piv > 0:
            # Symmetric sqrt normalization first: for kernels like
            # gaussian5 it yields dyadic factors ([1,4,6,4,1]/16) where the
            # pivot normalization would give inexact 1/6-style taps.
            s = np.float32(np.sqrt(piv))
            cands.append(((t[:, j0] / s).astype(np.float32),
                          (t[i0, :] / s).astype(np.float32)))
        cands.append((t[:, j0].astype(np.float32),
                      (t[i0, :] / np.float32(piv)).astype(np.float32)))

        def dyadic_1d(a):
            scaled = a * 256.0
            return bool(np.all(scaled == np.rint(scaled)))

        exact = [
            (col, row) for col, row in cands
            if np.array_equal(np.outer(col, row).astype(np.float32), t)
        ]
        if not exact:
            return None
        exact.sort(key=lambda cr: not (dyadic_1d(cr[0]) and dyadic_1d(cr[1])))
        return exact[0]


def _f(name: str, taps, divisor: float | None = None, dyadic: bool = False) -> Filter:
    t = np.asarray(taps, dtype=np.float32)
    if divisor is not None:
        t = t / np.float32(divisor)
    return Filter(name=name, taps=t, dyadic=dyadic)


# The reference's own blur kernel: Gaussian-like 3×3 over /16 — all taps are
# exact binary fractions (1/16, 2/16=1/8, 4/16=1/4), hence dyadic.
BLUR3 = _f("blur3", [[1, 2, 1], [2, 4, 2], [1, 2, 1]], divisor=16, dyadic=True)

# Box blur, /8 would not preserve brightness; true box is /9 (non-dyadic).
BOX3 = _f("box3", np.ones((3, 3)), divisor=9)

# 5×5 Gaussian (the classic /256 pyramid kernel) — dyadic: every tap is
# n/256 with n a small integer, exactly representable and exactly
# accumulable in float32 against uint8 inputs.
GAUSSIAN5 = _f(
    "gaussian5",
    [
        [1, 4, 6, 4, 1],
        [4, 16, 24, 16, 4],
        [6, 24, 36, 24, 6],
        [4, 16, 24, 16, 4],
        [1, 4, 6, 4, 1],
    ],
    divisor=256,
    dyadic=True,
)

# Laplacian-style edge detectors (integer taps — dyadic trivially).
EDGE3 = _f("edge3", [[-1, -1, -1], [-1, 8, -1], [-1, -1, -1]], dyadic=True)
EDGE5 = _f(
    "edge5",
    [
        [-1, -1, -1, -1, -1],
        [-1, -1, -1, -1, -1],
        [-1, -1, 24, -1, -1],
        [-1, -1, -1, -1, -1],
        [-1, -1, -1, -1, -1],
    ],
    dyadic=True,
)

SHARPEN3 = _f("sharpen3", [[0, -1, 0], [-1, 5, -1], [0, -1, 0]], dyadic=True)
IDENTITY3 = _f("identity3", [[0, 0, 0], [0, 1, 0], [0, 0, 0]], dyadic=True)

# Jacobi 4-point average: the smoothing stencil of BASELINE config 5
# (iterated to convergence in float space).  1/4 taps — dyadic.
JACOBI3 = _f("jacobi3", [[0, 1, 0], [1, 0, 1], [0, 1, 0]], divisor=4, dyadic=True)

FILTERS: dict[str, Filter] = {
    f.name: f
    for f in [BLUR3, BOX3, GAUSSIAN5, EDGE3, EDGE5, SHARPEN3, IDENTITY3, JACOBI3]
}


def get_filter(name: str) -> Filter:
    try:
        return FILTERS[name]
    except KeyError:
        raise KeyError(
            f"unknown filter {name!r}; available: {sorted(FILTERS)}"
        ) from None


def make_filter(name: str, taps: np.ndarray, divisor: float | None = None) -> Filter:
    """Build a custom odd k×k filter (arbitrary sizes are supported end-to-end)."""
    return _f(name, taps, divisor=divisor)


def gaussian(size: int, sigma: float) -> Filter:
    """Sampled normalized Gaussian of odd ``size`` (non-dyadic in general).

    Byte-parity caveat: taps with no integer divisor (these, or any
    ``make_filter`` taps without one) lose the rint-margin theorem, so
    quantize-mode outputs can differ from the two-rounding NumPy/C++
    oracle at isolated pixels (the FMA rint-straddle — DESIGN.md
    "bit-exactness" precision classes; measured ±1 at sigma=0.7).
    Compiled backends remain bit-identical to each other; every
    registry filter carries an integer divisor and keeps full byte
    equality.
    """
    if size % 2 == 0:
        raise ValueError("size must be odd")
    r = size // 2
    y, x = np.mgrid[-r : r + 1, -r : r + 1].astype(np.float64)
    g = np.exp(-(x * x + y * y) / (2.0 * sigma * sigma))
    g /= g.sum()
    return Filter(name=f"gaussian{size}_s{sigma:g}", taps=g.astype(np.float32))
