"""Single-device JAX convolution paths (reference components C1/C2/C8).

Two implementations of one zero-padded cross-correlation step:

* :func:`correlate_shifted` — the **normative** fixed-order shifted
  multiply-add (same op sequence as the NumPy oracle, see ops/oracle.py), so
  float32 results are bit-identical to the oracle on every XLA backend.  This
  is also the decomposition the Pallas kernel uses, and what the sharded path
  applies per block.
* :func:`correlate_xla_conv` — ``lax.conv_general_dilated`` (XLA's native
  convolution, MXU-eligible); used for cross-checking and benchmarking
  against the Pallas kernel.

Internal layout is **planar float32** ``(C, H, W)``: TPU wants the large
spatial dims trailing (lane dim = W), not the 3-wide interleaved channel axis
of the raw file format.  ``utils/imageio`` converts at the boundary.
Grayscale is ``C == 1``.

The iteration drivers mirror the reference's hot loop (SURVEY.md §3.1):
``for t in loops: convolute; swap(src, dst)`` becomes a ``lax.fori_loop``
whose functional carry *is* the double buffer (C8) — with buffer donation at
the jit boundary XLA reuses the storage just like the pointer swap did.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from parallel_convolution_tpu.ops.filters import Filter


def pad_zero(x: jnp.ndarray, r: int) -> jnp.ndarray:
    """Zero ghost ring of width ``r`` around the spatial dims of (C, H, W)."""
    return jnp.pad(x, ((0, 0), (r, r), (r, r)))


def correlate_padded(padded: jnp.ndarray, filt: Filter) -> jnp.ndarray:
    """Normative correlation over an already-padded (C, H+2r, W+2r) block.

    Fixed row-major tap order; one multiply-add per tap in float32.  Exposed
    separately because the sharded path pads via halo exchange, not zeros.
    """
    k = filt.size
    C, Hp, Wp = padded.shape
    H, W = Hp - 2 * filt.radius, Wp - 2 * filt.radius
    taps = [float(t) for t in filt.taps.reshape(-1)]
    # Accumulate in f32 regardless of storage dtype (bf16 carries hold exact
    # small integers, but products/sums must not round at bf16).
    padded = padded.astype(jnp.float32)
    acc = jnp.zeros((C, H, W), jnp.float32)
    i = 0
    for dy in range(k):
        for dx in range(k):
            acc = acc + jnp.float32(taps[i]) * padded[:, dy : dy + H, dx : dx + W]
            i += 1
    return acc


def correlate_shifted(x: jnp.ndarray, filt: Filter) -> jnp.ndarray:
    """One zero-padded correlation step on (C, H, W) float32."""
    return correlate_padded(pad_zero(x, filt.radius), filt)


def correlate_padded_separable(padded: jnp.ndarray, filt: Filter) -> jnp.ndarray:
    """Rank-1 fast path: two 1D passes (2k MACs/px instead of k²).

    Used when :meth:`Filter.separable` finds an exact float32 factorization
    (blur3, gaussian5, box blurs…); falls back to the 2D path otherwise.
    With dyadic 1D factors and u8-range inputs every intermediate is exact
    in f32, so the result is bit-identical to the 2D normative path.
    """
    sep = filt.separable()
    if sep is None:
        return correlate_padded(padded, filt)
    col, row = sep
    k, r = filt.size, filt.radius
    C, Hp, Wp = padded.shape
    H, W = Hp - 2 * r, Wp - 2 * r
    x = padded.astype(jnp.float32)
    acc1 = jnp.zeros((C, Hp, W), jnp.float32)
    for dx in range(k):
        acc1 = acc1 + jnp.float32(float(row[dx])) * x[:, :, dx : dx + W]
    out = jnp.zeros((C, H, W), jnp.float32)
    for dy in range(k):
        out = out + jnp.float32(float(col[dy])) * acc1[:, dy : dy + H, :]
    return out


def correlate_xla_conv(x: jnp.ndarray, filt: Filter) -> jnp.ndarray:
    """Same step via XLA's native conv (cross-check / benchmark path).

    Channels are independent (the reference's per-channel RGB loop), so C is
    the conv batch dim with a single feature channel.
    """
    r = filt.radius
    x = x.astype(jnp.float32)
    lhs = x[:, None, :, :]  # (C, 1, H, W)
    rhs = jnp.asarray(filt.taps, jnp.float32)[None, None, :, :]  # (1, 1, k, k)
    out = jax.lax.conv_general_dilated(
        lhs, rhs, window_strides=(1, 1), padding=[(r, r), (r, r)],
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        precision=jax.lax.Precision.HIGHEST,
    )
    return out[:, 0, :, :]


def quantize_f32(acc: jnp.ndarray) -> jnp.ndarray:
    """uint8 store-back semantics, kept in f32: clip(rint(acc), 0, 255).

    The values are exact small integers in float32, so carrying f32 across
    iterations is bit-identical to the reference's ``unsigned char`` buffers
    while avoiding per-iteration dtype churn on the VPU.
    """
    return jnp.clip(jnp.rint(acc), 0.0, 255.0)


def _step_u8(x: jnp.ndarray, filt: Filter, correlate) -> jnp.ndarray:
    return quantize_f32(correlate(x, filt))


@partial(jax.jit, static_argnames=("filt", "iters", "use_xla_conv"),
         donate_argnums=0)
def iterate_u8(x: jnp.ndarray, filt: Filter, iters: int,
               use_xla_conv: bool = False) -> jnp.ndarray:
    """``iters`` u8-semantics iterations on planar f32 (C, H, W).

    The fori_loop carry is the double buffer (C8); ``donate_argnums=0`` lets
    XLA alias input and output storage (the reference's pointer swap).
    """
    correlate = correlate_xla_conv if use_xla_conv else correlate_shifted
    body = lambda _, v: _step_u8(v, filt, correlate)
    return jax.lax.fori_loop(0, iters, body, x)


@partial(jax.jit, static_argnames=("filt", "iters", "use_xla_conv"),
         donate_argnums=0)
def iterate_f32(x: jnp.ndarray, filt: Filter, iters: int,
                use_xla_conv: bool = False) -> jnp.ndarray:
    """``iters`` float-mode iterations (Jacobi smoothing — no quantization)."""
    correlate = correlate_xla_conv if use_xla_conv else correlate_shifted
    body = lambda _, v: correlate(v, filt)
    return jax.lax.fori_loop(0, iters, body, x)


def run_u8(img_u8_planar, filt: Filter, iters: int):
    """Convenience: uint8 planar in → uint8 planar out, single device."""
    x = jnp.asarray(img_u8_planar, jnp.float32)
    out = iterate_u8(x, filt, iters)
    return jnp.asarray(out, jnp.uint8)
