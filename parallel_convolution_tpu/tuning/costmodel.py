"""The DESIGN.md roofline as code (autotuning analytical prior).

DESIGN.md "Roofline: an iterated stencil is bandwidth, not FLOPs" states
the performance model behind every knob this framework exposes — carry
bytes per pixel per iteration as a function of storage dtype and fusion
depth, a shrinking-rim recompute tax for fused kernels, and a per-round
collective cost for the halo exchange.  Until now that model lived only
in prose (and in a human running ``scripts/tune_pallas.py`` on silicon
and pasting the winner into ``ops/pallas_stencil.DEFAULT_TILE``).  This
module is the same model as *ranking functions*: the autotuner
(``tuning.search``) uses it to order the candidate space and to prune
measurement down to a handful of compiles, and ``backend="auto"`` uses
it as the zero-measurement fallback when no plan file exists.

Everything here is pure arithmetic on python ints/floats — no jax, no
device access — so the model runs identically on a dev laptop, in CI,
and on the chip host, and is trivially testable (monotonicity pins in
``tests/test_tuning.py``).

Accuracy contract: the model RANKS, it does not promise walls.  The
constants come from measured v5e rows (BASELINE.md / DESIGN.md round-4
cross-validated readings) but a factor-of-two absolute error is fine as
long as ordering survives; every number derived from the model is
stamped ``plan_source="predicted"`` so it can never masquerade as a
measurement (the round-4/5 evidence rule applied to predictions).
"""

from __future__ import annotations

import dataclasses

# Mirrors parallel.step.STORAGE_DTYPES widths without importing jax.
STORAGE_BYTES = {"f32": 4, "bf16": 2, "u8": 1}

# Mirrors ops.pallas_stencil._sublane: second-minor HBM/VMEM tile extent.
SUBLANE = {"f32": 8, "bf16": 16, "u8": 32}
LANE = 128

# Mirrors ops.pallas_stencil defaults (kept in sync by a tier-1 test).
DEFAULT_TILE = (256, 512)
SEP_TILE = (1024, 512)

# Mirrors ops.pallas_rdma._TILED_VMEM_BYTES: monolithic-kernel budget
# before the RDMA tier auto-switches to the HBM-pad windowed variant.
RDMA_TILED_VMEM_BYTES = 10 * 2**20

# Mosaic's scoped-VMEM stack limit (the 2D tap loop keeps ~k^2 live
# (th, tw) f32 temporaries; 1024x512 f32 failed compile at 25.3 MB vs
# this bound — DESIGN.md round-1 lesson 2).
SCOPED_VMEM_BYTES = 16 * 2**20

PALLAS_BACKENDS = ("pallas", "pallas_sep", "pallas_rdma")

# Kernel forms with PERSISTENT halo channels (parallel.channels): their
# exchange identity is bound once and reused across every fused
# iteration / converge chunk / V-cycle level, so the per-phase
# descriptor-setup term below is zeroed for them.  Mirrors the
# kernel-form registry's ``persistent_capable`` bit (drift-guarded in
# tests/test_channels.py) — hardcoded here because this module is
# jax-free and must not import the (provider-importing) registry.
PERSISTENT_BACKENDS = ("pallas_rdma",)

# Per-phase descriptor/channel setup charged to NON-persistent exchange
# forms: the cost of re-deriving buffers/counts/partners every round
# that persistent channels pay once at bind time (the persistent-MPI
# paper's motivating delta).  Order-of-magnitude from the same
# scaling-model family as exchange_lat_s; pinned by a drift-guard test.
EXCHANGE_SETUP_S = 1.5e-6

# Per-row descriptor issue cost of a DIRECT STRIDED column-slab copy:
# a strided RDMA walks one descriptor per contiguous run (one per padded
# row), so its overhead scales with slab height while the packed
# transport's extra cost scales with slab bytes — the derived-datatypes
# trade (PAPERS.md) the ``col_mode`` A/B prices.  Pinned by the same
# drift-guard test.
STRIDED_ROW_DESC_S = 15e-9

# The column transports the RDMA kernels implement (mirrors
# parallel.channels.COL_MODES without importing it — jax-free either
# way, but this module must stay import-cycle-free under tuning/).
COL_MODES = ("packed", "strided")

# Pallas kernels off-TPU run under the interpreter — hundreds to
# thousands of times slower than compiled XLA.  The exact factor is
# irrelevant; it only needs to dominate every legitimate difference so
# ``backend="auto"`` on a CPU mesh deterministically picks a compiled
# XLA tier.
INTERPRET_PENALTY = 1e4


@dataclasses.dataclass(frozen=True)
class HardwareModel:
    """Roofline constants for one chip kind.

    ``hbm_gbps``/``flop_gops`` are the streaming-bandwidth and
    FMA-slot-throughput ceilings the roofline maxes over;
    ``exchange_lat_s`` is the per-phase collective launch latency and
    ``ici_gbps`` the neighbor-link bandwidth (both from the
    scaling-model defaults, DESIGN.md "Scale path").
    ``interpret_pallas`` marks platforms where Pallas kernels execute
    under the interpreter rather than Mosaic.
    """

    name: str
    hbm_gbps: float
    flop_gops: float
    exchange_lat_s: float
    ici_gbps: float
    interpret_pallas: bool = False


# v5e-class constants.  flop_gops is the measured VPU rate (1 469.8
# Gflop/s f32, DESIGN.md "Cross-validated instrument readings").
# hbm_gbps is the ACHIEVED streaming rate of the XLA-orchestrated
# iteration loop, not the ~800 GB/s spec sheet: the measured pallas
# bf16 fuse=1 row (11.6 Gpx/s at ~8 charged bytes/px, BASELINE round 1)
# implies ~93 GB/s through the pad/exchange/kernel round trips, and it
# is that effective rate that makes the model reproduce the measured
# ~4x fusion lever (spec-sheet bandwidth never binds and would rank
# fuse=1 first, contradicting silicon).  ICI: 45 GB/s + 5 us/phase
# (the scaling-model assumption, labeled as such there).
TPU_V5E = HardwareModel("tpu-v5e", hbm_gbps=100.0, flop_gops=1470.0,
                        exchange_lat_s=5e-6, ici_gbps=45.0)

# Generic-host constants.  Absolute values are rough; on CPU the model
# only has to (a) crush interpreted Pallas via the penalty and (b) rank
# compiled XLA tiers against each other, where op count dominates.
CPU_HOST = HardwareModel("cpu", hbm_gbps=20.0, flop_gops=50.0,
                         exchange_lat_s=20e-6, ici_gbps=20.0,
                         interpret_pallas=True)


def hardware_for(platform: str, device_kind: str = "") -> HardwareModel:
    """The :class:`HardwareModel` for a jax platform/device_kind pair.

    Unknown TPU generations get the v5e constants (right order of
    magnitude, and ranking is what matters); anything that is not a TPU
    gets the generic host model with the interpret penalty armed.
    """
    if platform == "tpu":
        return dataclasses.replace(
            TPU_V5E, name=device_kind.strip() or "tpu")
    return dataclasses.replace(CPU_HOST, name=platform or "cpu")


def effective_tile(backend: str, tile: tuple[int, int] | None,
                   ) -> tuple[int, int] | None:
    """The kernel output tile a launch will actually use.

    ``None`` for backends with no tile concept; the per-kernel module
    default when the caller passed None — the value ``utils.bench``
    stamps so evidence rows can never disagree with the executable.
    """
    if backend not in PALLAS_BACKENDS:
        return None
    if tile is not None:
        return (int(tile[0]), int(tile[1]))
    return SEP_TILE if backend == "pallas_sep" else DEFAULT_TILE


def rdma_is_tiled(shape: tuple[int, int, int], block_hw: tuple[int, int],
                  radius: int, fuse: int, storage: str,
                  col_mode: str = "strided",
                  grid: tuple[int, int] | None = None) -> bool:
    """Whether ``pallas_rdma`` auto-selects its tiled (HBM-pad) kernel.

    Mirrors ``ops.pallas_rdma.fused_rdma_step``'s ``tiled=None``
    auto-select: monolithic f32 padded buffer + storage-dtype output —
    plus, for the packed column transport on a grid with a remote
    column axis, the 4 f32 VMEM staging slots — over
    ``RDMA_TILED_VMEM_BYTES`` switches to the windowed variant.
    Callers that do not know the resolved ``col_mode``/``grid`` get the
    staging-free (strided-equivalent) legacy accounting.
    """
    C = shape[0]
    h, w = block_hw
    d = radius * max(1, fuse)
    mono = (C * (h + 2 * d) * (w + 2 * d) * 4
            + C * h * w * STORAGE_BYTES[storage])
    if col_mode == "packed" and grid is not None and grid[1] > 1:
        mono += 4 * C * (h + 2 * d) * d * 4
    return mono > RDMA_TILED_VMEM_BYTES


def overlap_legal(backend: str, grid: tuple[int, int],
                  block_hw: tuple[int, int], radius: int,
                  fuse: int) -> bool:
    """Whether the interior-first overlapped halo pipeline applies.

    Overlap is an RDMA-kernel restructure (the exchange and the compute
    live in one program there — no other tier can interleave them), it
    needs a collective to hide (a 1x1 grid has none), and it needs a
    non-empty interior to compute under the in-flight DMAs: the rim of
    one fused chunk is ``d = radius*fuse`` deep on every side, so
    ``min(block) > 2*d`` or the whole block IS rim and the pipeline
    degenerates to the serialized order.  Mirrors the kernel's own
    region decomposition (``ops.pallas_rdma``); drift-guarded in
    ``tests/test_overlap.py``.
    """
    if backend != "pallas_rdma":
        return False
    if grid[0] * grid[1] == 1:
        return False
    d = radius * max(1, int(fuse))
    return min(block_hw) > 2 * d


def rim_overhead(fuse: int, tile_hw: tuple[int, int], radius: int) -> float:
    """Extra-compute fraction from recomputing the shrinking overlap rim.

    A fused kernel computes level ``s`` (1-based) of a (th, tw) output
    tile on the extended extent (th + 2r(T-s))(tw + 2r(T-s)); the sum
    over levels, normalized by T*th*tw, minus 1, is the recompute tax
    (DESIGN.md knob 3: ~6% at th=256, tw=512, r=1, T=8).
    """
    T = max(1, int(fuse))
    th, tw = tile_hw
    total = sum((th + 2 * radius * (T - s)) * (tw + 2 * radius * (T - s))
                for s in range(1, T + 1))
    return total / (T * th * tw) - 1.0


def hbm_bytes_per_px_iter(backend: str, storage: str, fuse: int,
                          tile: tuple[int, int] | None,
                          block_hw: tuple[int, int], radius: int,
                          shape: tuple[int, int, int] = (1, 0, 0)) -> float:
    """Predicted HBM bytes moved per pixel per iteration.

    The DESIGN.md table as a function: carry width B from the storage
    dtype.  The ppermute+Pallas tiers pay, once per T levels, the
    halo-pad materialization (XLA writes the padded block, one
    read+write pair = 2B), the kernel's windowed input read (grown by
    the 2r*T ghost rim), and one output write — so bytes fall as ~4B/T
    plus the rim term, the fused-kernel win of DESIGN.md knob 3.  The
    XLA tiers re-materialize and re-stream every level (charged 4B per
    iteration, fuse-invariant: fusion only saves them collective
    rounds).  The RDMA tier skips the pad materialization entirely
    (ghosts land by remote DMA); its monolithic form holds everything
    in VMEM and streams the block exactly once per T.
    """
    B = STORAGE_BYTES[storage]
    T = max(1, int(fuse))
    if backend not in PALLAS_BACKENDS:
        return 4.0 * B
    if backend == "pallas_rdma" and not rdma_is_tiled(
            shape, block_hw, radius, T, storage):
        return 2.0 * B / T
    th, tw = effective_tile(backend, tile)
    # Windows are clamped to the block: a tile bigger than the block
    # degenerates to one whole-block window.
    th = min(th, max(1, block_hw[0]))
    tw = min(tw, max(1, block_hw[1]))
    d = radius * T
    window = (th + 2 * d) * (tw + 2 * d)
    pad_rt = 0.0 if backend == "pallas_rdma" else 2.0
    return B * (pad_rt + window / (th * tw) + 1.0) / T


def flops_per_px_iter(k: int, separable: bool, quantize: bool,
                      fuse: int, rim_tile: tuple[int, int],
                      radius: int) -> float:
    """Predicted f32 FMA-slot work per pixel per iteration.

    Separable kernels do 2k MACs/px, the 2D tap loop k^2 (DESIGN.md
    knob set); quantize mode adds the two round-adds (the round-5 magic
    rounding — the measured 8-slot floor for the separable form).  The
    whole count is inflated by the fused rim-recompute tax evaluated on
    ``rim_tile`` (the kernel tile for Pallas, the device block for the
    ppermute-fused XLA path).
    """
    macs = 2 * k if separable else k * k
    slots = 2.0 * macs + (2.0 if quantize else 0.0)
    return slots * (1.0 + rim_overhead(fuse, rim_tile, radius))


def col_transport_seconds_per_round(block_hw: tuple[int, int], radius: int,
                                    fuse: int, storage: str,
                                    hw: HardwareModel,
                                    col_mode: str = "packed") -> float:
    """Extra per-round cost of moving the two STRIDED column slabs.

    The slabs are cut at row-padded height (``bh + 2d`` — the corner
    bytes ride the column phase).  ``strided`` pays one descriptor per
    contiguous run (per padded row, both directions);  ``packed`` pays
    the pack + unpack staging copies — the slab streamed through memory
    twice more, read + write each, both directions.  The crossover (thin
    slabs → packed, deep slabs → strided) is the derived-datatypes
    decision ``pick_col_mode`` automates.
    """
    if col_mode not in COL_MODES:
        raise ValueError(f"col_mode must be one of {COL_MODES}, "
                         f"got {col_mode!r}")
    d = radius * max(1, int(fuse))
    rows = block_hw[0] + 2 * d
    if col_mode == "strided":
        return 2.0 * rows * STRIDED_ROW_DESC_S
    slab_bytes = rows * d * STORAGE_BYTES[storage]
    return 2.0 * 4.0 * slab_bytes / (hw.hbm_gbps * 1e9)


def pick_col_mode(grid: tuple[int, int], block_hw: tuple[int, int],
                  radius: int, fuse: int, storage: str,
                  hw: HardwareModel) -> str:
    """The cheaper column transport for this decomposition ("auto"'s
    verdict).  No remote column partner (a 1-extent column axis) means
    no column transport at all: the canonical label is then "packed"
    (both modes compile the identical statically-elided program)."""
    if grid[1] <= 1:
        return "packed"
    packed = col_transport_seconds_per_round(block_hw, radius, fuse,
                                             storage, hw, "packed")
    strided = col_transport_seconds_per_round(block_hw, radius, fuse,
                                              storage, hw, "strided")
    return "packed" if packed <= strided else "strided"


def exchange_seconds_per_px_iter(grid: tuple[int, int],
                                 block_hw: tuple[int, int], radius: int,
                                 fuse: int, storage: str,
                                 hw: HardwareModel,
                                 persistent: bool = False,
                                 col_mode: str = "packed") -> float:
    """Per-pixel-iteration cost of the halo exchange, amortized over T.

    Two terms per round, split since round 16 (persistent channels):

    * SETUP — per-phase descriptor/schedule derivation, charged only to
      non-persistent forms (``persistent=True`` zeroes it: channels are
      bound once per exchange identity and reused);
    * TRANSFER — two phases of launch latency, the four ghost slabs
      (depth r*T) over the neighbor links, plus the column-transport
      overhead of ``col_mode`` (strided descriptors vs staging copies).

    A 1x1 grid has no collective and costs zero (the statically-elided
    exchange, both terms).
    """
    if grid[0] * grid[1] == 1:
        return 0.0
    T = max(1, int(fuse))
    B = STORAGE_BYTES[storage]
    bh, bw = block_hw
    d = radius * T
    slab_bytes = 2.0 * (bh + bw) * d * B
    setup = 0.0 if persistent else 2.0 * EXCHANGE_SETUP_S
    col = (col_transport_seconds_per_round(block_hw, radius, T, storage,
                                           hw, col_mode)
           if grid[1] > 1 else 0.0)
    per_round = (2.0 * hw.exchange_lat_s + setup + col
                 + slab_bytes / (hw.ici_gbps * 1e9))
    return per_round / (T * bh * bw)


def predict_seconds_per_px_iter(backend: str, storage: str, fuse: int,
                                tile: tuple[int, int] | None,
                                shape: tuple[int, int, int],
                                block_hw: tuple[int, int],
                                grid: tuple[int, int], k: int,
                                separable: bool, quantize: bool,
                                hw: HardwareModel,
                                overlap: bool = False,
                                col_mode: str = "packed") -> float:
    """Roofline time: max(bandwidth, compute) + exchange, per px-iter.

    ``overlap=True`` (legal only per :func:`overlap_legal`) models the
    interior-first pipeline: the exchange rides UNDER the interior
    compute, so the serial ``compute + exchange`` sum becomes
    ``max(compute, exchange)`` — exchange is free until it exceeds the
    compute it hides behind, the persistent/partitioned-MPI overlap
    claim (PAPERS.md) as a roofline term.  An illegal overlap request
    silently prices the serialized form (same clamp the dispatch layer
    applies), so the model and the executable can never disagree.

    ``col_mode`` prices the column transport for tiers that HAVE the
    A/B (``PERSISTENT_BACKENDS``); every other tier is charged the
    packed-equivalent term (XLA's pad materialization IS a staging
    copy), so the knob can never skew a cross-tier ranking.  The
    persistent tiers also zero the per-phase setup term — the honest
    ranking delta of bound-once channels.
    """
    radius = k // 2
    T = max(1, int(fuse))
    tile_eff = effective_tile(backend, tile)
    rim_tile = tile_eff if tile_eff is not None else block_hw
    if backend == "pallas_rdma" and not rdma_is_tiled(
            shape, block_hw, radius, T, storage,
            col_mode=col_mode, grid=grid):
        rim_tile = block_hw  # monolithic: levels run on the whole block
    sep = separable and backend in ("separable", "pallas_sep")
    t_hbm = hbm_bytes_per_px_iter(
        backend, storage, T, tile, block_hw, radius, shape
    ) / (hw.hbm_gbps * 1e9)
    t_flop = flops_per_px_iter(
        k, sep, quantize, T, rim_tile, radius) / (hw.flop_gops * 1e9)
    t_roof = max(t_hbm, t_flop)
    persistent = backend in PERSISTENT_BACKENDS
    t_ex = exchange_seconds_per_px_iter(
        grid, block_hw, radius, T, storage, hw, persistent=persistent,
        col_mode=col_mode if persistent else "packed")
    if overlap and overlap_legal(backend, grid, block_hw, radius, T):
        t = max(t_roof, t_ex)
    else:
        t = t_roof + t_ex
    if backend in PALLAS_BACKENDS and hw.interpret_pallas:
        t *= INTERPRET_PENALTY
    return t


def predict_gpx_per_chip(seconds_per_px_iter: float) -> float:
    """Gpixels/sec/chip implied by a per-px-iter time (the bench unit)."""
    return 1.0 / (seconds_per_px_iter * 1e9)


# -- rank-3 volumes (round 23) ---------------------------------------------
# Per-axis star taps of one registered rank-3 form application: the FD
# smoothers touch 6r neighbors + rhs + diagonal scale; the physics forms
# are 7-point-Laplacian updates with a handful of pointwise reaction
# terms.  A jax-free mirror of volumes.forms (drift-guarded in
# tests/test_volumes.py).
VOLUME_FORM_TAPS = {
    "fd7": 8, "fd7_stack": 8, "fd25": 26, "fd25_stack": 26,
    "wave": 10, "grayscott": 24,
}


def volume_bytes_per_cell_iter(storage: str = "f32",
                               fields: int = 2) -> float:
    """Predicted HBM bytes per CELL (one field-pair grid point) per
    iteration of a rank-3 form.

    The volume path is the XLA shifted-add tier generalized by one axis:
    the 6-face ghost pad is materialized (read + write), the padded
    block is streamed once and the output written once — the same 4B
    accounting as the rank-2 XLA tiers, times the live fields.
    Fuse-invariant for the same reason rank 2 is: fusion saves
    collective rounds, not HBM traffic."""
    return 4.0 * STORAGE_BYTES[storage] * max(1, int(fields))


def predict_volume_seconds_per_cell_iter(
        grid: tuple[int, int], block_hw: tuple[int, int], depth: int,
        radius: int, fuse: int, name: str, hw: HardwareModel,
        fields: int = 2, storage: str = "f32") -> float:
    """Roofline time per cell-iteration of one rank-3 form.

    ``max(bandwidth, compute) + exchange``: bytes from
    :func:`volume_bytes_per_cell_iter`, FMA slots from
    :data:`VOLUME_FORM_TAPS`, and the exchange term priced through the
    rank-2 slab arithmetic at an effective channel count of
    ``fields * (depth + 2d)`` — the ±H/±W face slabs carry the whole
    depth-padded column (the ±D faces are a local pad, zero bytes), so
    a volume's face bytes ARE the rank-2 formula at that channel width.
    """
    T = max(1, int(fuse))
    d = radius * T
    depth = max(1, int(depth))
    bh, bw = block_hw
    cells = max(1, depth * bh * bw)
    t_hbm = volume_bytes_per_cell_iter(storage, fields) / (hw.hbm_gbps * 1e9)
    taps = VOLUME_FORM_TAPS.get(name, 8)
    t_flop = 2.0 * taps * max(1, int(fields)) / (hw.flop_gops * 1e9)
    t_roof = max(t_hbm, t_flop)
    if grid[0] * grid[1] == 1:
        return t_roof
    B = STORAGE_BYTES[storage]
    ch = max(1, int(fields)) * (depth + 2 * d)
    slab_bytes = ch * d * (2.0 * bw + 2.0 * (bh + 2 * d)) * B
    per_round = (2.0 * hw.exchange_lat_s + 2.0 * EXCHANGE_SETUP_S
                 + slab_bytes / (hw.ici_gbps * 1e9))
    return t_roof + per_round / (T * cells)


def predict_vcycle_seconds(
        terms: list[tuple[float, int, int]]) -> float:
    """Price of one multigrid V-cycle: the SUM of its per-level sweeps.

    ``terms`` is one ``(seconds_per_px_iter, pixels, sweeps)`` triple per
    grid level (from :func:`predict_seconds_per_px_iter` on that level's
    own block/grid geometry).  Coarse levels are cheaper — fewer pixels,
    and often a smaller mesh — but never free: the sum keeps
    ``backend="auto"`` comparisons between a V-cycle and a single-level
    solver honest, rather than letting coarse sweeps vanish from the
    bill.
    """
    return sum(spp * px * n for spp, px, n in terms)


# Sweep counts of the V-cycle schedule — a jax-free mirror of
# solvers.multigrid's NU_PRE/NU_POST/NU_COARSE plus its documented
# work-unit charge (residual = one sweep equivalent, restriction +
# prolongation together one more): the admission pricer must cost a
# converge job without importing the solver (or a mesh).  Drift-guarded
# against the solver's constants in tests/test_autoscale.py.
MG_PRE_SWEEPS = 2
MG_POST_SWEEPS = 2
MG_COARSE_SWEEPS = 16
MG_TRANSFER_SWEEP_EQUIV = 2
# Mirrors multigrid.MG_MIN_EXTENT / MG_MAX_LEVELS.
MG_MIN_EXTENT = 8
MG_MAX_LEVELS = 12


def mg_default_levels(extent_hw: tuple[int, int],
                      mg_levels: int | None = None,
                      floor: int = MG_MIN_EXTENT) -> int:
    """Level count a V-cycle schedule would plan for this GLOBAL fine
    extent: halve per level until a side would drop under ``floor``
    (capped by ``mg_levels`` and :data:`MG_MAX_LEVELS`).  A PRICING
    mirror of ``multigrid.plan_levels`` — ranking-grade, not byte-grade:
    the real planner also vetoes torus misalignment, enforces the block
    floor, and reshards coarse levels, all of which only LOWER cost."""
    h, w = max(1, int(extent_hw[0])), max(1, int(extent_hw[1]))
    levels = 1
    while (min(h, w) >> levels) >= floor and levels < MG_MAX_LEVELS:
        levels += 1
    if mg_levels is not None:
        levels = max(1, min(levels, int(mg_levels)))
    return levels


def predict_mg_cycle_seconds(shape: tuple[int, int, int],
                             grid: tuple[int, int], k: int,
                             storage: str, quantize: bool,
                             hw: HardwareModel, *,
                             levels: int | None = None,
                             backend: str = "shifted",
                             ) -> tuple[float, float]:
    """``(cycle_seconds, fine_work_units_per_cycle)`` for one V-cycle.

    Per-level terms from :func:`predict_seconds_per_px_iter` on that
    level's own (halved) geometry, summed by
    :func:`predict_vcycle_seconds`; the second element is the
    pixel-weighted fine-grid work units one cycle spends — the SAME
    unit ``mg_converge_stream`` bounds with ``max_iters``, so a caller
    holding a work budget can price the whole job as
    ``(max_iters / wu_per_cycle) * cycle_seconds``.  The mesh is held
    fixed across levels (the real schedule reshards coarse levels onto
    sub-meshes, which only cheapens them — ranking-safe).
    """
    C, H, W = (max(1, int(v)) for v in shape)
    R, Q = (max(1, int(v)) for v in grid)
    if levels is None:
        levels = mg_default_levels((H, W))
    levels = max(1, int(levels))
    terms: list[tuple[float, int, int]] = []
    wu = 0.0
    fine_px = C * H * W
    for lvl in range(levels):
        h = max(1, H >> lvl)
        w = max(1, W >> lvl)
        block = (max(1, -(-h // R)), max(1, -(-w // Q)))
        px = C * h * w
        if levels == 1:
            sweeps = MG_PRE_SWEEPS + MG_POST_SWEEPS
        elif lvl < levels - 1:
            sweeps = (MG_PRE_SWEEPS + MG_POST_SWEEPS
                      + MG_TRANSFER_SWEEP_EQUIV)
        else:
            sweeps = MG_COARSE_SWEEPS
        spp = predict_seconds_per_px_iter(
            backend, storage, 1, None, (C, h, w), block, (R, Q), k,
            False, quantize, hw)
        terms.append((spp, px, sweeps))
        wu += sweeps * px / fine_px
    return predict_vcycle_seconds(terms), wu
