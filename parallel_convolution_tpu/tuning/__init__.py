"""Autotuning subsystem: cost model, measured search, persistent plans.

The three parts (see each module's docstring):

* :mod:`tuning.costmodel` — the DESIGN.md roofline as ranking functions
  (pure math, no jax);
* :mod:`tuning.search` — legal candidate enumeration + model-pruned
  measured refinement over ``utils.bench``;
* :mod:`tuning.plans` — the schema-versioned persistent plan cache with
  the exact -> nearest-bucket -> cost-model fallback ladder.

This package root owns :func:`resolve` — the ``backend="auto"`` entry
point the rest of the framework calls (``parallel/step.py``,
``ConvolutionModel``, ``utils.bench``, the serving engine, the CLI).
Resolution order, by construction *before* any resilience machinery:

  1. plan cache (exact key, else nearest same-chip size bucket),
  2. cost model over the legal candidate space,

and the winner's provenance — ``measured | interpolated | predicted`` —
travels with it (``Resolution.source``) so every bench/serving row can
stamp ``plan_source`` and a silent mistune is visible in artifacts.
The resilience degrade walk (``resilience/degrade.py``) then applies to
the *resolved* backend exactly as it would to an explicitly-named one:
auto picks the tier, degradation still guards the launch.
"""

from __future__ import annotations

import dataclasses

from parallel_convolution_tpu.tuning import costmodel, search
from parallel_convolution_tpu.tuning.plans import (
    PLAN_FILE_ENV, PLAN_SCHEMA, Plan, PlanCache, Workload, canonical_key,
    default_cache, default_plan_path,
)

AUTO = "auto"

__all__ = [
    "AUTO", "PLAN_FILE_ENV", "PLAN_SCHEMA", "Plan", "PlanCache",
    "Resolution", "Workload", "canonical_key", "costmodel",
    "default_cache", "default_plan_path", "last_resolution", "resolve",
    "search",
]


@dataclasses.dataclass(frozen=True)
class Resolution:
    """What ``backend="auto"`` resolved to, with provenance.

    ``source`` is the ``plan_source`` rows stamp: ``measured`` (exact
    plan-cache hit), ``interpolated`` (nearest same-chip size bucket),
    or ``predicted`` (cost model only) — plus the stored provenance of
    a hit plan, which may itself be ``predicted`` when the plan file
    was emitted by a dry-run tune.
    """

    backend: str
    fuse: int
    tile: tuple[int, int] | None
    source: str
    predicted_gpx: float | None
    key: str
    # Interior-first overlapped halo pipeline: the tuned (or clamped-
    # request) decision for the resolved backend; always a concrete bool.
    overlap: bool = False
    # Column-slab transport (packed | strided): the tuned (or clamped-
    # request) decision; always concrete — "packed" is the canonical
    # inert label for tiers with no column RDMA transport.
    col_mode: str = "packed"


# The most recent resolution per process, for entry points that label
# their output after the fact (mirrors degrade._LAST_RESOLVED).
_LAST: list[Resolution] = []


def last_resolution() -> Resolution | None:
    return _LAST[-1] if _LAST else None


def _legal_plan_knobs(w: Workload, plan: Plan) -> tuple[int, object]:
    """Clamp a (possibly other-bucket) plan's knobs to THIS workload's
    legality: fuse to the block/RDMA bounds, tile to alignment+VMEM —
    an interpolated plan from a larger bucket must never hand an
    impossible launch to the kernels.  (The plan's overlap verdict is
    clamped by :func:`resolve` itself, at the FINAL fuse — a pinned
    fuse can change the legal interior, so a clamp here would be stale.)
    """
    fuse = plan.fuse
    legal_f = search._legal_fuses(w, plan.backend, (fuse,))
    if fuse not in legal_f:
        allf = search._legal_fuses(w, plan.backend, search.FUSE_MENU)
        fuse = max((f for f in allf if f <= fuse), default=min(allf))
    tile = plan.tile
    if tile is not None and tile not in search._legal_tiles(
            w, plan.backend, (tile,), fuse=fuse):
        tile = None
    return fuse, tile


def resolve(mesh, filt, shape, *, storage: str = "f32",
            quantize: bool = True, boundary: str = "zero",
            fuse: int | None = None, tile: tuple[int, int] | None = None,
            overlap: bool | None = None,
            col_mode: str | None = None,
            plans: PlanCache | None = None,
            check_every: int | None = None) -> Resolution:
    """Resolve ``backend="auto"`` (and unset fuse/tile) for one workload.

    ``fuse``/``tile`` passed non-None are pins: the plan/model fills
    only the unset knobs, and a pinned value is honored verbatim (a pin
    that is illegal for EVERY backend dies loudly in the candidate
    enumeration — never silently remeasured as fuse=1/default tile).
    ``overlap`` is a clamped *request*, not a pin (see
    ``search._legal_overlaps``): None lets the cost model decide, an
    explicit value is honored exactly where legal for the resolved
    backend and clamped to False otherwise — the resolved bool lands in
    ``Resolution.overlap`` and every row stamps it.
    ``plans=None`` consults
    the ambient cache (``PCTPU_PLAN_FILE``); pass an explicit
    :class:`PlanCache` (e.g. the serving engine's) to override.

    ``check_every`` marks a convergence-path workload: it joins the plan
    key (a convergence tune never drives the fixed-count program, and
    vice versa) and bounds the legal fusion depth to ``check_every - 1``
    (the chunk's final iteration is always unfused — it forms the
    (prev, cur) convergence pair).

    Deterministic by construction: the candidate space, the model, and
    every tie-break are pure functions of the workload — two processes
    on the same platform resolve identically (pinned in tier-1).
    """
    if check_every is not None and fuse is not None:
        # Mirror step._build_converge's clamp (a chunk fuses at most its
        # n-1 pre-pair iterations) so a pinned fuse resolves to the depth
        # the runner will actually execute, same surface as the no-plan
        # path.
        fuse = max(1, min(int(fuse), max(1, int(check_every) - 1)))
    w = Workload.from_mesh(mesh, filt, shape, storage=storage,
                           quantize=quantize, boundary=boundary,
                           check_every=check_every)
    cache = plans if plans is not None else default_cache()
    plan = cache.best_plan(w) if len(cache) else None
    if plan is not None and fuse is not None and not search._legal_fuses(
            w, plan.backend, (int(fuse),), strict=True):
        # Same error surface as the no-plan path (candidate enumeration
        # rejects the pin there) — resolution behavior must not depend
        # on whether a plan file happens to be armed.
        raise ValueError(
            f"no legal candidates: pinned fuse={fuse} fails legality for "
            f"{w.filter_name} {w.shape} on grid {w.grid}")
    if plan is not None and tile is not None and not search._legal_tiles(
            w, plan.backend, (tuple(tile),), strict=True):
        raise ValueError(
            f"no legal candidates: pinned tile={tuple(tile)} fails "
            f"legality for {w.filter_name} {w.shape} on grid {w.grid}")
    if plan is not None:
        p_fuse, p_tile = _legal_plan_knobs(w, plan)
        r_fuse = int(fuse) if fuse is not None else p_fuse
        # An explicit overlap request overrides the plan's verdict;
        # either way the decision is clamped to legality at the knobs
        # actually resolved (a pinned fuse can change the legal
        # interior, so the stored clamp is not enough).  Same rule for
        # col_mode: explicit request wins, the stored verdict otherwise,
        # normalized to the canonical "packed" off the persistent tiers
        # (where no column RDMA transport exists).
        want_ov = plan.overlap if overlap is None else overlap
        want_cm = (plan.col_mode if col_mode in (None, "auto")
                   else col_mode)
        if (plan.backend not in costmodel.PERSISTENT_BACKENDS
                or w.grid[1] <= 1
                or want_cm not in costmodel.COL_MODES):
            want_cm = "packed"  # no transport / inert: canonical label
        res = Resolution(
            backend=plan.backend,
            fuse=r_fuse,
            tile=tile if tile is not None else p_tile,
            source=plan.source,
            predicted_gpx=plan.predicted_gpx,
            key=w.key(),
            overlap=bool(want_ov) and costmodel.overlap_legal(
                plan.backend, w.grid, w.block_hw, w.radius, r_fuse),
            col_mode=want_cm,
        )
    else:
        result = search.tune(
            w, mesh=None, dry_run=True,
            fuses=[int(fuse)] if fuse is not None else None,
            tiles=[tuple(tile)] if tile is not None else None,
            overlap=overlap, col_mode=col_mode)
        p = result.plan
        res = Resolution(
            backend=p.backend,
            fuse=int(fuse) if fuse is not None else p.fuse,
            tile=tile if tile is not None else p.tile,
            source="predicted",
            predicted_gpx=p.predicted_gpx,
            key=w.key(),
            overlap=p.overlap,
            col_mode=p.col_mode,
        )
    _LAST.append(res)
    del _LAST[:-4]  # bounded history; only the last is ever read
    return res
