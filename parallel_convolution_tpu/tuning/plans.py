"""Persistent tuning-plan cache: key schema, atomic JSON store, fallback.

A *plan* is the tuner's verdict for one workload identity — which
backend, fusion depth, and kernel tile to run — plus provenance (was it
measured on this chip, interpolated from a neighboring size bucket, or
predicted by the cost model alone?).  Plans persist as one
schema-versioned JSON file so two expensive rounds of hand-run silicon
sweeps (``scripts/tune_pallas.py`` → paste into ``DEFAULT_TILE``)
become infrastructure: tune once, every later process — CLI runs, the
serving tier's warmup, bench sweeps — resolves ``backend="auto"``
through the file.

Key schema (``PLAN_SCHEMA``): the full tuning identity —

  platform / device kind (a v5e plan must never drive a v4 or a CPU),
  mesh grid (block geometry changes the whole candidate space),
  channels + (H, W) size *bucket* (next power of two: 8000x8000 and
  8192x8192 tune identically; distinct buckets do not),
  filter name + radius, storage dtype, quantize, boundary.

Canonical keys are ``json.dumps(..., sort_keys=True)`` of the field
dict, so key equality is insensitive to construction order (pinned by
``tests/test_tuning.py``).

Fallback ladder of :meth:`PlanCache.best_plan`::

  exact key hit          -> the plan, its own provenance ("measured"
                            or "predicted", as stored)
  same chip+config,      -> nearest bucket by |log2 area| distance,
  different size bucket     provenance rewritten to "interpolated"
  nothing                -> None (caller falls back to the cost model,
                            provenance "predicted")

Writes are atomic (tmp + ``os.replace``); a corrupt or
wrong-schema file loads as empty with a warning — a torn write can
cost a re-tune, never a crash or a silently-wrong plan.

jax-free by design: the one jax touch (resolving platform/device kind
from a mesh) lives in :meth:`Workload.from_mesh` and imports lazily.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import tempfile
import warnings

PLAN_SCHEMA = 1

# Environment override for the default plan file consulted by
# ``backend="auto"`` when the caller supplies no cache.
PLAN_FILE_ENV = "PCTPU_PLAN_FILE"

PROVENANCES = ("measured", "interpolated", "predicted")


def _bucket(n: int) -> int:
    """Size bucket: next power of two (>= 8) — 8000 and 8192 share one."""
    b = 8
    while b < n:
        b *= 2
    return b


@dataclasses.dataclass(frozen=True)
class Workload:
    """One tunable workload identity (everything the plan key carries).

    ``shape`` is the logical (C, H, W); ``block_hw`` the per-device
    block after pad-to-multiple (derived, not part of the key — it is a
    function of shape bucket + grid).
    """

    platform: str
    device_kind: str
    grid: tuple[int, int]
    shape: tuple[int, int, int]
    filter_name: str
    radius: int
    taps_k: int
    separable: bool
    dyadic: bool
    storage: str = "f32"
    quantize: bool = True
    boundary: str = "zero"
    # Convergence-path identity (None = the fixed-count path).  Part of
    # the key because check_every bounds the legal fusion depth (a chunk
    # fuses at most its n-1 pre-pair iterations) — a plan tuned for the
    # fixed-count program must not silently drive the convergence one.
    check_every: int | None = None

    @property
    def block_hw(self) -> tuple[int, int]:
        _, H, W = self.shape
        R, C = self.grid
        return (-(-H // R), -(-W // C))  # ceil-div == padded_extent // n

    @classmethod
    def from_mesh(cls, mesh, filt, shape, *, storage: str = "f32",
                  quantize: bool = True, boundary: str = "zero",
                  check_every: int | None = None,
                  ) -> "Workload":
        """Build the identity for ``shape`` (C, H, W) on ``mesh``."""
        from parallel_convolution_tpu.parallel.mesh import grid_shape

        dev = mesh.devices.flat[0]
        return cls(
            platform=dev.platform,
            device_kind=getattr(dev, "device_kind", "") or "",
            grid=grid_shape(mesh),
            shape=tuple(int(s) for s in shape),
            filter_name=filt.name,
            radius=filt.radius,
            taps_k=filt.size,
            separable=filt.separable() is not None,
            dyadic=bool(filt.dyadic),
            storage=storage,
            quantize=bool(quantize),
            boundary=boundary,
            check_every=None if check_every is None else int(check_every),
        )

    def key_fields(self) -> dict:
        """The plan-key field dict (bucketed sizes, no derived values).

        ``check_every`` appears only when set: fixed-count keys are
        byte-identical to the pre-round-10 schema, so existing plan
        files stay valid without a schema bump.
        """
        C, H, W = self.shape
        fields = {
            "schema": PLAN_SCHEMA,
            "platform": self.platform,
            "device_kind": self.device_kind,
            "grid": f"{self.grid[0]}x{self.grid[1]}",
            "channels": C,
            "bucket_hw": f"{_bucket(H)}x{_bucket(W)}",
            "filter": self.filter_name,
            "radius": self.radius,
            "storage": self.storage,
            "quantize": self.quantize,
            "boundary": self.boundary,
        }
        if self.check_every is not None:
            fields["check_every"] = int(self.check_every)
        return fields

    def key(self) -> str:
        return canonical_key(self.key_fields())


def canonical_key(fields: dict) -> str:
    """Order-insensitive canonical key string for a field dict."""
    return json.dumps(fields, sort_keys=True, separators=(",", ":"))


@dataclasses.dataclass
class Plan:
    """One tuning verdict.  ``source`` is the provenance the resolving
    caller stamps into its rows (``plan_source``)."""

    backend: str
    fuse: int = 1
    tile: tuple[int, int] | None = None
    source: str = "predicted"
    predicted_gpx: float | None = None
    measured_gpx: float | None = None
    # Interior-first overlapped halo pipeline (RDMA tier).  Serialized
    # records from pre-overlap plan files lack the key and default to
    # False — the exact pre-overlap behavior, so no schema bump.
    overlap: bool = False
    # Column-slab transport (round 16, the packed-vs-strided A/B).
    # Legacy records lack the key and default to the canonical "packed"
    # — byte-identical to every other mode, so no schema bump.
    col_mode: str = "packed"

    def to_record(self, workload: Workload | None = None) -> dict:
        rec = {
            "backend": self.backend,
            "fuse": int(self.fuse),
            "tile": list(self.tile) if self.tile else None,
            "source": self.source,
            "predicted_gpx": self.predicted_gpx,
            "measured_gpx": self.measured_gpx,
            "overlap": bool(self.overlap),
            "col_mode": str(self.col_mode),
        }
        if workload is not None:
            rec["key_fields"] = workload.key_fields()
        return rec

    @classmethod
    def from_record(cls, rec: dict) -> "Plan":
        tile = rec.get("tile")
        return cls(
            backend=rec["backend"],
            fuse=int(rec.get("fuse", 1)),
            tile=tuple(int(v) for v in tile) if tile else None,
            source=rec.get("source", "measured"),
            predicted_gpx=rec.get("predicted_gpx"),
            measured_gpx=rec.get("measured_gpx"),
            overlap=bool(rec.get("overlap", False)),
            col_mode=str(rec.get("col_mode", "packed")),
        )


def _area_of_bucket(bucket_hw: str) -> float:
    h, w = (int(v) for v in bucket_hw.split("x"))
    return float(h) * float(w)


def _ndev_of_grid(grid: str) -> float:
    r, c = (int(v) for v in grid.split("x"))
    return float(r) * float(c)


class PlanCache:
    """In-memory view of one plan file (key string -> plan record)."""

    def __init__(self, path: str | None = None):
        self.path = path
        self.records: dict[str, dict] = {}

    # -- persistence --------------------------------------------------------
    @classmethod
    def load(cls, path: str | None) -> "PlanCache":
        """Load ``path``; missing, corrupt, or wrong-schema files yield an
        EMPTY cache (warned) — a torn write costs a re-tune, never a
        crash and never a silently-wrong plan."""
        cache = cls(path)
        if not path or not os.path.exists(path):
            return cache
        try:
            with open(path, encoding="utf-8") as f:
                data = json.load(f)
            if data.get("schema") != PLAN_SCHEMA:
                raise ValueError(
                    f"plan schema {data.get('schema')!r} != {PLAN_SCHEMA}")
            records = data["plans"]
            if not isinstance(records, dict):
                raise ValueError("'plans' must be an object")
        except Exception as e:  # noqa: BLE001 — fallback IS the contract
            warnings.warn(
                f"ignoring unusable plan file {path!r}: {e!r} (tuning "
                "falls back to the cost model)",
                stacklevel=2)
            return cache
        cache.records = records
        return cache

    def save(self, path: str | None = None) -> str:
        """Atomic write (tmp + rename) of the whole cache; returns path."""
        path = path or self.path
        if not path:
            raise ValueError("PlanCache.save needs a path")
        payload = {"schema": PLAN_SCHEMA, "plans": self.records}
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(prefix=".plans.", dir=d)
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                json.dump(payload, f, indent=1, sort_keys=True)
                f.write("\n")
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        self.path = path
        return path

    def merge_save(self, path: str) -> str:
        """Merge this cache's records over whatever ``path`` holds now
        and write the union atomically (the ``--emit-plans`` verb)."""
        disk = PlanCache.load(path)
        disk.records.update(self.records)
        disk.save(path)
        self.records = disk.records
        self.path = path
        return path

    # -- access -------------------------------------------------------------
    def put(self, workload: Workload, plan: Plan) -> None:
        self.records[workload.key()] = plan.to_record(workload)

    @staticmethod
    def _plan_of(rec) -> Plan | None:
        """Parse one record; malformed records are WARNED AND SKIPPED —
        the file-level 'never a crash' contract applies per record too
        (a hand-edited or buggy-merge entry must cost a re-tune, not
        kill every backend='auto' resolution in the process)."""
        try:
            return Plan.from_record(rec)
        except (KeyError, TypeError, ValueError) as e:
            warnings.warn(f"ignoring malformed plan record {rec!r}: {e!r}",
                          stacklevel=3)
            return None

    def exact(self, workload: Workload) -> Plan | None:
        rec = self.records.get(workload.key())
        return self._plan_of(rec) if rec else None

    def best_plan(self, workload: Workload) -> Plan | None:
        """The fallback ladder: exact -> nearest same-chip size bucket ->
        nearest same-chip GRID (elastic recovery: a resharded resume on
        a shrunken mesh still resolves the run's tuned plan instead of
        silently falling back to the cost model) -> None.  Every
        non-exact hit's provenance is rewritten to 'interpolated', and
        the resolver re-clamps interpolated knobs to the target grid's
        legality (``tuning._legal_plan_knobs``).
        """
        hit = self.exact(workload)
        if hit is not None:
            return hit
        want = workload.key_fields()
        want_area = _area_of_bucket(want["bucket_hw"])
        want_ndev = _ndev_of_grid(want["grid"])
        # rank: same-grid tier before cross-grid, then grid distance
        # (|log2 device-count ratio|), bucket distance, key string —
        # fully deterministic.
        best: tuple[tuple, dict] | None = None
        for key, rec in self.records.items():
            have = rec.get("key_fields")
            # Field-set parity: a record carrying fields the workload
            # lacks (e.g. a convergence plan's check_every against a
            # fixed-count resolve) is a different identity, not a
            # neighbor.
            if not have or set(have) != set(want):
                continue
            diff = {f for f in want if have.get(f) != want[f]}
            if not diff <= {"bucket_hw", "grid"}:
                continue
            try:
                bucket_dist = abs(
                    math.log2(_area_of_bucket(have["bucket_hw"]))
                    - math.log2(want_area))
                grid_dist = abs(math.log2(_ndev_of_grid(have["grid"]))
                                - math.log2(want_ndev))
            except (KeyError, ValueError):
                continue
            rank = ("grid" in diff, grid_dist, bucket_dist, key)
            if best is None or rank < best[0]:
                best = (rank, rec)
        if best is None:
            return None
        plan = self._plan_of(best[1])
        if plan is None:
            return None
        plan.source = "interpolated"
        return plan

    def __len__(self) -> int:
        return len(self.records)


def default_plan_path() -> str | None:
    """The plan file named by ``PCTPU_PLAN_FILE`` (None when unset)."""
    return os.environ.get(PLAN_FILE_ENV) or None


def default_cache() -> PlanCache:
    """The ambient plan cache: ``PCTPU_PLAN_FILE`` if set, else empty.

    Loaded fresh per call — plan files are small, and re-reading keeps
    long-lived processes (the serving tier) coherent with a tuner that
    just emitted new plans.
    """
    return PlanCache.load(default_plan_path())
