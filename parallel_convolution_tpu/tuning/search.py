"""Candidate enumeration + measured search over the stencil knob space.

The reference repo's whole point is choosing the decomposition that fits
the hardware (MPI grid x OpenMP tile); this module is that choice made
by machine for the TPU port's knobs — backend tier, temporal-fusion
depth, Pallas kernel tile — in the AutoTVM/Halide-scheduler shape
(PAPERS.md): a deterministic *legal* candidate space, an analytical
prior (``tuning.costmodel``) that ranks it, and measured refinement
(``utils.bench.bench_iterate``) over only the model's shortlist, so a
full tune is O(dozens) of compiles rather than the knob product.

Legality rules are the kernels' own constraints, enumerated rather than
discovered as compile errors:

* tiles: multiples of the storage dtype's (sublane, 128) HBM tiling,
  within the Mosaic scoped-VMEM budget for the kernel form (the 2D tap
  loop keeps ~k^2 live (th, tw) f32 temporaries; the separable form
  reuses one pair — DESIGN.md round-1 lesson 2);
* fuse: ``block >= r*T`` (every backend), plus ``r*T <= sublane`` when
  the RDMA tier would auto-select its tiled kernel (the aligned band
  carries every live ghost row);
* separable tiers only where they are byte-safe: an exactly rank-1
  filter, and only in quantize mode with dyadic taps (the same rule
  ``resilience.degrade`` applies when walking *out* of them) — auto
  must never pick a backend that changes bytes.

``tune(..., dry_run=True)`` never touches a device: it returns the
model-ranked best with ``source="predicted"`` — runnable on any CPU,
which is what the tier-1 ``--tuning-smoke`` leg exercises.
"""

from __future__ import annotations

import dataclasses

from parallel_convolution_tpu.tuning import costmodel
from parallel_convolution_tpu.tuning.plans import Plan, Workload

__all__ = ["Candidate", "enumerate_candidates", "rank", "tune",
           "TuneResult"]

# Tile menu swept on silicon by the round-1 tuner; legality filters trim
# it per workload.  None = the per-kernel tuned default, always legal.
TILE_MENU = (None, (128, 512), (256, 256), (256, 512), (256, 1024),
             (512, 512), (512, 1024), (1024, 512))

FUSE_MENU = (1, 2, 4, 8, 16, 32)

# Model-tie preference: earlier wins.  Compiled-XLA normative path first
# among equals so a flat model (e.g. all-CPU) resolves to 'shifted'.
_PREFERENCE = ("shifted", "xla_conv", "separable", "pallas_sep", "pallas",
               "pallas_rdma")


@dataclasses.dataclass(frozen=True)
class Candidate:
    """One point of the knob space:
    (backend, fuse, tile, overlap, col_mode)."""

    backend: str
    fuse: int = 1
    tile: tuple[int, int] | None = None
    overlap: bool = False  # interior-first overlapped halo pipeline
    #                        (RDMA tier only; costmodel.overlap_legal)
    col_mode: str = "packed"  # column-slab transport (persistent tiers
    #                           only; both modes byte-identical, the
    #                           model prices the descriptor trade)


def _sep_byte_safe(w: Workload) -> bool:
    """Separable tiers are candidates only where their rank-1 rounding
    order is provably byte-identical (degrade.py's rule, applied at
    selection time instead of fallback time)."""
    return w.separable and w.quantize and w.dyadic


def _legal_backends(w: Workload) -> list[str]:
    out = ["shifted", "xla_conv", "pallas", "pallas_rdma"]
    if _sep_byte_safe(w):
        out += ["separable", "pallas_sep"]
    return out


def _legal_fuses(w: Workload, backend: str, menu,
                 strict: bool = False) -> list[int]:
    """``strict=True`` (explicitly-pinned menus) returns [] when nothing
    survives — the pin must die loudly upstream, never be silently
    remeasured as fuse=1; the default menu falls back to the always-
    legal unfused depth."""
    bh, bw = w.block_hw
    # Convergence workloads fuse at most their n-1 pre-pair iterations
    # (step._build_converge clamps to check_every - 1); enumerating past
    # that would tune a depth the runner can never execute.
    ce = getattr(w, "check_every", None)
    fuse_cap = None if ce is None else max(1, int(ce) - 1)
    out = []
    for T in menu:
        T = int(T)
        if T < 1 or w.radius * T > min(bh, bw):
            continue
        if fuse_cap is not None and T > fuse_cap:
            continue
        if backend == "pallas_rdma":
            if costmodel.rdma_is_tiled(w.shape, w.block_hw, w.radius, T,
                                       w.storage):
                sub = costmodel.SUBLANE[w.storage]
                if (w.radius * T > min(sub, costmodel.LANE)
                        or bh < sub or bw < costmodel.LANE):
                    continue
        out.append(T)
    return out or ([] if strict else [1])


def _tile_vmem_ok(w: Workload, backend: str, tile: tuple[int, int],
                  fuse: int = 1) -> bool:
    """Scoped-VMEM estimate for a candidate (tile, fuse) point.

    2D tap loop: ~(k^2 + 2) live (th, tw) f32 temporaries (the unrolled
    shifted multiply-add chain) — the form that failed Mosaic compile at
    1024x512 f32 (25.3 MB vs the 16 MB bound).  Separable: one
    (th+k-1, tw) + one (th, tw) accumulator.  Both forms additionally
    hold the double-buffered input-window pair, which GROWS with the
    fusion depth (2*r*T rim per side) — legality is per (tile, fuse)
    point, not per tile, or a deep-fused candidate near the bound would
    pass at the fuse=1 estimate and fail Mosaic compile at launch.
    Estimates err permissive-by-~20%; the degrade walk (and measured
    search) catches what slips through.
    """
    th, tw = tile
    k = w.taps_k
    d = w.radius * max(1, int(fuse))
    window = 2 * (th + 2 * d) * (tw + 2 * d) * costmodel.STORAGE_BYTES[
        w.storage]
    if backend == "pallas_sep" and _sep_byte_safe(w):
        live = ((th + k - 1) * tw + th * tw) * 4 + window
    else:
        live = (k * k + 2) * th * tw * 4 + window
    return live <= costmodel.SCOPED_VMEM_BYTES


def _legal_tiles(w: Workload, backend: str, menu,
                 strict: bool = False, fuse: int = 1) -> list:
    """``strict`` as in :func:`_legal_fuses` — a pinned tile that fails
    legality yields [] (loud upstream error), never a silent None.
    Non-Pallas backends have no tile knob, so any menu degenerates to
    [None] there (the value is ignored by the kernels)."""
    if backend not in costmodel.PALLAS_BACKENDS:
        return [None]
    sub = costmodel.SUBLANE[w.storage]
    bh, bw = w.block_hw
    out = []
    for t in menu:
        if t is None:
            out.append(None)
            continue
        th, tw = (int(v) for v in t)
        if th % sub or tw % costmodel.LANE:
            continue  # HBM DMA slices must align to (sublane, 128)
        if th > max(bh, sub) or tw > max(bw, costmodel.LANE):
            continue  # larger than the block: degenerate duplicate of None
        if not _tile_vmem_ok(w, backend, (th, tw), fuse):
            continue
        out.append((th, tw))
    return out or ([] if strict else [None])


def _legal_overlaps(w: Workload, backend: str, fuse: int,
                    overlap: bool | None) -> list[bool]:
    """Overlap options for one (backend, fuse) point.

    ``overlap`` is a *request*, not a hard pin: an explicit True is
    clamped to legality (the serialized form is always available, and
    every artifact stamps the RESOLVED value) — unlike fuse/tile pins,
    which die loudly, because overlap legality depends on the backend
    the tuner is still choosing, so a hard pin would empty every
    non-RDMA branch of the space.

    Interpreted-Pallas platforms enumerate only the serialized form
    (unless the byte-proof env hatch is armed): the dispatch layer
    force-serializes overlap there, so an overlap=True candidate would
    MEASURE the serialized executable — two identical twins burning the
    measurement budget, and a plan stamped overlap=True whose
    measured_gpx never ran the overlapped program.
    """
    import os

    from parallel_convolution_tpu.utils.config import OVERLAP_INTERPRET_ENV

    legal = costmodel.overlap_legal(backend, w.grid, w.block_hw, w.radius,
                                    fuse)
    if (legal and costmodel.hardware_for(
            w.platform, w.device_kind).interpret_pallas
            and not os.environ.get(OVERLAP_INTERPRET_ENV)):
        legal = False
    if overlap is None:
        return [False, True] if legal else [False]
    return [bool(overlap) and legal]


def _legal_col_modes(w: Workload, backend: str,
                     col_mode: str | None) -> list[str]:
    """Column-transport options for one backend.

    Only persistent-capable tiers with a REAL remote column axis have
    the A/B (both transports compile the identical statically-elided
    program otherwise — enumerating twins would burn the measurement
    budget on duplicates); everywhere else the knob is inert and
    normalizes to the canonical "packed".  Like overlap, an explicit
    request is clamped rather than dying: both modes are byte-identical,
    and legality depends on the backend the tuner is still choosing.
    """
    if (backend not in costmodel.PERSISTENT_BACKENDS
            or w.grid[1] <= 1):
        return ["packed"]
    if col_mode in (None, "auto"):
        return ["packed", "strided"]
    return [col_mode if col_mode in costmodel.COL_MODES else "packed"]


def enumerate_candidates(w: Workload, backends=None, fuses=None,
                         tiles=None, overlap: bool | None = None,
                         col_mode: str | None = None,
                         ) -> list[Candidate]:
    """The deterministic legal candidate list for one workload.

    ``backends``/``fuses``/``tiles`` pin a sub-space (an explicitly
    passed knob is honored verbatim; legality still filters fuse depth
    so an impossible pin dies here with an empty-space error rather
    than deep inside a kernel launch).  ``overlap`` (None = enumerate
    both where legal) is a clamped request — see :func:`_legal_overlaps`
    — and ``col_mode`` likewise (None/'auto' = enumerate both where the
    transport exists; see :func:`_legal_col_modes`).
    """
    out = []
    for b in (backends if backends is not None else _legal_backends(w)):
        for T in _legal_fuses(w, b, fuses if fuses is not None
                              else FUSE_MENU, strict=fuses is not None):
            for t in _legal_tiles(w, b, tiles if tiles is not None
                                  else TILE_MENU, strict=tiles is not None,
                                  fuse=T):
                for ov in _legal_overlaps(w, b, T, overlap):
                    for cm in _legal_col_modes(w, b, col_mode):
                        out.append(Candidate(b, T, t, ov, cm))
    if not out:
        raise ValueError(
            f"no legal candidates for {w.filter_name} {w.shape} on grid "
            f"{w.grid} (backends={backends}, fuses={fuses}, tiles={tiles})")
    return out


def predict(w: Workload, c: Candidate,
            hw: costmodel.HardwareModel | None = None) -> float:
    """Model seconds/px/iter for one candidate (ranking unit)."""
    hw = hw or costmodel.hardware_for(w.platform, w.device_kind)
    return costmodel.predict_seconds_per_px_iter(
        c.backend, w.storage, c.fuse, c.tile, w.shape, w.block_hw, w.grid,
        w.taps_k, w.separable, w.quantize, hw, overlap=c.overlap,
        col_mode=c.col_mode)


def rank(w: Workload, candidates,
         hw: costmodel.HardwareModel | None = None,
         ) -> list[tuple[float, Candidate]]:
    """Candidates sorted best-first by predicted time, deterministically
    (ties break on the backend preference order, then the knob tuple)."""
    hw = hw or costmodel.hardware_for(w.platform, w.device_kind)

    def sort_key(pc):
        t, c = pc
        pref = (_PREFERENCE.index(c.backend)
                if c.backend in _PREFERENCE else len(_PREFERENCE))
        # overlap last: on a model tie (exchange fully hidden OR zero)
        # the serialized form wins — never pipeline for a predicted 0.
        # col_mode last of all: packed (the canonical label) wins ties.
        return (t, pref, c.fuse, c.tile or (0, 0), c.overlap, c.col_mode)

    return sorted(((predict(w, c, hw), c) for c in candidates),
                  key=sort_key)


@dataclasses.dataclass
class TuneResult:
    """A tune's verdict plus its evidence rows (one per measured point)."""

    plan: Plan
    workload: Workload
    rows: list[dict]


def measure(w: Workload, c: Candidate, mesh, *, iters: int = 8,
            reps: int = 2, interior_split: bool = False) -> dict:
    """One measured point: a ``bench_iterate`` row for this candidate
    (resolved tile/fuse stamped by bench itself), plus the model's
    prediction for measured-vs-predicted visibility."""
    from parallel_convolution_tpu.ops.filters import get_filter
    from parallel_convolution_tpu.utils import bench

    # At least one full fused chunk: bench clamps fuse to iters, so a
    # fuse=32 candidate measured at iters=8 would silently price fuse=8
    # (and its row would say so — but the tuner must price the ACTUAL
    # candidate).  Per-iteration normalization keeps rows comparable.
    row = bench.bench_iterate(
        w.shape[1:], get_filter(w.filter_name), max(iters, c.fuse),
        mesh=mesh, channels=w.shape[0], backend=c.backend,
        quantize=w.quantize, storage=w.storage, fuse=c.fuse,
        boundary=w.boundary, reps=reps, tile=c.tile,
        interior_split=interior_split, overlap=c.overlap,
        col_mode=c.col_mode)
    row["predicted_gpx_per_chip"] = round(
        costmodel.predict_gpx_per_chip(predict(w, c)), 3)
    return row


def tune(w: Workload, mesh=None, *, dry_run: bool = False,
         backends=None, fuses=None, tiles=None, overlap: bool | None = None,
         col_mode: str | None = None,
         iters: int = 8,
         reps: int = 2, max_measure: int = 8, prune_factor: float = 4.0,
         interior_split: bool = False) -> TuneResult:
    """Tune one workload: rank the legal space, optionally measure.

    ``dry_run=True`` (or ``mesh=None``) returns the model's pick with
    ``source="predicted"`` and zero device work.  Otherwise the top
    ``max_measure`` candidates within ``prune_factor`` of the model-best
    predicted time are benched (each one compile + a few timed reps — a
    full tune is O(dozens) of compiles, not the knob product) and the
    best measured Gpx/s/chip wins, ``source="measured"``.  Candidates
    that fail to compile/launch are recorded as error rows and skipped —
    the tuner prices what works.
    """
    ranked = rank(w, enumerate_candidates(w, backends, fuses, tiles,
                                          overlap=overlap,
                                          col_mode=col_mode))
    best_t, best_c = ranked[0]
    predicted_gpx = costmodel.predict_gpx_per_chip(best_t)
    if dry_run or mesh is None:
        return TuneResult(
            Plan(best_c.backend, best_c.fuse, best_c.tile,
                 source="predicted",
                 predicted_gpx=round(predicted_gpx, 3),
                 overlap=best_c.overlap, col_mode=best_c.col_mode),
            w, rows=[])
    rows: list[dict] = []
    measured: list[tuple[float, Candidate, float]] = []
    shortlist = [(t, c) for t, c in ranked
                 if t <= best_t * prune_factor][:max(1, int(max_measure))]
    for t, c in shortlist:
        try:
            row = measure(w, c, mesh, iters=iters, reps=reps,
                          interior_split=interior_split)
        except Exception as e:  # noqa: BLE001 — an illegal point is data
            rows.append({"backend": c.backend, "fuse": c.fuse,
                         "tile": (f"{c.tile[0]}x{c.tile[1]}" if c.tile
                                  else None),
                         "overlap": c.overlap, "col_mode": c.col_mode,
                         "error": repr(e)[:200]})
            continue
        rows.append(row)
        measured.append((row["gpixels_per_s_per_chip"], c,
                         row["predicted_gpx_per_chip"]))
    if not measured:
        raise RuntimeError(
            f"every shortlisted candidate failed to measure "
            f"({len(shortlist)} tried); see rows for errors")
    measured.sort(key=lambda m: (-m[0], _PREFERENCE.index(m[1].backend)
                                 if m[1].backend in _PREFERENCE
                                 else len(_PREFERENCE)))
    gpx, c, pred = measured[0]
    return TuneResult(
        Plan(c.backend, c.fuse, c.tile, source="measured",
             predicted_gpx=round(pred, 3), measured_gpx=round(gpx, 3),
             overlap=c.overlap, col_mode=c.col_mode),
        w, rows=rows)
