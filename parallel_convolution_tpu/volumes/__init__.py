"""Rank-3 volumetric subsystem: (D, H, W) volumes on the 2D mesh.

Round 23.  The kernel-form registry (``parallel/kernels.py``) was built
so rank-3 workloads "register without touching dispatch" — this package
is that claim cashed in:

* ``halo3``  — 6-face ghost exchange for (F, D, h, w) blocks.  The mesh
  stays 2D ('x', 'y') and shards (H, W); the depth axis D rides WHOLE on
  every device, so its two faces are a local pad (zeros or wrap) and the
  ±H/±W faces reuse ``parallel.halo.halo_pad_axis`` — the exact slab
  machinery rank 2 exchanges through, one extra leading dim.
* ``forms``  — the rank-3 kernel forms, registered under
  ``(3, name, boundary)`` keys: 7-point and 25-point (8th-order star)
  FD Laplacian Jacobi relaxations (each with a ``_stack`` twin — the
  same fixed-order arithmetic through a different XLA program, the
  byte-identity proof pair), plus two time-dependent ``physics`` forms
  (wave leapfrog, Gray–Scott reaction–diffusion), every one carrying
  TWO stacked fields.
* ``driver`` — the sharded entry points (prepare / iterate / converge
  stream), mirroring ``parallel/step.py``'s shard_map + temporal-fusion
  schedule for rank 3.
* ``oracle3`` — an INDEPENDENT numpy oracle (np.pad/np.roll, float64
  accumulation) the tests and the volume smoke compare against.

Zero new dispatch ladders: everything resolves through
``kernels.resolve(3, name, boundary)``.
"""
