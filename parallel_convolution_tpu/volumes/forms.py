"""Rank-3 kernel forms: FD Laplacian smoothers + time-dependent physics.

Every rank-3 form operates on a block of TWO stacked fields — the field
contract (DESIGN.md "Volumetric workloads"):

* ``fd7`` / ``fd25``       — ``(u, f)``: damped-Jacobi relaxation of the
  discrete Poisson problem ``-∇²u = f`` (``f`` pre-scaled by ``h²`` by
  the caller; the forms are spacing-free).  ``fd7`` is the classic
  7-point star; ``fd25`` the 8th-order 25-point star (3 axes × 8
  off-center taps + center) — the wafer-scale stencil paper's marquee
  kernel.  Per-axis taps at distance k: ``8/5, -1/5, 8/315, -1/560``;
  diagonal ``3·205/72 = 205/24``.
* ``wave``                 — ``(u, u_prev)``: 2nd-order leapfrog of the
  wave equation, ``u_next = 2u - u_prev + c²dt²·∇²₇u``.
* ``grayscott``            — ``(U, V)``: Gray–Scott reaction–diffusion,
  two coupled fields through the 7-point Laplacian.

``fd7_stack`` / ``fd25_stack`` are byte-identity proof twins: the SAME
weighted terms accumulated in the SAME fixed order, but routed through a
``jnp.stack`` + re-slice — a genuinely different XLA program that must
(and does — gated by scripts/volume_smoke.py) produce identical bytes.

Fields arrive INTERLEAVED on the leading axis — ``(2B, D, h, w)`` with
field k of batch item b at index ``2b + k`` — so a batched volume folds
to one shard_map call exactly like rank 2's channel fold, and the forms
vectorize over the batch for free (``p[0::2]`` / ``p[1::2]``).

The build contract (owned by this module, resolved through the
registry): ``build(grid, depth, valid_hw, block_hw, fuse, boundary) ->
step``, where ``step`` maps one device's UNPADDED (F, D, h, w) block to
the next — one 6-face exchange at ghost depth ``radius*fuse``, then
``fuse`` stencil applications with per-level re-masking (H/W through
the rank-2 global-coordinate mask rule; the resident D ghost ring
re-zeroed locally), exactly rank 2's temporal-fusion schedule.
"""

from __future__ import annotations

import functools

import jax.numpy as jnp
from jax import lax

from parallel_convolution_tpu.parallel import kernels as kernel_forms
from parallel_convolution_tpu.utils.config import (
    VOLUME_PHYSICS_FORMS, VOLUME_RADII, VOLUME_SMOOTH_FORMS,
)
from parallel_convolution_tpu.volumes import halo3

__all__ = ["FD25_COEFFS", "FD25_DIAG", "FD25_OMEGA", "FD7_COEFFS",
           "FD7_DIAG",
           "GS_PARAMS", "WAVE_C2DT2", "build_volume_step", "form_radius"]

# Per-axis off-center taps at distance k = 1..r (Jacobi sign convention:
# u_new = (f + Σ c_k · neighbors) / diag) and the star's diagonal.
FD7_COEFFS = (1.0,)
FD7_DIAG = 6.0
FD25_COEFFS = (8.0 / 5.0, -1.0 / 5.0, 8.0 / 315.0, -1.0 / 560.0)
FD25_DIAG = 205.0 / 24.0
# The 25-point star's Jacobi damping: the mixed-sign taps sum (in
# absolute phase) past the diagonal at high frequency, so the UNDAMPED
# iteration diverges; ω = 0.8 bounds every mode below 1 while barely
# touching the smooth-mode rate the convergence gate measures.
FD25_OMEGA = 0.8

# Wave leapfrog Courant factor c²dt²/h² — inside the 3D CFL bound (1/3).
WAVE_C2DT2 = 0.2
# Gray–Scott constants (Du, Dv, F, k, dt) — the classic "solitons" spot.
GS_PARAMS = (0.16, 0.08, 0.060, 0.062, 1.0)


def form_radius(name: str) -> int:
    """Ghost radius of one application of a registered rank-3 form."""
    return VOLUME_RADII[name]


def _split(p):
    """Interleaved fields of a (2B, ...) block → two (B, ...) views."""
    return p[0::2], p[1::2]


def _merge(a, b):
    """Re-interleave two (B, ...) fields → (2B, ...)."""
    return jnp.stack([a, b], axis=1).reshape((-1,) + tuple(a.shape[1:]))


def _center(u, r):
    """The interior crop of a padded (B, d, h, w) field at radius r."""
    return (slice(None),) + tuple(slice(r, s - r) for s in u.shape[1:])


def _star_views(u, r):
    """Cropped shifted views of padded ``u`` in the canonical fixed
    order — for k = 1..r, for axis (D, H, W): the −k then +k view.
    Every consumer (plain and ``_stack``) accumulates in exactly this
    order; the order IS the byte-identity contract."""
    views = []
    for k in range(1, r + 1):
        for ax in (1, 2, 3):
            lo = list(_center(u, r))
            hi = list(_center(u, r))
            lo[ax] = slice(r - k, u.shape[ax] - r - k)
            hi[ax] = slice(r + k, u.shape[ax] - r + k)
            views.append(u[tuple(lo)])
            views.append(u[tuple(hi)])
    return views


def _fixed_sum(terms, stacked: bool):
    """Left-to-right accumulation; the ``stacked`` arm routes the same
    terms through one jnp.stack and re-slices — a different program, the
    same adds in the same association."""
    if stacked:
        st = jnp.stack(terms)
        s = st[0]
        for i in range(1, len(terms)):
            s = s + st[i]
        return s
    s = terms[0]
    for t in terms[1:]:
        s = s + t
    return s


def _jacobi_apply(p, r: int, coeffs, inv_diag, stacked: bool,
                  omega=None):
    """One damped-Jacobi sweep of an FD star: (u, f) interleaved, padded
    by r on (D, H, W) → interleaved interior.

    ``omega`` is the damping factor: ``u + ω(u_jacobi − u)``.  The
    7-point star converges plain (ω absent: the historical bytes), but
    the 8th-order star's mixed-sign taps put the undamped iteration
    matrix above 1 at high frequency (|Σ taps(π)| > diag), so fd25
    REQUIRES damping to be a convergent smoother — ω = 0.8 keeps every
    Dirichlet mode strictly inside the unit circle."""
    u, f = _split(p)
    cc = _center(u, r)
    terms = [f[cc]]
    views = _star_views(u, r)
    for k in range(1, r + 1):
        c = coeffs[k - 1]
        for i in range(6):
            terms.append(c * views[(k - 1) * 6 + i])
    s = _fixed_sum(terms, stacked)
    if omega is None:
        return _merge(s * inv_diag, f[cc])
    return _merge(u[cc] + omega * (s * inv_diag - u[cc]), f[cc])


def _lap7(u, cc):
    """7-point Laplacian of a padded (B, d, h, w) field at its interior:
    fixed-order neighbor sum minus 6·center."""
    views = _star_views(u, 1)
    s = views[0]
    for v in views[1:]:
        s = s + v
    return s - 6.0 * u[cc]


def _wave_apply(p):
    """Leapfrog: (u, u_prev) → (2u − u_prev + c²dt²·∇²u, u)."""
    u, v = _split(p)
    cc = _center(u, 1)
    u_next = (2.0 * u[cc] - v[cc]) + WAVE_C2DT2 * _lap7(u, cc)
    return _merge(u_next, u[cc])


def _gs_apply(p):
    """Gray–Scott: (U, V) coupled through the 7-point Laplacian."""
    du, dv, feed, kill, dt = GS_PARAMS
    ua, va = _split(p)
    cc = _center(ua, 1)
    uc, vc = ua[cc], va[cc]
    uvv = uc * vc * vc
    u_new = uc + (du * _lap7(ua, cc) - uvv + feed * (1.0 - uc)) * dt
    v_new = vc + (dv * _lap7(va, cc) + uvv - (feed + kill) * vc) * dt
    return _merge(u_new, v_new)


_APPLY = {
    "fd7": functools.partial(_jacobi_apply, r=1, coeffs=FD7_COEFFS,
                             inv_diag=jnp.float32(1.0 / FD7_DIAG),
                             stacked=False),
    "fd7_stack": functools.partial(_jacobi_apply, r=1, coeffs=FD7_COEFFS,
                                   inv_diag=jnp.float32(1.0 / FD7_DIAG),
                                   stacked=True),
    "fd25": functools.partial(_jacobi_apply, r=4, coeffs=FD25_COEFFS,
                              inv_diag=jnp.float32(1.0 / FD25_DIAG),
                              stacked=False,
                              omega=jnp.float32(FD25_OMEGA)),
    "fd25_stack": functools.partial(_jacobi_apply, r=4,
                                    coeffs=FD25_COEFFS,
                                    inv_diag=jnp.float32(1.0 / FD25_DIAG),
                                    stacked=True,
                                    omega=jnp.float32(FD25_OMEGA)),
    "wave": _wave_apply,
    "grayscott": _gs_apply,
}


def _valid_mask3(valid_hw, block_hw, margin: int = 0):
    """Rank-3 twin of ``step._valid_mask``: globally-in-volume cells of
    one block's (H, W) plane as (1, 1, h+2m, w+2m) f32.  D never pads to
    a multiple (it is resident), so depth needs no global mask — the
    ghost-ring re-zero in the fused schedule handles its boundary."""
    H, W = valid_hw
    bh, bw = block_hw
    m = int(margin)
    row0 = lax.axis_index("x") * bh - m
    col0 = lax.axis_index("y") * bw - m
    shape = (bh + 2 * m, bw + 2 * m)
    rows = row0 + lax.broadcasted_iota(jnp.int32, shape, 0)
    cols = col0 + lax.broadcasted_iota(jnp.int32, shape, 1)
    ok = (rows >= 0) & (rows < H) & (cols >= 0) & (cols < W)
    return ok[None, None].astype(jnp.float32)


def build_volume_step(name: str, grid, depth: int, valid_hw, block_hw,
                      fuse: int, boundary: str):
    """The registered build: one per-block step of form ``name``.

    ``step`` maps (F, depth, bh, bw) → same shape: one 6-face exchange
    at ghost depth ``radius*fuse``, then ``fuse`` applications with the
    rank-2 re-masking rule per intermediate level — the H/W mask speaks
    global coordinates (so the pad-to-multiple rim and the image edge
    re-zero), and the resident D ghost ring re-zeroes by a local re-pad
    (zero boundary only; periodic wraps exactly and never masks).
    """
    r = form_radius(name)
    fuse = max(1, int(fuse))
    d = r * fuse
    bh, bw = (int(b) for b in block_hw)
    depth = int(depth)
    if bh < d or bw < d:
        raise ValueError(
            f"form {name!r} at fuse={fuse} needs ghost depth {d} <= "
            f"block ({bh}, {bw}); shrink fuse or the mesh")
    periodic = boundary == "periodic"
    apply_fn = _APPLY[name]
    needs_mask = (not periodic) and (
        valid_hw[0] < bh * grid[0] or valid_hw[1] < bw * grid[1])

    def step(block):
        p = halo3.volume_halo_exchange(block, d, grid, boundary)
        for t in range(fuse):
            margin = d - r * (t + 1)
            p = apply_fn(p)
            if not periodic and (needs_mask or margin > 0):
                p = p * _valid_mask3(valid_hw, (bh, bw), margin)
                if margin > 0:
                    # Re-impose the zero D faces on the shrinking ghost
                    # ring (the temporal-fusion boundary rule, D arm).
                    core = p[:, margin:margin + depth]
                    p = jnp.pad(
                        core, ((0, 0), (margin, margin), (0, 0), (0, 0)))
        return p

    return step


def _register_volume_forms() -> None:
    from parallel_convolution_tpu.utils.config import BOUNDARIES

    for name in VOLUME_SMOOTH_FORMS:
        kernel_forms.register(kernel_forms.KernelForm(
            name=name, rank=3, stencil_form="smooth",
            boundaries=tuple(BOUNDARIES), overlap_capable=False,
            persistent_capable=False,
            build=functools.partial(build_volume_step, name)))
    for name in VOLUME_PHYSICS_FORMS:
        kernel_forms.register(kernel_forms.KernelForm(
            name=name, rank=3, stencil_form="physics",
            boundaries=tuple(BOUNDARIES), overlap_capable=False,
            persistent_capable=False,
            build=functools.partial(build_volume_step, name)))


_register_volume_forms()
