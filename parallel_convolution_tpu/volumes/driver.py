"""Sharded rank-3 drivers: prepare / iterate / converge on volumes.

The rank-3 twin of ``parallel/step.py``'s entry layer, kept deliberately
thin: every stencil program comes out of the kernel-form registry
(``kernels.resolve(3, name, boundary)`` — no backend ladder lives here),
and the compiled artifacts mirror rank 2 exactly:

* state is (F, D, H, W) float32, F interleaved fields (2 per volume,
  ``2B`` for a folded batch), sharded ``P(None, None, 'x', 'y')`` — the
  (H, W) plane on the mesh, D resident;
* (H, W) pad to block multiples + per-level masking (the forms own the
  mask rule); D never pads;
* fixed-count iterate = fori_loop over fused chunks + remainder tail;
* converge chunk = n−1 iterations (fused where legal) + ONE single step
  forming the (prev, cur) pair, ``diff = pmax(max|cur − prev|)`` — the
  same chunk math the serving stream and checkpoint/resume logic rely
  on for byte-stable resumes.

Compiled runners are ``lru_cache``d per (mesh, form, geometry, fuse),
``jax.jit(donate_argnums=0)`` like every other runner in the tree.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from parallel_convolution_tpu.parallel import kernels as kernel_forms
from parallel_convolution_tpu.parallel.mesh import (
    AXES, grid_shape, make_grid_mesh, padded_extent,
)
from parallel_convolution_tpu.utils.config import VOLUME_RADII
from parallel_convolution_tpu.utils.jax_compat import shard_map

__all__ = ["converge_chunk_fn", "prepare_volume", "volume_converge",
           "volume_converge_stream", "volume_iterate", "volume_sharding"]


def volume_sharding(mesh: Mesh) -> NamedSharding:
    """(F, D, H, W) over the 2D grid: P(None, None, 'x', 'y')."""
    return NamedSharding(mesh, P(None, None, *AXES))


def _geometry(state_shape, mesh: Mesh, boundary: str):
    """(valid_hw, block_hw, padded_hw) of a (F, D, H, W) volume on
    ``mesh`` — the one geometry rule (periodic must divide, zero pads
    and masks), shared by every entry point."""
    F, D, H, W = (int(s) for s in state_shape)
    if F < 2 or F % 2:
        raise ValueError(
            f"rank-3 state carries interleaved field pairs: leading "
            f"extent must be even >= 2, got {F}")
    R, C = grid_shape(mesh)
    if boundary == "periodic" and (H % R or W % C):
        raise ValueError(
            f"periodic volumes need grid-divisible extents: "
            f"{H}x{W} on {R}x{C}")
    Hp, Wp = padded_extent(H, R), padded_extent(W, C)
    return (H, W), (Hp // R, Wp // C), (Hp, Wp)


def prepare_volume(state, mesh: Mesh, boundary: str = "zero"):
    """Pad a host (F, D, H, W) float32 volume to block multiples and
    place it sharded; returns ``(device_state, valid_hw)``."""
    state = jnp.asarray(state, jnp.float32)
    if state.ndim != 4:
        raise ValueError(
            f"volume state must be (F, D, H, W), got {state.shape}")
    valid_hw, _, (Hp, Wp) = _geometry(state.shape, mesh, boundary)
    H, W = valid_hw
    if (Hp, Wp) != (H, W):
        state = jnp.pad(
            state, ((0, 0), (0, 0), (0, Hp - H), (0, Wp - W)))
    return jax.device_put(state, volume_sharding(mesh)), valid_hw


def _resolve_step(name: str, boundary: str, grid, depth, valid_hw,
                  block_hw, fuse: int):
    """One per-block step through the registry — the ONLY dispatch."""
    form = kernel_forms.resolve(3, name, boundary)
    return form.build(grid, depth, valid_hw, block_hw, fuse, boundary)


def _check_fuse(name: str, block_hw, fuse: int) -> None:
    # Unknown names fall through (radius 1): resolution raises the
    # registry's typed error naming the registered forms, not a KeyError.
    d = VOLUME_RADII.get(name, 1) * max(1, int(fuse))
    if min(block_hw) < d:
        raise ValueError(
            f"fuse={fuse} needs blocks >= {d} for form {name!r}, got "
            f"{block_hw}")


@lru_cache(maxsize=64)
def _build_volume_iterate(mesh: Mesh, name: str, iters: int, depth: int,
                          valid_hw, block_hw, fuse: int, boundary: str):
    """Compile the fixed-count volume runner for one (mesh, config)."""
    grid = grid_shape(mesh)
    fuse = max(1, min(int(fuse), iters or 1))
    _check_fuse(name, block_hw, fuse)
    chunk = _resolve_step(name, boundary, grid, depth, valid_hw,
                          block_hw, fuse)
    n_chunks, rem = divmod(int(iters), fuse)
    tail = (_resolve_step(name, boundary, grid, depth, valid_hw,
                          block_hw, rem) if rem else None)

    def body(block):
        block = lax.fori_loop(0, n_chunks, lambda _, v: chunk(v), block)
        if tail is not None:
            block = tail(block)
        return block

    sharded = shard_map(
        body, mesh=mesh, in_specs=P(None, None, *AXES),
        out_specs=P(None, None, *AXES),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=0)


@lru_cache(maxsize=64)
def _build_volume_converge_chunk(mesh: Mesh, name: str, n: int,
                                 depth: int, valid_hw, block_hw,
                                 fuse: int, boundary: str):
    """Compile ONE volume convergence chunk: ``n`` iterations + the
    (prev, cur) max-abs diff — the same chunk math as rank 2's
    ``_build_converge_chunk``, so host-driven chunk loops (the serving
    stream) resume byte-stably on check_every boundaries."""
    grid = grid_shape(mesh)
    fuse = max(1, min(int(fuse), max(1, n - 1)))
    _check_fuse(name, block_hw, fuse)
    step = _resolve_step(name, boundary, grid, depth, valid_hw,
                         block_hw, 1)
    fused = (_resolve_step(name, boundary, grid, depth, valid_hw,
                           block_hw, fuse)
             if fuse > 1 and n > 1 else None)

    def body(block):
        if fused is None:
            prev = lax.fori_loop(0, n - 1, lambda _, v: step(v), block)
        else:
            prev = lax.fori_loop(0, (n - 1) // fuse,
                                 lambda _, v: fused(v), block)
            prev = lax.fori_loop(0, (n - 1) % fuse,
                                 lambda _, v: step(v), prev)
        cur = step(prev)
        delta = jnp.abs(cur - prev)
        diff = lax.pmax(jnp.max(delta), AXES)
        return cur, diff

    sharded = shard_map(
        body, mesh=mesh, in_specs=P(None, None, *AXES),
        out_specs=(P(None, None, *AXES), P()),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=0)


def converge_chunk_fn(mesh: Mesh, name: str, n: int, depth: int,
                      valid_hw, block_hw, fuse: int, boundary: str):
    """Public cached-compile surface for chunk drivers (the serving
    engine): ``fn(xs) -> (xs, diff)``."""
    return _build_volume_converge_chunk(
        mesh, str(name), int(n), int(depth), tuple(valid_hw),
        tuple(block_hw), int(fuse), str(boundary))


def _crop(state, valid_hw) -> np.ndarray:
    H, W = valid_hw
    return np.asarray(jax.device_get(state))[:, :, :H, :W]


def volume_iterate(state, name: str, iters: int, *, mesh: Mesh | None = None,
                   boundary: str = "zero", fuse: int = 1) -> np.ndarray:
    """Run ``iters`` applications of rank-3 form ``name`` on a host
    (F, D, H, W) volume; returns the host float32 result at the valid
    extent.  The one-call CLI/test surface."""
    mesh = mesh if mesh is not None else make_grid_mesh()
    if int(iters) < 1:
        return np.asarray(state, np.float32)
    xs, valid_hw = prepare_volume(state, mesh, boundary)
    _, block_hw, _ = _geometry(
        (xs.shape[0], xs.shape[1], valid_hw[0], valid_hw[1]), mesh,
        boundary)
    fn = _build_volume_iterate(
        mesh, str(name), int(iters), int(xs.shape[1]), valid_hw,
        block_hw, int(fuse), str(boundary))
    xs = fn(xs)
    jax.block_until_ready(xs)
    return _crop(xs, valid_hw)


def volume_converge_stream(state, name: str, *, tol: float,
                           max_iters: int, check_every: int = 10,
                           mesh: Mesh | None = None,
                           boundary: str = "zero", fuse: int = 1):
    """Host-driven chunked convergence: yields ``(state, iters, diff)``
    per chunk (state cropped to the valid extent, host float32), the
    last yield being the converged/budget-exhausted field — rank 2's
    ``sharded_converge_stream`` shape, for volumes."""
    mesh = mesh if mesh is not None else make_grid_mesh()
    xs, valid_hw = prepare_volume(state, mesh, boundary)
    depth = int(xs.shape[1])
    _, block_hw, _ = _geometry(
        (xs.shape[0], depth, valid_hw[0], valid_hw[1]), mesh, boundary)
    done = 0
    check_every = max(1, int(check_every))
    max_iters = max(1, int(max_iters))
    while done < max_iters:
        n = min(check_every, max_iters - done)
        fn = converge_chunk_fn(mesh, name, n, depth, valid_hw, block_hw,
                               fuse, boundary)
        xs, d = fn(xs)
        done += n
        diff = float(jax.device_get(d))
        yield _crop(xs, valid_hw), done, diff
        if diff < tol:
            return


def volume_converge(state, name: str, *, tol: float, max_iters: int,
                    check_every: int = 10, mesh: Mesh | None = None,
                    boundary: str = "zero", fuse: int = 1):
    """The terminal row of :func:`volume_converge_stream`:
    ``(state, iters, diff)``."""
    out = None
    for out in volume_converge_stream(
            state, name, tol=tol, max_iters=max_iters,
            check_every=check_every, mesh=mesh, boundary=boundary,
            fuse=fuse):
        pass
    return out
