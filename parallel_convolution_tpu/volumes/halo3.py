"""6-face ghost exchange for (F, D, h, w) volume blocks.

The decomposition (DESIGN.md "Volumetric workloads"): the device mesh
stays the 2D ('x', 'y') grid and shards the (H, W) plane; the depth
axis D is RESIDENT — every device holds the full depth column of its
(h, w) tile.  The six ghost faces therefore split into two kinds:

* ±D faces — no neighbor owns them, so they are a **local** pad: zeros
  for the zero boundary (the reference's ghost ring), a wrap
  concatenation for periodic.  No collective moves.
* ±H and ±W faces — exactly rank 2's row/column slabs with one extra
  leading depth extent, exchanged through the SAME
  ``halo.halo_pad_axis`` ppermute machinery (``dim=2`` on axis 'x',
  ``dim=3`` on axis 'y').

Phase order (D pad, then rows, then columns of the already-padded
block) propagates the twelve edge and eight corner ghost regions
without any diagonal messages — the rank-2 two-hop corner argument,
applied once more: the H-phase slabs carry the fresh D ghosts, and the
W-phase slabs carry both.

Runs *inside* ``jax.shard_map``; ``block`` is one device's (F, D, h, w)
float32 tile (F = stacked fields, possibly batch-interleaved).
"""

from __future__ import annotations

import jax.numpy as jnp

from parallel_convolution_tpu.parallel.halo import halo_pad_axis

__all__ = ["volume_halo_exchange"]


def volume_halo_exchange(block: jnp.ndarray, r: int,
                         grid: tuple[int, int],
                         boundary: str = "zero") -> jnp.ndarray:
    """Pad all six faces of a (F, D, h, w) block with r-deep ghosts.

    Returns (F, D+2r, h+2r, w+2r).  ``boundary``: 'zero' or 'periodic'
    (validated against the canonical registry, same error surface as
    rank 2's ``halo_exchange``).
    """
    from parallel_convolution_tpu.utils.config import BOUNDARIES

    if boundary not in BOUNDARIES:
        raise ValueError(
            f"boundary must be one of {BOUNDARIES}, got {boundary!r}")
    if block.ndim != 4:
        raise ValueError(
            f"volume block must be (F, D, h, w), got shape {block.shape}")
    periodic = boundary == "periodic"
    r = int(r)
    R, C = grid
    # Phase 0: the resident depth axis — a local pad, no collective.
    if periodic:
        if block.shape[1] < r:
            raise ValueError(
                f"periodic depth wrap needs D >= ghost depth, got "
                f"D={block.shape[1]} < r={r}")
        p = jnp.concatenate(
            [block[:, block.shape[1] - r:], block, block[:, :r]], axis=1)
    else:
        p = jnp.pad(block, ((0, 0), (r, r), (0, 0), (0, 0)))
    # Phases 1+2: the sharded (H, W) plane — rank 2's slab exchange with
    # one extra leading dim (the slabs now carry the D ghosts, so the
    # D×H / D×W edge regions arrive correct by phase ordering).
    p = halo_pad_axis(p, r, "x", R, dim=2, periodic=periodic)
    return halo_pad_axis(p, r, "y", C, dim=3, periodic=periodic)
