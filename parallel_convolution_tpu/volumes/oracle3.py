"""Independent numpy oracle for the rank-3 subsystem.

Deliberately NOT a port of ``volumes/forms.py``: ghosting goes through
``np.pad`` on the GLOBAL volume (no decomposition, no collectives),
neighbor taps through full-array slicing of the padded cube, and the FD
accumulations run in float64 before rounding back — a different
algorithm and a different arithmetic, so agreement with the sharded
float32 path (tight ``allclose``) is evidence, not tautology.  Byte
identity is only claimed XLA-to-XLA (between registered forms), never
against this oracle.

Used by ``tests/test_volumes.py`` (halo faces vs np.pad slices, one-step
and fused-step equivalence) and ``scripts/volume_smoke.py`` (the seeded
3D Poisson gate).
"""

from __future__ import annotations

import numpy as np

from parallel_convolution_tpu.utils.config import VOLUME_RADII

__all__ = ["oracle_step", "pad_global", "run_oracle"]

_FD_COEFFS = {
    "fd7": (1.0,),
    "fd25": (8.0 / 5.0, -1.0 / 5.0, 8.0 / 315.0, -1.0 / 560.0),
}
_FD_DIAG = {"fd7": 6.0, "fd25": 205.0 / 24.0}
# The _stack twins are the same mathematical operator.
_FD_COEFFS["fd7_stack"] = _FD_COEFFS["fd7"]
_FD_COEFFS["fd25_stack"] = _FD_COEFFS["fd25"]
_FD_DIAG["fd7_stack"] = _FD_DIAG["fd7"]
_FD_DIAG["fd25_stack"] = _FD_DIAG["fd25"]
# The wide star needs damped Jacobi (see forms.FD25_OMEGA); fd7 is plain.
_FD_OMEGA = {"fd25": 0.8, "fd25_stack": 0.8}


def pad_global(vol: np.ndarray, r: int, boundary: str) -> np.ndarray:
    """Ghost-pad a GLOBAL (F, D, H, W) volume by r on all six faces —
    the reference every exchanged block is sliced out of."""
    mode = "wrap" if boundary == "periodic" else "constant"
    return np.pad(vol, ((0, 0), (r, r), (r, r), (r, r)), mode=mode)


def _nbr(p: np.ndarray, r: int, axis: int, k: int) -> np.ndarray:
    """Interior view of padded field ``p`` shifted by ±k along ``axis``
    (1=D, 2=H, 3=W of the (B, D+2r, H+2r, W+2r) cube); k signed."""
    sl = [slice(None)] + [slice(r, s - r) for s in p.shape[1:]]
    sl[axis] = slice(r + k, p.shape[axis] - r + k)
    return p[tuple(sl)]


def _lap7(p: np.ndarray, r: int) -> np.ndarray:
    cc = tuple([slice(None)] + [slice(r, s - r) for s in p.shape[1:]])
    s = np.zeros_like(p[cc], dtype=np.float64)
    for ax in (1, 2, 3):
        for k in (-1, 1):
            s += _nbr(p, r, ax, k)
    return s - 6.0 * p[cc]


def oracle_step(state: np.ndarray, name: str,
                boundary: str = "zero") -> np.ndarray:
    """One global application of rank-3 form ``name`` on a (2, D, H, W)
    — or batched (2B, D, H, W), fields interleaved — float array."""
    from parallel_convolution_tpu.volumes.forms import GS_PARAMS, WAVE_C2DT2

    r = VOLUME_RADII[name]
    a = np.asarray(state, np.float64)
    u, f = a[0::2], a[1::2]
    pu = pad_global(u, r, boundary)
    if name in _FD_COEFFS:
        coeffs, diag = _FD_COEFFS[name], _FD_DIAG[name]
        acc = f.astype(np.float64).copy()
        for k in range(1, r + 1):
            for ax in (1, 2, 3):
                acc += coeffs[k - 1] * (_nbr(pu, r, ax, -k)
                                        + _nbr(pu, r, ax, k))
        u_jac = acc / diag
        om = _FD_OMEGA.get(name)
        out = np.stack(
            [u_jac if om is None else u + om * (u_jac - u), f], axis=1)
    elif name == "wave":
        u_next = 2.0 * u - f + WAVE_C2DT2 * _lap7(pu, r)
        out = np.stack([u_next, u], axis=1)
    elif name == "grayscott":
        du, dv, feed, kill, dt = GS_PARAMS
        pv = pad_global(f, r, boundary)
        uvv = u * f * f
        u_new = u + (du * _lap7(pu, r) - uvv + feed * (1.0 - u)) * dt
        v_new = f + (dv * _lap7(pv, r) + uvv - (feed + kill) * f) * dt
        out = np.stack([u_new, v_new], axis=1)
    else:
        raise ValueError(f"unknown rank-3 form {name!r}")
    return out.reshape(a.shape).astype(np.float32)


def run_oracle(state: np.ndarray, name: str, iters: int,
               boundary: str = "zero") -> np.ndarray:
    """``iters`` sequential global applications (no fusion — fusion must
    not change results, which is exactly what the tests assert)."""
    s = np.asarray(state, np.float32)
    for _ in range(int(iters)):
        s = oracle_step(s, name, boundary)
    return s
