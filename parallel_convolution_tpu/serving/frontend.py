"""Transports for the convolution service: in-process and HTTP/JSON.

One wire format, two transports:

* :class:`InProcessClient` — dict-in/dict-out against a local
  :class:`ConvolutionService`.  Tier-1 tests and ``loadgen --in-process``
  use this: the full request/response codec is exercised with no sockets.
* :func:`make_http_server` — a stdlib-only ``ThreadingHTTPServer``
  speaking the same JSON bodies.  No framework, no dependencies: the
  deployment story stays "python scripts/serve.py".

Wire format (POST ``/v1/convolve``)::

    {"image_b64": <base64 raw u8 bytes>, "rows": H, "cols": W,
     "mode": "grey"|"rgb", "filter": "blur3", "iters": 1,
     "backend": "shifted", "storage": "f32", "fuse": 1,
     "boundary": "zero", "quantize": true, "deadline_ms": 500}

    200 -> {"ok": true, "image_b64": ..., "effective_backend": ...,
            "effective_grid": "RxC", "backend": ..., "request_id": ...,
            "batch_size": ...,
            "phases": {"queue": s, "compile": s, "device": s,
                       "copy_in": s, "copy_out": s, "total": s}}
    400 -> {"ok": false, "rejected": "invalid",    "detail": ...}
    429 -> {"ok": false,
            "rejected": "queue_full"|"deadline"|"error"|"resharding", ...}

``GET /healthz`` returns ``{"ok": true}`` plus the service snapshot
(liveness: the process is up and can report state); ``GET /readyz``
returns the READINESS verdict — 200 only when the service can usefully
take a new request (503 while a mesh reshape is in progress or the
admission queue is at its bound; the current degrade tier rides in the
payload) — the probe surface the ROADMAP-item-2 replica router keys on.
``GET /stats`` returns the snapshot alone; ``POST /v1/warm``
(``{"configs": [...]}``) pre-compiles declared configs — the
warm-placement surface a joining replica is driven through before its
ring vnodes take traffic (round 17); ``GET /metrics`` serves the
process-global obs registry in Prometheus text exposition format 0.0.4
(round 11 — the pull endpoint the stack never had; with ``PCTPU_OBS=0``
it serves a comment noting obs is disabled, still a valid exposition).
Rejections map to HTTP 429 (load shed — retryable by the client) except
contract errors (400).

Tracing (round 13): each request runs under a ``request`` root span
(obs.trace) and every response body carries its ``trace_id``.  Context
propagates IN via the W3C-style ``traceparent`` — an HTTP header on the
POST, or an explicit ``"traceparent"`` body field on the in-process
client — so an upstream caller's trace adopts the serving spans instead
of starting a fresh tree.
"""

from __future__ import annotations

import base64
import json
import struct
import time

import numpy as np

from parallel_convolution_tpu.obs import (
    metrics as obs_metrics, trace as obs_trace,
)
from parallel_convolution_tpu.serving import frames as frames_mod
from parallel_convolution_tpu.serving.service import (
    RETRYABLE_REJECTS, ConvolutionService, Rejected, Request, Response,
    Snapshot,
)

__all__ = ["InProcessClient", "decode_converge", "decode_request",
           "drain_body", "encode_response", "encode_response_frames",
           "encode_stream_row", "encode_stream_row_frames",
           "iter_framed_rows", "make_http_server", "metrics_text",
           "retry_after_header", "send_frames", "send_frames_stream",
           "send_json", "send_ndjson_stream"]

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
NDJSON_CONTENT_TYPE = "application/x-ndjson"

# Framed streaming (the binary twin of NDJSON): each row is one
# length-prefixed envelope — u32 LE byte count, then the envelope —
# flushed per row, so progressive delivery latency matches the NDJSON
# arm (a row is actionable the moment its bytes land).
_ROW_PREFIX = struct.Struct("<I")


def _codec_obs(codec: str, op: str, dt: float, nbytes: int) -> None:
    """Per-wire codec accounting: the observable the crossover curve
    (scripts/wire_ab.py) and capacity math read — how much wall time and
    how many bytes each wire arm's encode/decode actually costs."""
    if not obs_metrics.enabled():
        return
    obs_metrics.counter(
        "pctpu_codec_seconds_total",
        "wall seconds spent encoding/decoding wire payloads",
        ("codec", "op")).inc(dt, codec=codec, op=op)
    obs_metrics.counter(
        "pctpu_codec_bytes_total",
        "payload bytes through each wire codec",
        ("codec", "op")).inc(nbytes, codec=codec, op=op)


def metrics_text() -> str:
    """The /metrics body: one renderer for both transports."""
    if not obs_metrics.enabled():
        return "# PCTPU_OBS disabled\n"
    return obs_metrics.render_text()

# Typed rejection -> HTTP status.  The split encodes "back off" vs "give
# up": retryable sheds (RETRYABLE_REJECTS) are 429 (too many requests:
# queue_full, tenant_quota) or 503 (service transiently unable:
# resharding, replica_unavailable) and carry a Retry-After header;
# contract errors are 400 and terminal execution failures 500 — retrying
# those verbatim cannot succeed.  ``deadline`` stays 429 (the queue was
# too deep for the request's own budget) but is NOT flagged retryable:
# the body's ``retryable`` field, not the status code, is the contract.
_REJECT_STATUS = {"invalid": 400, "queue_full": 429, "deadline": 429,
                  "error": 500, "resharding": 503, "timeout": 504,
                  "tenant_quota": 429, "replica_unavailable": 503,
                  # Round 19 epoch fencing: the request came from a
                  # ZOMBIE router (an epoch older than the fence a
                  # takeover ratcheted).  409 Conflict, retryable:false
                  # — the zombie must stand down, not back off.
                  "stale_epoch": 409,
                  # Round 21 sharded control plane: the request's route
                  # key hashes to a shard this router does not own.  421
                  # Misdirected Request, retryable:true — the client
                  # refreshes its shard map and retries at the owner.
                  "wrong_shard": 421,
                  # A malformed binary envelope/frame (truncation, CRC
                  # mismatch, unknown dtype code): a contract error, the
                  # binary twin of bad-JSON 400.
                  "bad_frame": 400}


def _stale_epoch_wire(body: dict, fence: int, trace_id: str) -> dict:
    """The typed non-retryable rejection a fenced-out request gets."""
    shard = body.get("router_shard")
    where = (f"shard {shard!r}" if shard
             else "this replica set")
    wire = {
        "ok": False, "rejected": "stale_epoch", "retryable": False,
        "request_id": body.get("request_id") or "",
        "fence_epoch": fence, "trace_id": trace_id,
        "detail": f"router epoch {body.get('router_epoch')!r} is stale "
                  f"(fence at {fence}): a newer router has taken over "
                  f"{where}",
    }
    if shard is not None:
        wire["shard"] = str(shard)
    return wire


def retry_after_header(wire: dict) -> str | None:
    """The Retry-After header value for a rejection body (None = no
    header).  HTTP wants integer seconds, so sub-second hints round UP —
    the precise float rides the body's ``retry_after_s`` for clients
    that can do better (scripts/loadgen.py)."""
    if not wire.get("retryable") or wire.get("retry_after_s") is None:
        return None
    import math

    return str(max(1, math.ceil(float(wire["retry_after_s"]))))


def decode_request(body: dict) -> Request:
    """Wire dict → :class:`Request` (raises ValueError on malformed).

    EVERY coercion sits inside the try: a null/listy ``iters`` or
    ``deadline_ms`` raises TypeError, which must surface as the typed
    400, not as an unhandled handler-thread exception (DESIGN.md
    invariant 3: contract violations are typed, decided before enqueue).
    """
    try:
        rows, cols = int(body["rows"]), int(body["cols"])
        mode = body.get("mode", "grey")
        if rows < 1 or cols < 1:
            raise ValueError(f"bad image extent {rows}x{cols}")
        if mode == "volume":
            return _decode_volume_request(body, rows, cols)
        want = (rows, cols, 3) if mode == "rgb" else (rows, cols)
        framed = body.get("_frames") or {}
        if "image" in framed:
            # Binary wire arm: the image arrived as a tensor frame — a
            # zero-copy view over the request buffer (no base64, no
            # bytes copy in the codec).  Geometry/dtype checks mirror
            # the JSON arm exactly so the two wires reject identically.
            img = framed["image"]
            if img.dtype != np.uint8:
                raise ValueError(
                    f"image frame must be uint8, got {img.dtype}")
            if img.shape != want:
                raise ValueError(
                    f"image frame is {img.shape}, expected {want} for "
                    f"{rows}x{cols} {mode}")
        else:
            raw = base64.b64decode(body["image_b64"])
            channels = 3 if mode == "rgb" else 1
            if len(raw) != rows * cols * channels:
                raise ValueError(
                    f"image_b64 carries {len(raw)} bytes, expected "
                    f"{rows * cols * channels} for {rows}x{cols} {mode}")
            img = np.frombuffer(raw, np.uint8).reshape(want)
        deadline_ms = body.get("deadline_ms")
        return Request(
            image=img,
            filter_name=body.get("filter", "blur3"),
            iters=int(body.get("iters", 1)),
            backend=body.get("backend", "shifted"),
            storage=body.get("storage", "f32"),
            # fuse: null means 'tune it' (backend="auto"); absent means 1.
            fuse=(None if body.get("fuse", 1) is None
                  else int(body.get("fuse", 1))),
            boundary=body.get("boundary", "zero"),
            quantize=bool(body.get("quantize", True)),
            # overlap: null/absent = off for explicit backends, tuned
            # for backend="auto"; true/false = clamped request.
            overlap=(None if body.get("overlap") is None
                     else bool(body.get("overlap"))),
            # col_mode: null/absent = auto (cost-model pick on the RDMA
            # tier, canonical 'packed' elsewhere); packed/strided =
            # honored where the transport exists.
            col_mode=(None if body.get("col_mode") is None
                      else str(body.get("col_mode"))),
            deadline_s=(float(deadline_ms) / 1e3
                        if deadline_ms is not None else None),
            request_id=body.get("request_id"),
            tenant=str(body.get("tenant") or ""),
            # solver: convergence strategy (converge jobs; the batch path
            # sheds non-jacobi as invalid server-side).
            solver=str(body.get("solver") or "jacobi"),
            mg_levels=(None if body.get("mg_levels") is None
                       else int(body["mg_levels"])),
        )
    except (KeyError, TypeError, ValueError) as e:
        raise ValueError(f"malformed request body: {e}") from e


def _decode_volume_request(body: dict, rows: int, cols: int) -> Request:
    """The rank-3 arm of :func:`decode_request` (``mode: "volume"``):
    the body carries ``depth`` plus a (2, D, H, W) float32 volume — as
    a ``volume`` tensor frame on the binary wire (the r20 envelope's
    4-dim f32 frames carry it untouched) or ``volume_b64`` raw f32
    bytes on JSON.  Raises the same typed ValueError family as the
    rank-2 arm; the caller's except wraps it."""
    from parallel_convolution_tpu.utils.config import VOLUME_FIELDS

    depth = int(body["depth"])
    if depth < 1:
        raise ValueError(f"bad volume depth {depth}")
    want = (VOLUME_FIELDS, depth, rows, cols)
    framed = body.get("_frames") or {}
    if "volume" in framed:
        vol = framed["volume"]
        if vol.dtype != np.float32:
            raise ValueError(
                f"volume frame must be float32, got {vol.dtype}")
        if vol.shape != want:
            raise ValueError(
                f"volume frame is {vol.shape}, expected {want} for "
                f"depth={depth} {rows}x{cols}")
    else:
        raw = base64.b64decode(body["volume_b64"])
        n = int(np.prod(want)) * 4
        if len(raw) != n:
            raise ValueError(
                f"volume_b64 carries {len(raw)} bytes, expected {n} "
                f"for f32 {want}")
        vol = np.frombuffer(raw, np.float32).reshape(want)
    deadline_ms = body.get("deadline_ms")
    return Request(
        volume=vol,
        filter_name=body.get("filter", "fd7"),
        iters=int(body.get("iters", 1)),
        backend=body.get("backend", "shifted"),
        storage="f32",
        fuse=(None if body.get("fuse", 1) is None
              else int(body.get("fuse", 1))),
        boundary=body.get("boundary", "zero"),
        quantize=False,
        overlap=(None if body.get("overlap") is None
                 else bool(body.get("overlap"))),
        col_mode=(None if body.get("col_mode") is None
                  else str(body.get("col_mode"))),
        deadline_s=(float(deadline_ms) / 1e3
                    if deadline_ms is not None else None),
        request_id=body.get("request_id"),
        tenant=str(body.get("tenant") or ""),
        solver=str(body.get("solver") or "jacobi"),
    )


def _response_parts(result) -> tuple[int, dict, dict]:
    """:class:`Response`/:class:`Rejected` → (status, control header,
    tensor fields) — the wire-agnostic split both encoders share, so the
    JSON and frames arms cannot drift on anything but tensor carriage."""
    if isinstance(result, Rejected):
        wire = {
            "ok": False, "rejected": result.reason,
            "retryable": result.reason in RETRYABLE_REJECTS,
            "request_id": result.request_id, "detail": result.detail,
            "trace_id": result.trace_id,
        }
        if wire["retryable"] and result.retry_after_s is not None:
            wire["retry_after_s"] = round(float(result.retry_after_s), 4)
        return _REJECT_STATUS.get(result.reason, 429), wire, {}
    assert isinstance(result, Response)
    return 200, {
        "ok": True,
        "effective_backend": result.effective_backend,
        "effective_grid": result.effective_grid,
        "backend": result.backend,
        "plan_source": result.plan_source,
        "plan_key": result.plan_key,
        "predicted_gpx_per_chip": result.predicted_gpx_per_chip,
        "overlap": result.overlap,
        "col_mode": result.col_mode,
        "exchange_fraction": result.exchange_fraction,
        "exchange_hidden_fraction": result.exchange_hidden_fraction,
        "request_id": result.request_id,
        "batch_size": result.batch_size,
        "phases": result.phases,
        "trace_id": result.trace_id,
        # Content-addressed result cache verdict (hit|miss|off) + the
        # input digest — every wire body carries them, so loadgen rows,
        # the router's hit-refund settlement, and the cache_smoke gates
        # all read the same stamp.
        "cache": result.cache,
        "digest": result.digest,
    }, {"image": result.image}


def encode_response(result) -> tuple[int, dict]:
    """:class:`Response`/:class:`Rejected` → (http_status, wire dict)."""
    status, wire, tensors = _response_parts(result)
    wire["wire"] = "json"
    if "image" in tensors:
        t0 = time.perf_counter()
        wire["image_b64"] = base64.b64encode(
            np.ascontiguousarray(tensors["image"]).tobytes()).decode("ascii")
        _codec_obs("json", "encode", time.perf_counter() - t0,
                   tensors["image"].nbytes)
    return status, wire


def encode_response_frames(result) -> tuple[int, bytes]:
    """The binary twin of :func:`encode_response`: (http_status,
    envelope bytes).  Control fields ride the envelope's JSON header
    (``wire: "frames"`` stamped; retry hints included — framed clients
    read the header, not HTTP headers); the image rides as a tensor
    frame.  Rejections are header-only envelopes."""
    status, wire, tensors = _response_parts(result)
    wire["wire"] = "frames"
    t0 = time.perf_counter()
    data = frames_mod.encode_envelope(wire, tensors)
    _codec_obs("frames", "encode", time.perf_counter() - t0,
               sum(a.nbytes for a in tensors.values()))
    return status, data


def decode_converge(body: dict) -> tuple[Request, dict]:
    """Wire dict → (:class:`Request`, converge params) for
    ``POST /v1/converge`` (raises ValueError on malformed).

    Same body as ``/v1/convolve`` minus ``iters``/``deadline_ms`` plus
    ``tol`` / ``max_iters`` / ``check_every``; ``quantize`` defaults to
    FALSE here (convergence runs float carries — the u8 store-back
    semantics would clamp the diff trajectory).

    Round 18 (durable jobs): ``resume_state: true`` asks every snapshot
    row to carry its own resume token (``state_b64``/``state_shape`` —
    the exact f32 carries, since the u8 image is lossy), and ``resume``
    (a token dict: iters / diff / work_units / state_b64 / state_shape)
    seeds the stream from that token instead of iteration 0 — the
    mid-stream failover surface ``router.converge`` drives."""
    try:
        params = {"tol": float(body.get("tol", 1e-3)),
                  "max_iters": int(body.get("max_iters", 500)),
                  "check_every": int(body.get("check_every", 10)),
                  "carry_state": bool(body.get("resume_state", False))}
        token = body.get("resume")
        if token is not None:
            from parallel_convolution_tpu.serving import jobs

            if not isinstance(token, dict):
                raise ValueError("resume must be a token object")
            framed_state = (body.get("_frames") or {}).get("resume_state")
            if framed_state is not None:
                # state_b64's framed twin: the f32 carries arrive as a
                # tensor frame; same shape/dtype contract, no base64.
                state = np.asarray(framed_state)
                if state.ndim not in (3, 4) or state.dtype != np.float32:
                    raise ValueError(
                        f"resume_state frame must be float32 (C, H, W) "
                        f"or rank-3 (F, D, H, W), got {state.dtype} "
                        f"{state.shape}")
                state = np.ascontiguousarray(state)
            else:
                state = jobs.state_from_wire(
                    token.get("state_b64") or "",
                    token.get("state_shape") or ())
            params["resume"] = {
                "iters": int(token.get("iters", 0)),
                "diff": float(token.get("diff", float("inf"))),
                "work_units": float(token.get("work_units", 0.0)),
                "state": state,
            }
    except (TypeError, ValueError) as e:
        raise ValueError(f"malformed request body: {e}") from e
    b = dict(body)
    b.setdefault("quantize", False)
    b.pop("deadline_ms", None)   # chunk streaming IS the deadline story
    b["iters"] = 1               # keying uses check_every (service-side)
    return decode_request(b), params


def _stream_row_parts(row) -> tuple[dict, dict]:
    """Stream row → (control header, tensor fields): the shared split
    behind the NDJSON and framed stream encoders."""
    if isinstance(row, Rejected):
        _, wire, _ = _response_parts(row)
        wire["kind"] = "rejected"
        return wire, {}
    assert isinstance(row, Snapshot)
    out = {
        "kind": "final" if row.final else "snapshot",
        "ok": True,
        "iters": row.iters,
        "diff": round(float(row.diff), 8),
        "converged": row.converged,
        # Solver-shaped accounting (round 15): which convergence
        # strategy produced the row (iters counts V-cycles for
        # multigrid, diff is then the fine-grid residual norm) and the
        # solver-comparable fine-grid work spent so far.
        "solver": row.solver,
        "work_units": round(float(row.work_units), 3),
        "mg_levels": row.mg_levels,
        "col_mode": row.col_mode,
        "request_id": row.request_id,
        "effective_backend": row.effective_backend,
        "effective_grid": row.effective_grid,
        "plan_key": row.plan_key,
        "trace_id": row.trace_id,
        "cache": row.cache,
        "digest": row.digest,
    }
    tensors = {"image": row.image}
    if row.state is not None:
        # The resume-token payload (round 18): exact f32 carries, only
        # when the job asked for durability (resume_state on the wire).
        tensors["state"] = row.state
    return out, tensors


def encode_stream_row(row) -> dict:
    """:class:`Snapshot`/:class:`Rejected` → one NDJSON stream line."""
    out, tensors = _stream_row_parts(row)
    out["wire"] = "json"
    if "image" in tensors:
        t0 = time.perf_counter()
        # Geometry rides the row so an edge re-framing the stream into
        # tensor frames (the router's framed converge) needs no
        # request-side context.
        out["image_shape"] = list(tensors["image"].shape)
        out["image_b64"] = base64.b64encode(
            np.ascontiguousarray(tensors["image"]).tobytes()).decode("ascii")
        if "state" in tensors:
            from parallel_convolution_tpu.serving import jobs

            out["state_b64"], out["state_shape"] = jobs.state_to_wire(
                tensors["state"])
        _codec_obs("json", "encode", time.perf_counter() - t0,
                   sum(a.nbytes for a in tensors.values()))
    return out


def encode_stream_row_frames(row) -> bytes:
    """The binary twin of :func:`encode_stream_row`: one envelope per
    stream row.  The image rides as a u8 frame; when the job asked for
    durability, ``state_b64``'s framed twin ``state`` rides as an f32
    frame (``state_shape`` is the frame's own shape header)."""
    out, tensors = _stream_row_parts(row)
    out["wire"] = "frames"
    t0 = time.perf_counter()
    data = frames_mod.encode_envelope(out, tensors)
    _codec_obs("frames", "encode", time.perf_counter() - t0,
               sum(a.nbytes for a in tensors.values()))
    return data


def drain_body(handler) -> None:
    """Consume an unread POST body on a ``BaseHTTPRequestHandler``.

    Under HTTP/1.1 keep-alive (which /v1/converge's chunked streaming
    requires) a response sent with the request body still unread leaves
    those bytes in the socket — the server would parse them as the next
    request line.  Shared by the replica and router frontends."""
    try:
        n = int(handler.headers.get("Content-Length", "0") or 0)
    except ValueError:
        n = 0
    while n > 0:
        chunk = handler.rfile.read(min(n, 65536))
        if not chunk:
            break
        n -= len(chunk)


def send_json(handler, status: int, payload: dict) -> None:
    """One JSON response on a ``BaseHTTPRequestHandler``: Content-Length
    framing plus the Retry-After header for retryable rejection bodies.
    Shared by the replica frontend and the router frontend so the two
    cannot drift ("a client cannot tell a router from a replica")."""
    data = json.dumps(payload).encode()
    handler.send_response(status)
    handler.send_header("Content-Type", "application/json")
    handler.send_header("Content-Length", str(len(data)))
    ra = retry_after_header(payload)
    if ra is not None:
        handler.send_header("Retry-After", ra)
    handler.end_headers()
    handler.wfile.write(data)


def send_ndjson_stream(handler, rows) -> None:
    """Chunked NDJSON on a ``BaseHTTPRequestHandler``: one line per
    stream row, flushed as produced — the progressive-results
    transport.  The terminal chunk is best-effort: a client that
    disconnected mid-stream must not raise again out of the finally."""
    handler.send_response(200)
    handler.send_header("Content-Type", NDJSON_CONTENT_TYPE)
    handler.send_header("Transfer-Encoding", "chunked")
    handler.end_headers()
    try:
        for row in rows:
            data = (json.dumps(row) + "\n").encode()
            handler.wfile.write(b"%x\r\n" % len(data))
            handler.wfile.write(data + b"\r\n")
            handler.wfile.flush()
    finally:
        try:
            handler.wfile.write(b"0\r\n\r\n")
        except OSError:
            pass


def send_frames(handler, status: int, data: bytes) -> None:
    """One framed response body (Content-Length framing).  No
    Retry-After header: framed clients read retry hints from the
    envelope header JSON, which always carries them."""
    handler.send_response(status)
    handler.send_header("Content-Type", frames_mod.FRAMES_CONTENT_TYPE)
    handler.send_header("Content-Length", str(len(data)))
    handler.end_headers()
    handler.wfile.write(data)


def send_frames_stream(handler, rows) -> None:
    """Chunked framed streaming: one length-prefixed envelope per row,
    FLUSHED per row exactly like the NDJSON arm — progressive delivery
    latency is a property of the stream, not of the negotiated wire
    (a buffered framed stream would un-ship the progressive story for
    binary clients).  ``rows`` yields envelope ``bytes``."""
    handler.send_response(200)
    handler.send_header("Content-Type", frames_mod.FRAMES_CONTENT_TYPE)
    handler.send_header("Transfer-Encoding", "chunked")
    handler.end_headers()
    try:
        for data in rows:
            line = _ROW_PREFIX.pack(len(data)) + data
            handler.wfile.write(b"%x\r\n" % len(line))
            handler.wfile.write(line + b"\r\n")
            handler.wfile.flush()
    finally:
        try:
            handler.wfile.write(b"0\r\n\r\n")
        except OSError:
            pass


def iter_framed_rows(stream) -> "object":
    """Parse a framed row stream (client side): yields envelope bytes
    per length-prefixed row from a file-like ``stream``.  Raises
    :class:`frames.BadFrame` on a truncated prefix/row."""
    while True:
        prefix = stream.read(_ROW_PREFIX.size)
        if not prefix:
            return
        if len(prefix) < _ROW_PREFIX.size:
            raise frames_mod.BadFrame("truncated stream row prefix")
        (n,) = _ROW_PREFIX.unpack(prefix)
        data = b""
        while len(data) < n:
            chunk = stream.read(n - len(data))
            if not chunk:
                raise frames_mod.BadFrame(
                    f"truncated stream row: {len(data)}/{n} bytes")
            data += chunk
        yield data


class InProcessClient:
    """The socket-free transport: same codec, direct service calls."""

    def __init__(self, service: ConvolutionService):
        self.service = service

    def request(self, body: dict, timeout: float | None = None,
                traceparent: str | None = None,
                transport: str = "in_process",
                wire: str = "json") -> tuple[int, dict]:
        """One wire-format request → (status, wire-format response).

        The request runs under a ``request`` root span; ``traceparent``
        (the explicit argument, or a ``"traceparent"`` body field) makes
        it a CHILD of the caller's span instead — the in-process twin of
        the HTTP header.  Every response dict carries ``trace_id``
        ("" with obs disabled).  ``transport`` labels the root span —
        the HTTP handler delegates here and passes ``"http"``.

        ``wire`` is the NEGOTIATED response encoding: ``"json"``
        returns a dict; ``"frames"`` returns envelope ``bytes``
        (:func:`request_frames` is the full binary round trip).  A body
        carrying decoded tensor frames (``_frames``) is accepted either
        way — request and response wires negotiate independently in
        principle, though the transports pair them.
        """
        tp = traceparent if traceparent is not None else body.get(
            "traceparent")
        pctx = obs_trace.parse_traceparent(tp)
        with obs_trace.span(
                "request", parent=pctx, transport=transport, wire=wire,
                request_id=str(body.get("request_id") or ""),
                # The parent span (if any) lives in the CALLER's process:
                # reconstruction must treat this span as a local root, not
                # an orphan, when the parent is absent from the log.
                **({"remote_parent": True} if pctx is not None
                   else {})) as sp:
            tid = sp.context.trace_id if sp.context is not None else ""
            admit, fence = self.service.epoch_gate(
                body.get("router_epoch"), shard=body.get("router_shard"))
            if not admit:
                sp.set(outcome="stale_epoch")
                stale = _stale_epoch_wire(body, fence, tid)
                if wire == "frames":
                    return 409, frames_mod.encode_envelope(stale, {})
                return 409, stale
            try:
                req = decode_request(body)
            except ValueError as e:
                sp.set(outcome="invalid")
                bad = {"ok": False, "rejected": "invalid",
                       "request_id": body.get("request_id") or "",
                       "detail": str(e), "trace_id": tid}
                if wire == "frames":
                    return 400, frames_mod.encode_envelope(bad, {})
                return 400, bad
            result = self.service.submit(req, timeout=timeout)
            if wire == "frames":
                status, data = encode_response_frames(result)
                sp.set(status=status)
                return status, data
            status, wired = encode_response(result)
            if not wired.get("trace_id"):
                wired["trace_id"] = tid
            sp.set(status=status)
            return status, wired

    def request_frames(self, raw, timeout: float | None = None,
                       traceparent: str | None = None,
                       transport: str = "in_process",
                       tenant: str | None = None) -> tuple[int, bytes]:
        """The full binary round trip: envelope bytes in, envelope bytes
        out.  A malformed envelope is the typed ``bad_frame`` 400 —
        returned as a header-only envelope, so a frames client never has
        to switch codecs to read its own rejection."""
        t0 = time.perf_counter()
        try:
            header, arrays = frames_mod.decode_envelope(raw)
        except frames_mod.BadFrame as e:
            status, data = encode_response_frames(
                Rejected("bad_frame", "", detail=str(e)))
            return status, data
        _codec_obs("frames", "decode", time.perf_counter() - t0,
                   sum(a.nbytes for a in arrays.values()))
        header["_frames"] = arrays
        if tenant:
            header["tenant"] = tenant
        return self.request(header, timeout=timeout,
                            traceparent=traceparent, transport=transport,
                            wire="frames")

    def converge(self, body: dict, timeout: float | None = None,
                 traceparent: str | None = None,
                 transport: str = "in_process", wire: str = "json"):
        """One progressive convergence request → (status, row iterator).

        An immediate rejection returns its status with a one-row
        iterator; an admitted job returns ``(200, rows)`` where ``rows``
        yields NDJSON-shaped dicts (``kind: snapshot`` per chunk, then
        ``kind: final`` — or ``kind: rejected`` if the job died
        mid-stream, after the best-so-far rows).  The HTTP transport
        streams exactly these lines chunked.  With ``wire="frames"``
        every row (rejections included) is envelope ``bytes`` instead.
        """
        tp = traceparent if traceparent is not None else body.get(
            "traceparent")
        pctx = obs_trace.parse_traceparent(tp)

        def row_out(d: dict):
            d["wire"] = wire
            return (frames_mod.encode_envelope(d, {})
                    if wire == "frames" else d)

        with obs_trace.span(
                "request", parent=pctx, transport=transport, wire=wire,
                progressive=True,
                request_id=str(body.get("request_id") or ""),
                **({"remote_parent": True} if pctx is not None
                   else {})) as sp:
            tid = sp.context.trace_id if sp.context is not None else ""
            admit, fence = self.service.epoch_gate(
                body.get("router_epoch"), shard=body.get("router_shard"))
            if not admit:
                sp.set(outcome="stale_epoch")
                stale = _stale_epoch_wire(body, fence, tid)
                stale["kind"] = "rejected"
                return 409, iter([row_out(stale)])
            try:
                req, params = decode_converge(body)
            except ValueError as e:
                sp.set(outcome="invalid")
                return 400, iter([row_out({
                    "kind": "rejected", "ok": False, "rejected": "invalid",
                    "retryable": False,
                    "request_id": body.get("request_id") or "",
                    "detail": str(e), "trace_id": tid})])
            result = self.service.submit_progressive(req, **params)
            if isinstance(result, Rejected):
                status, wired = encode_response(result)
                wired.pop("wire", None)
                wired["kind"] = "rejected"
                if not wired.get("trace_id"):
                    wired["trace_id"] = tid
                sp.set(outcome=result.reason)
                return status, iter([row_out(wired)])
            sp.set(status=200)
        if wire == "frames":
            return 200, (encode_stream_row_frames(row) for row in result)
        return 200, (encode_stream_row(row) for row in result)

    def converge_frames(self, raw, timeout: float | None = None,
                        traceparent: str | None = None,
                        transport: str = "in_process",
                        tenant: str | None = None):
        """Binary converge: envelope bytes in → (status, iterator of
        envelope-bytes rows).  The framed twin of :meth:`converge`."""
        t0 = time.perf_counter()
        try:
            header, arrays = frames_mod.decode_envelope(raw)
        except frames_mod.BadFrame as e:
            status, data = encode_response_frames(
                Rejected("bad_frame", "", detail=str(e)))
            return status, iter([data])
        _codec_obs("frames", "decode", time.perf_counter() - t0,
                   sum(a.nbytes for a in arrays.values()))
        header["_frames"] = arrays
        if tenant:
            header["tenant"] = tenant
        return self.converge(header, timeout=timeout,
                             traceparent=traceparent, transport=transport,
                             wire="frames")

    def warm(self, configs) -> tuple[int, dict]:
        """Pre-compile declared configs (the warm-placement surface: a
        JOINING replica inherits its ring shard's executables BEFORE
        taking traffic).  ``configs`` are the ``service.warmup`` dicts;
        a bad config is a typed 400, never a half-warmed crash."""
        try:
            effective = self.service.warmup(list(configs or ()))
        except Exception as e:  # noqa: BLE001 — typed contract errors
            return 400, {"ok": False, "rejected": "invalid",
                         "detail": f"warmup failed: {e}"[:300]}
        return 200, {"ok": True, "warmed": len(effective),
                     "effective_backends": effective}

    def fence(self, epoch, shard=None) -> tuple[int, dict]:
        """Ratchet the router-epoch fence (``POST /v1/fence`` twin) —
        the explicit propagation call a taking-over router makes so a
        zombie is rejected EVERYWHERE at once, not just on replicas the
        new router happened to talk to first.  ``shard`` scopes the
        sweep to one lineage's ratchet (round 21): fencing shard A's
        zombie must not reject the same process's live shard-B owner."""
        try:
            e = int(epoch)
        except (TypeError, ValueError):
            return 400, {"ok": False, "rejected": "invalid",
                         "detail": f"bad fence epoch {epoch!r}"}
        s = None if shard is None else str(shard)
        out = {"ok": True,
               "fence_epoch": self.service.fence(e, shard=s)}
        if s is not None:
            out["shard"] = s
        return 200, out

    def healthz(self) -> tuple[int, dict]:
        return 200, {"ok": True, **self.service.snapshot()}

    def readyz(self) -> tuple[int, dict]:
        """Socket-free readiness twin: (200|503, verdict payload)."""
        ready, payload = self.service.readiness()
        return (200 if ready else 503), {"ok": ready, **payload}

    def stats(self) -> tuple[int, dict]:
        return 200, self.service.snapshot()

    def metrics(self) -> tuple[int, str]:
        """The Prometheus text exposition (socket-free surface)."""
        return 200, metrics_text()


def make_http_server(service: ConvolutionService, host: str = "127.0.0.1",
                     port: int = 8080):
    """A ``ThreadingHTTPServer`` bound to (host, port); ``port=0`` picks a
    free one (``server.server_address[1]`` reports it).  The caller runs
    ``serve_forever()`` / ``shutdown()``; handler threads block inside
    ``service.submit`` while the single batcher worker drives the mesh.
    """
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    client = InProcessClient(service)

    class Handler(BaseHTTPRequestHandler):
        # HTTP/1.1 so /v1/converge can stream with chunked
        # transfer-encoding; every non-stream response still carries
        # Content-Length (keep-alive stays correct).
        protocol_version = "HTTP/1.1"

        # Quiet by default: per-request lines go through log_message,
        # which a server script may re-point at its own logger.
        def log_message(self, fmt, *args):  # noqa: D102
            pass

        def _send(self, status: int, payload: dict) -> None:
            send_json(self, status, payload)

        def _send_stream(self, rows) -> None:
            send_ndjson_stream(self, rows)

        def do_GET(self):  # noqa: N802 — http.server API
            if self.path == "/healthz":
                self._send(*client.healthz())
            elif self.path == "/readyz":
                self._send(*client.readyz())
            elif self.path == "/stats":
                self._send(*client.stats())
            elif self.path == "/metrics":
                data = metrics_text().encode()
                self.send_response(200)
                self.send_header("Content-Type", PROM_CONTENT_TYPE)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
            else:
                self._send(404, {"ok": False, "detail": "unknown path"})

        def do_POST(self):  # noqa: N802 — http.server API
            if self.path not in ("/v1/convolve", "/v1/converge",
                                 "/v1/warm", "/v1/fence"):
                # Drain the body first: under HTTP/1.1 keep-alive an
                # unread body would be parsed as the NEXT request line.
                drain_body(self)
                self._send(404, {"ok": False, "detail": "unknown path"})
                return
            ctype = (self.headers.get("Content-Type") or "").split(
                ";")[0].strip().lower()
            if (ctype == frames_mod.FRAMES_CONTENT_TYPE
                    and self.path in ("/v1/convolve", "/v1/converge")):
                # Negotiated binary wire: the raw body IS the envelope;
                # the response comes back framed too.  Decode (and the
                # one CRC walk) happens in the client surface — a
                # malformed envelope is its typed bad_frame 400.
                n = int(self.headers.get("Content-Length", "0") or 0)
                raw = self.rfile.read(n)
                tp = self.headers.get("traceparent")
                ten = self.headers.get("x-tenant")
                if self.path == "/v1/convolve":
                    status, data = client.request_frames(
                        raw, traceparent=tp, transport="http", tenant=ten)
                    send_frames(self, status, data)
                else:
                    status, rows = client.converge_frames(
                        raw, traceparent=tp, transport="http", tenant=ten)
                    if status != 200:
                        send_frames(self, status, next(iter(rows)))
                    else:
                        send_frames_stream(self, rows)
                return
            try:
                n = int(self.headers.get("Content-Length", "0"))
                body = json.loads(self.rfile.read(n) or b"{}")
                if not isinstance(body, dict):
                    raise ValueError("body must be a JSON object")
            except (ValueError, json.JSONDecodeError) as e:
                self._send(400, {"ok": False, "rejected": "invalid",
                                 "detail": f"bad JSON body: {e}"})
                return
            if self.path == "/v1/warm":
                self._send(*client.warm(body.get("configs") or []))
                return
            if self.path == "/v1/fence":
                self._send(*client.fence(body.get("epoch"),
                                         shard=body.get("shard")))
                return
            # Tenant identity: the transport header wins over the body
            # field (the router's QoS key rides either).
            tenant = self.headers.get("x-tenant")
            if tenant:
                body["tenant"] = tenant
            if self.path == "/v1/converge":
                status, rows = client.converge(
                    body, traceparent=self.headers.get("traceparent"),
                    transport="http")
                if status != 200:
                    self._send(status, next(iter(rows)))
                else:
                    self._send_stream(rows)
                return
            # W3C-style trace propagation: the transport header wins
            # over any body field (the HTTP twin of the in-process
            # client's explicit argument).
            self._send(*client.request(
                body, traceparent=self.headers.get("traceparent"),
                transport="http"))

    return ThreadingHTTPServer((host, port), Handler)
