"""Admission-controlled convolution service over the warm engine.

The request/response surface of the serving layer: validation, admission
control (bounded queue depth + per-request deadlines + typed
load-shedding), micro-batched execution, and the resilience wiring —
transient failures retry via ``resilience.retry.with_retry`` and compile
faults walk the ``resilience.degrade`` backend ladder per key (inside
the engine).  Every successful response is stamped with the
``effective_backend`` that actually produced its bytes, continuing the
round-7 rule that a degraded tier can never masquerade as the requested
one in any artifact.

Results are TYPED, never exceptions across the service boundary:

* :class:`Response`  — the filtered image + per-request latency phases
  (queue / compile / device / copy, from ``utils.tracing.PhaseTimer``).
* :class:`Rejected`  — load shedding (``queue_full``), missed deadlines
  (``deadline``), contract errors (``invalid``), and exhausted/terminal
  execution failures (``error``).  A queue overflow yields a
  ``Rejected``, not an exception and not a hang — asserted in tier-1.
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
import time

import numpy as np

from parallel_convolution_tpu.obs import (
    events as obs_events, metrics as obs_metrics, trace as obs_trace,
)
from parallel_convolution_tpu.serving.batcher import MicroBatcher
from parallel_convolution_tpu.serving.engine import EngineKey, WarmEngine
from parallel_convolution_tpu.utils.tracing import PhaseTimer

__all__ = ["ConvolutionService", "Rejected", "Request", "Response"]


@dataclasses.dataclass
class Request:
    """One filtering request: an interleaved u8 image + run knobs.

    ``image`` is (H, W) grey or (H, W, 3) RGB uint8 — the reference CLI's
    image contract.  ``deadline_s`` is a relative latency budget; a
    request still queued past it is shed with ``Rejected("deadline")``
    rather than served late.
    """

    image: np.ndarray
    filter_name: str = "blur3"
    iters: int = 1
    backend: str = "shifted"         # or "auto": plan-cache/cost-model
    #                                  resolved (engine.key_for)
    storage: str = "f32"
    fuse: int | None = 1             # None = tune it (backend="auto")
    boundary: str = "zero"
    quantize: bool = True
    overlap: bool | None = None      # interior-first overlapped halo
    #                                  pipeline: None = off for explicit
    #                                  backends / tuned for "auto"; the
    #                                  RESOLVED value rides the key and
    #                                  every response stamps it
    deadline_s: float | None = None
    request_id: str | None = None


@dataclasses.dataclass
class Response:
    """A served result; ``phases`` is the per-request latency breakdown
    in seconds (queue, compile, device, copy_in, copy_out, total)."""

    image: np.ndarray                # uint8, same layout as the request
    effective_backend: str
    backend: str                     # as requested
    request_id: str
    batch_size: int                  # how many requests shared the program
    phases: dict
    plan_source: str = "explicit"    # explicit|measured|interpolated|
    #                                  predicted (auto-resolution origin)
    predicted_gpx_per_chip: float | None = None  # cost-model figure for
    #                                  the served config (vs measured)
    effective_grid: str = ""         # "RxC" mesh grid that produced the
    #                                  bytes (changes after an elastic
    #                                  reshape mid-process)
    overlap: bool = False            # the compiled program's RESOLVED
    #                                  overlap knob (False when clamped
    #                                  or degraded off the RDMA tier)
    exchange_fraction: float = 0.0   # model-attributed EXPOSED exchange
    #                                  share of one iteration's wall
    exchange_hidden_fraction: float = 0.0  # share of exchange time the
    #                                  overlapped pipeline hides under
    #                                  compute (0.0 when serialized)
    trace_id: str = ""               # the request's causal trace id
    #                                  (obs.trace; "" with PCTPU_OBS=0)
    plan_key: str = ""               # tuning canonical key of the served
    #                                  config — the perf_gate.py history
    #                                  key and the drift-series label

    ok = True


@dataclasses.dataclass
class Rejected:
    """A typed non-result: load shed, deadline miss, or failed execution."""

    reason: str   # queue_full | deadline | invalid | error | resharding
    request_id: str
    detail: str = ""
    trace_id: str = ""   # the request's causal trace id (when admitted
    #                      under an active trace; "" otherwise)

    ok = False


class ConvolutionService:
    """Micro-batched, admission-controlled serving of the stencil stack.

    ``retry_policy`` governs ``with_retry`` around batch execution:
    classified-transient failures (tunnel blips, injected faults, Mosaic
    INTERNAL crashes) are retried with deterministic backoff; terminal
    failures and exhausted retries become ``Rejected("error")`` for every
    request in the batch.  ``fallback`` (default True) lets the engine
    walk the degradation ladder per key on transient compile faults.
    """

    def __init__(self, mesh=None, *, capacity: int = 16,
                 max_batch: int = 8, max_delay_s: float = 0.005,
                 max_queue: int = 64, fallback: bool = True,
                 retry_policy=None, start: bool = True, plans=None):
        from parallel_convolution_tpu.resilience.retry import RetryPolicy

        self.engine = WarmEngine(mesh, capacity=capacity, fallback=fallback,
                                 plans=plans)
        self.retry_policy = retry_policy or RetryPolicy(
            max_attempts=3, base_delay=0.05, max_delay=2.0)
        self.batcher = MicroBatcher(
            self._execute_batch, max_batch=max_batch,
            max_delay_s=max_delay_s, max_queue=max_queue, start=start)
        self._ids = itertools.count(1)
        self._lock = threading.Lock()
        self._reshape_lock = threading.Lock()
        self._reshaping = False
        # The legacy stats dict, now a view over the obs registry: every
        # write mirrors into pctpu_service_stats{key=...} (obs.metrics),
        # so the admission-control ledger is one /metrics scrape away.
        self.stats = obs_metrics.MirroredStats(obs_metrics.gauge(
            "pctpu_service_stats", "service admission/completion counters",
            ("key",)), initial={
            "submitted": 0, "completed": 0, "retries": 0,
            "rejected_queue_full": 0, "rejected_deadline": 0,
            "rejected_invalid": 0, "rejected_error": 0,
            "rejected_resharding": 0, "client_timeouts": 0,
            "reshapes": 0,
        })

    # -- admission -----------------------------------------------------------
    def _bump(self, counter: str, n: int = 1) -> None:
        with self._lock:
            self.stats[counter] += n

    def _shed(self, reason: str, rid: str, detail: str = "",
              counter: str | None = None, n: int = 1,
              trace=None) -> Rejected:
        """One path for every typed rejection: the legacy counter bump,
        the admission event, and the Rejected value.  ``trace`` is the
        request's :class:`obs.trace.SpanContext` when it was admitted
        under an active trace — the rejection then joins the tree."""
        if counter is not None:
            self._bump(counter, n)
        if obs_metrics.enabled():
            obs_metrics.counter(
                "pctpu_admission_total",
                "typed request outcomes at the admission boundary",
                ("outcome",)).inc(n, outcome=reason)
            obs_events.emit(
                "admission", outcome=reason, request_id=rid,
                detail=detail[:200],
                **({"trace_id": trace.trace_id} if trace is not None
                   else {}))
        return Rejected(reason, rid, detail=detail,
                        trace_id=trace.trace_id if trace is not None else "")

    def _validate(self, req: Request) -> tuple[EngineKey, str, np.ndarray]:
        """Terminal ValueError on any contract violation (→ ``invalid``).

        Returns ``(key, plan_source, planar)`` — provenance is
        per-REQUEST (an auto and an explicit request can share a key)."""
        from parallel_convolution_tpu.ops.filters import get_filter
        from parallel_convolution_tpu.utils import imageio

        img = np.asarray(req.image)
        if img.dtype != np.uint8 or img.ndim not in (2, 3) or (
                img.ndim == 3 and img.shape[-1] != 3):
            raise ValueError(
                f"image must be uint8 (H, W) or (H, W, 3), got "
                f"{img.dtype} {img.shape}")
        planar = imageio.interleaved_to_planar(img).astype(np.float32)
        key, plan_source = self.engine.resolve_key(
            planar.shape, filter_name=req.filter_name, storage=req.storage,
            iters=int(req.iters),
            fuse=None if req.fuse is None else int(req.fuse),
            boundary=req.boundary,
            quantize=bool(req.quantize), backend=req.backend,
            overlap=req.overlap)
        key.validate()
        filt = get_filter(key.filter_name)
        R, C = key.grid
        if (min(-(-planar.shape[1] // R), -(-planar.shape[2] // C))
                < filt.radius * key.fuse):
            raise ValueError(
                f"per-device block smaller than radius*fuse "
                f"({filt.radius}*{key.fuse}) for image "
                f"{planar.shape[1:]} on grid {key.grid}")
        if key.boundary == "periodic" and (
                planar.shape[1] % R or planar.shape[2] % C):
            raise ValueError(
                "periodic boundary requires grid-divisible dimensions")
        return key, plan_source, planar

    def submit(self, req: Request, wait: bool = True,
               timeout: float | None = None):
        """Admit + (optionally) await one request.

        ``wait=True`` returns a :class:`Response` or :class:`Rejected`;
        ``wait=False`` returns the queue :class:`Slot` (or the immediate
        ``Rejected``) so callers can multiplex.
        """
        rid = req.request_id or f"r{next(self._ids)}"
        self._bump("submitted")
        # The request's causal root: the transport's `request` span when
        # one is active (frontend.InProcessClient / the HTTP handler),
        # else the admission span below becomes the root — either way a
        # traced request has exactly ONE root (obs.trace).
        parent = obs_trace.current()
        root = parent
        with obs_trace.span("admission", request_id=rid,
                            backend=req.backend) as asp:
            if root is None:
                root = asp.context
            asp.set(outcome="admitted")
            if self._reshaping:
                # The mesh is being swapped under us: shed with a typed,
                # retryable reason (the window is one drain + re-warm
                # long).
                asp.set(outcome="resharding")
                return self._shed("resharding", rid,
                                  detail="mesh reshape in progress; retry",
                                  counter="rejected_resharding",
                                  trace=root)
            try:
                key, plan_source, planar = self._validate(req)
            except Exception as e:  # noqa: BLE001 — typed contract errors
                asp.set(outcome="invalid")
                return self._shed("invalid", rid, detail=str(e),
                                  counter="rejected_invalid", trace=root)
            deadline_at = (time.monotonic() + req.deadline_s
                           if req.deadline_s is not None else None)
            payload = {"planar": planar, "rid": rid,
                       "rgb": req.image.ndim == 3,
                       "backend": req.backend, "plan_source": plan_source,
                       # The context the worker thread re-enters: queue
                       # span parent, batch-span link, response trace_id.
                       "trace": root}
            slot = self.batcher.try_submit(key, payload, deadline_at)
            if slot is None:
                asp.set(outcome="queue_full")
                return self._shed(
                    "queue_full", rid,
                    detail=f"queue depth >= {self.batcher.max_queue}",
                    counter="rejected_queue_full", trace=root)
        if not wait:
            return slot
        result = slot.result(timeout)
        if result is None:
            # NOT a server-side shed: the caller gave up waiting while the
            # request may still be executing (and will later count as
            # completed).  Distinct reason + counter so an unresponsive
            # service can never reconcile as healthy load shedding.
            return self._shed("timeout", rid,
                              detail="client wait timed out",
                              counter="client_timeouts", trace=root)
        return result

    # -- execution (batcher worker thread) ------------------------------------
    def _execute_batch(self, key: EngineKey, items) -> None:
        from parallel_convolution_tpu.resilience.retry import with_retry
        from parallel_convolution_tpu.utils import imageio

        start = time.monotonic()
        live = []
        for it in items:
            if it.deadline_at is not None and start > it.deadline_at:
                it.slot.set(self._shed(
                    "deadline", it.payload["rid"],
                    detail=f"queued {start - it.enqueued_at:.3f}s past "
                           "deadline",
                    counter="rejected_deadline",
                    trace=it.payload.get("trace")))
            else:
                live.append(it)
        if not live:
            return
        if key.grid != self.engine.grid():
            # The submit-vs-reshape race: a request that passed the
            # _reshaping check keyed against the old grid, then landed on
            # the post-swap batcher.  Shed it typed-and-retryable — the
            # stale-grid ValueError in run_batch must stay a caller-bug
            # backstop, never a client-visible "error".
            for it in live:
                it.slot.set(self._shed(
                    "resharding", it.payload["rid"],
                    detail="mesh resharded while queued; retry",
                    counter="rejected_resharding",
                    trace=it.payload.get("trace")))
            return
        stacked = np.stack([it.payload["planar"] for it in live])
        timer = PhaseTimer()

        def attempt():
            return self.engine.run_batch(key, stacked, timer=timer)

        def on_retry(attempt_no, exc, delay):
            self._bump("retries")

        # The batch-join span (obs.trace): ONE span per flush, parented
        # to the first traced request (whose trace natively owns the
        # shared compile/device work — "who paid") and LINKING every
        # co-batched request's root, so each of the N traces can find
        # the batch it rode.  The engine phases below run on this worker
        # thread inside this span, becoming its children.
        traces = [it.payload.get("trace") for it in live]
        primary = next((c for c in traces if c is not None), None)
        with obs_trace.span(
                "batch", parent=primary,
                links=[c for c in traces if c is not None],
                n_requests=len(live)) as bsp:
            now_ts = time.time()
            for it in live:
                c = it.payload.get("trace")
                if c is not None:
                    q = start - it.enqueued_at
                    # Synthetic queue span: enqueue → batch collect, from
                    # the batcher's own clocks, child of the request root.
                    obs_trace.emit_span(
                        "queue", trace_id=c.trace_id,
                        parent_id=c.span_id, start_ts=now_ts - q,
                        dur_s=q, request_id=it.payload["rid"])
            try:
                out, info = with_retry(attempt, self.retry_policy,
                                       on_retry=on_retry)
            except Exception as e:  # noqa: BLE001 — typed, never a hang
                bsp.set(outcome="error")
                for it in live:
                    it.slot.set(self._shed("error", it.payload["rid"],
                                           detail=repr(e)[:500],
                                           counter="rejected_error",
                                           trace=it.payload.get("trace")))
                return
            bsp.set(batch_size=info["batch_size"],
                    effective_backend=info["effective_backend"],
                    plan_key=info.get("plan_key", ""))
            phases = dict(info["phases"])
            u8 = np.clip(np.rint(out), 0.0, 255.0).astype(np.uint8)
            for i, it in enumerate(live):
                plane = u8[i]
                image = (imageio.planar_to_interleaved(plane)
                         if it.payload["rgb"] else plane[0])
                queue_s = start - it.enqueued_at
                per = {"queue": round(queue_s, 6),
                       **{k: round(v, 6) for k, v in phases.items()},
                       }
                per["total"] = round(queue_s + sum(phases.values()), 6)
                c = it.payload.get("trace")
                it.slot.set(Response(
                    image=image,
                    effective_backend=info["effective_backend"],
                    backend=it.payload["backend"],
                    request_id=it.payload["rid"],
                    batch_size=info["batch_size"],
                    phases=per,
                    # Per-REQUEST provenance from admission time: an auto
                    # and an explicit request can share this entry, so
                    # the entry's build-time note cannot label them both.
                    plan_source=it.payload.get(
                        "plan_source", info.get("plan_source", "explicit")),
                    predicted_gpx_per_chip=info.get(
                        "predicted_gpx_per_chip"),
                    effective_grid=info.get("effective_grid", ""),
                    overlap=bool(info.get("overlap", False)),
                    exchange_fraction=info.get("exchange_fraction", 0.0),
                    exchange_hidden_fraction=info.get(
                        "exchange_hidden_fraction", 0.0),
                    trace_id=c.trace_id if c is not None else "",
                    plan_key=info.get("plan_key", ""),
                ))
                self._bump("completed")
                if obs_metrics.enabled():
                    ph = obs_metrics.histogram(
                        "pctpu_request_phase_seconds",
                        "per-request serving latency by phase",
                        ("phase", "backend"))
                    eff = info["effective_backend"]
                    for name, v in per.items():
                        ph.observe(v, phase=name, backend=eff)
                    obs_metrics.counter(
                        "pctpu_admission_total",
                        "typed request outcomes at the admission boundary",
                        ("outcome",)).inc(outcome="completed")
        if obs_metrics.enabled():
            obs_metrics.histogram(
                "pctpu_batch_size", "co-batched requests per flush", (),
                buckets=(1, 2, 4, 8, 16, 32, 64)).observe(len(live))

    # -- elastic recovery ----------------------------------------------------
    def reshape(self, mesh) -> dict:
        """Shrink (or otherwise re-grid) the serving mesh WITHOUT a
        process restart — the serve-through-shrink leg of elastic
        recovery.  ``mesh`` is a Mesh or an ``"RxC"`` spec string.

        Sequence, in order (each step's invariant):

        1. flag ``resharding`` — new submissions shed with a typed,
           retryable ``Rejected("resharding")`` (never an error, never a
           hang);
        2. drain the batcher — every in-flight/queued request completes
           on the OLD grid (its response stamps the old
           ``effective_grid``), and the single worker thread exits, so
           no execution can straddle the swap;
        3. ``engine.reshape`` — warm entries drop, the mesh swaps, the
           previously-resident keys re-warm on the new grid;
        4. a fresh batcher starts and admission reopens.

        Requests admitted afterwards re-key against the new mesh in
        ``_validate`` (``engine.resolve_key`` reads the live grid), so
        their responses stamp the new ``effective_grid``.
        """
        from parallel_convolution_tpu.parallel.mesh import (
            grid_shape, mesh_from_spec,
        )

        if isinstance(mesh, str):
            mesh = mesh_from_spec(mesh)
        grid_shape(mesh)  # malformed mesh dies HERE, before any teardown
        with self._reshape_lock:
            self._reshaping = True
            try:
                old = self.batcher
                old.close(drain=True)
                try:
                    info = self.engine.reshape(mesh)
                finally:
                    # Admission must reopen even if the engine swap blew
                    # up (per-key re-warm failures are absorbed inside
                    # reshape; anything else must not wedge the service
                    # behind a closed batcher forever).
                    self.batcher = MicroBatcher(
                        self._execute_batch, max_batch=old.max_batch,
                        max_delay_s=old.max_delay_s,
                        max_queue=old.max_queue, start=True)
                self._bump("reshapes")
            finally:
                self._reshaping = False
        return info

    # -- lifecycle / introspection -------------------------------------------
    def warmup(self, configs, plan_file: str | None = None) -> list[str]:
        """Pre-compile declared configs before taking traffic.

        ``configs`` are dicts with ``rows``/``cols``/``mode`` plus any
        :class:`Request` knobs (filter, iters, backend, storage, fuse,
        boundary, quantize — plus ``tile``); returns each config's
        effective backend.  ``backend="auto"`` configs (and later auto
        requests) resolve through ``plan_file`` when given (the tuner's
        emitted plans — the service boots already tuned), else the
        ambient/engine plan cache, else the cost model.
        """
        if plan_file is not None:
            from parallel_convolution_tpu.tuning import PlanCache

            self.engine.plans = PlanCache.load(plan_file)
        keys = []
        for c in configs:
            channels = 3 if c.get("mode", "grey") == "rgb" else 1
            fuse = c.get("fuse", 1)
            tile = c.get("tile")
            keys.append(self.engine.key_for(
                (channels, int(c["rows"]), int(c["cols"])),
                filter_name=c.get("filter", c.get("filter_name", "blur3")),
                storage=c.get("storage", "f32"),
                iters=int(c.get("iters", 1)),
                fuse=None if fuse is None else int(fuse),
                tile=None if tile is None else tuple(int(v) for v in tile),
                boundary=c.get("boundary", "zero"),
                quantize=bool(c.get("quantize", True)),
                backend=c.get("backend", "shifted")))
        return self.engine.warmup(keys)

    def readiness(self) -> tuple[bool, dict]:
        """The ``/readyz`` verdict: can this service usefully take a NEW
        request right now?

        Not ready while a mesh reshape is in progress (submissions shed
        ``resharding``) or while the queue is at its admission bound
        (submissions shed ``queue_full``) — exactly the two states where
        a replica router (ROADMAP item 2) should steer traffic
        elsewhere.  A DEGRADED backend tier keeps readiness true (the
        service is serving, on a lower tier) but is reported in the
        payload so the router can prefer healthy replicas.
        """
        depth = self.batcher.depth()
        bound = self.batcher.max_queue
        degraded = self.engine.degraded()
        ready = not self._reshaping and depth < bound
        return ready, {
            "ready": ready,
            "reshaping": bool(self._reshaping),
            "queue_depth": depth,
            "queue_bound": bound,
            "queue_full": depth >= bound,
            "degraded": degraded,
            "grid": "x".join(str(v) for v in self.engine.grid()),
        }

    def snapshot(self) -> dict:
        with self._lock:
            stats = dict(self.stats)
        snap = self.engine.snapshot()
        dev = self.engine.mesh.devices.flat[0]
        return {
            "service": stats,
            "batcher": dict(self.batcher.stats),
            "engine": snap["stats"],
            "resident": snap["resident"],
            "queue_depth": self.batcher.depth(),
            "mesh": "x".join(str(s)
                             for s in (self.engine.mesh.shape["x"],
                                       self.engine.mesh.shape["y"])),
            "platform": dev.platform,
            "device_kind": getattr(dev, "device_kind", "") or "",
        }

    def close(self) -> None:
        self.batcher.close()
