"""Admission-controlled convolution service over the warm engine.

The request/response surface of the serving layer: validation, admission
control (bounded queue depth + per-request deadlines + typed
load-shedding), micro-batched execution, and the resilience wiring —
transient failures retry via ``resilience.retry.with_retry`` and compile
faults walk the ``resilience.degrade`` backend ladder per key (inside
the engine).  Every successful response is stamped with the
``effective_backend`` that actually produced its bytes, continuing the
round-7 rule that a degraded tier can never masquerade as the requested
one in any artifact.

Results are TYPED, never exceptions across the service boundary:

* :class:`Response`  — the filtered image + per-request latency phases
  (queue / compile / device / copy, from ``utils.tracing.PhaseTimer``).
* :class:`Rejected`  — load shedding (``queue_full``), missed deadlines
  (``deadline``), contract errors (``invalid``), and exhausted/terminal
  execution failures (``error``).  A queue overflow yields a
  ``Rejected``, not an exception and not a hang — asserted in tier-1.
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
import time

import numpy as np

from parallel_convolution_tpu.obs import (
    events as obs_events, metrics as obs_metrics, trace as obs_trace,
)
from parallel_convolution_tpu.serving import cache as cache_mod
from parallel_convolution_tpu.serving import engine as engine_mod
from parallel_convolution_tpu.serving.batcher import MicroBatcher
from parallel_convolution_tpu.serving.engine import EngineKey, WarmEngine
from parallel_convolution_tpu.serving.pricing import WorkPricer
from parallel_convolution_tpu.utils.tracing import PhaseTimer

__all__ = ["ConvolutionService", "RETRYABLE_REJECTS", "Rejected",
           "ReleasingStream", "Request", "Response", "Snapshot"]

# The rejection reasons a client should BACK OFF AND RETRY (the condition
# is transient server state: a full queue, a mesh reshape window, an
# exhausted tenant bucket, a router with no live replica).  Everything
# else — invalid, error, deadline, timeout — means the same request will
# not fare better on a retry.  The frontend maps these to 429/503 with a
# Retry-After header; scripts/loadgen.py honors them with capped backoff.
RETRYABLE_REJECTS = frozenset(
    {"queue_full", "resharding", "tenant_quota", "replica_unavailable"})

# Default client back-off hints per retryable reason (seconds) — used
# when the shed site doesn't compute a better one (the tenant bucket
# computes its exact refill time).
_RETRY_AFTER_DEFAULT = {"queue_full": 0.1, "resharding": 0.5,
                        "tenant_quota": 1.0, "replica_unavailable": 0.5}


@dataclasses.dataclass
class Request:
    """One filtering request: an interleaved u8 image + run knobs.

    ``image`` is (H, W) grey or (H, W, 3) RGB uint8 — the reference CLI's
    image contract.  ``deadline_s`` is a relative latency budget; a
    request still queued past it is shed with ``Rejected("deadline")``
    rather than served late.

    A RANK-3 request (round 23) sets ``volume`` instead of ``image``: a
    (2, D, H, W) float32 two-field volume (``utils.config.VOLUME_FIELDS``
    interleaved fields — (u, f) for the FD smoothers, (u, u_prev) for
    wave, (U, V) for Gray–Scott), with ``filter_name`` naming a
    registered rank-3 form.  Volume responses carry the float32 fields
    (never u8) and ride the same admission, batching, caching and
    progressive machinery.
    """

    image: np.ndarray | None = None
    filter_name: str = "blur3"
    iters: int = 1
    backend: str = "shifted"         # or "auto": plan-cache/cost-model
    #                                  resolved (engine.key_for)
    storage: str = "f32"
    fuse: int | None = 1             # None = tune it (backend="auto")
    boundary: str = "zero"
    quantize: bool = True
    overlap: bool | None = None      # interior-first overlapped halo
    #                                  pipeline: None = off for explicit
    #                                  backends / tuned for "auto"; the
    #                                  RESOLVED value rides the key and
    #                                  every response stamps it
    col_mode: str | None = None      # RDMA column-slab transport
    #                                  (packed | strided | auto; None =
    #                                  auto) — resolved and stamped
    #                                  under the same rule as overlap
    deadline_s: float | None = None
    request_id: str | None = None    # client-stamped idempotency id: a
    #                                  hedged/retried submission with the
    #                                  same id rides the FIRST one's slot
    #                                  (one device execution per id)
    tenant: str = ""                 # QoS identity (router token buckets;
    #                                  "" = the default tenant)
    solver: str = "jacobi"           # convergence strategy (converge jobs
    #                                  only: the batch path sheds
    #                                  "multigrid" as invalid — there is
    #                                  no fixed-count V-cycle workload)
    mg_levels: int | None = None     # multigrid level-count cap
    volume: np.ndarray | None = None  # rank-3 payload: (2, D, H, W)
    #                                  float32 fields (mutually exclusive
    #                                  with ``image``)


@dataclasses.dataclass
class Response:
    """A served result; ``phases`` is the per-request latency breakdown
    in seconds (queue, compile, device, copy_in, copy_out, total)."""

    image: np.ndarray                # uint8, same layout as the request
    effective_backend: str
    backend: str                     # as requested
    request_id: str
    batch_size: int                  # how many requests shared the program
    phases: dict
    plan_source: str = "explicit"    # explicit|measured|interpolated|
    #                                  predicted (auto-resolution origin)
    predicted_gpx_per_chip: float | None = None  # cost-model figure for
    #                                  the served config (vs measured)
    effective_grid: str = ""         # "RxC" mesh grid that produced the
    #                                  bytes (changes after an elastic
    #                                  reshape mid-process)
    overlap: bool = False            # the compiled program's RESOLVED
    #                                  overlap knob (False when clamped
    #                                  or degraded off the RDMA tier)
    col_mode: str = "packed"         # the compiled program's RESOLVED
    #                                  column-slab transport ('packed'
    #                                  is the canonical label off the
    #                                  RDMA tier)
    exchange_fraction: float = 0.0   # model-attributed EXPOSED exchange
    #                                  share of one iteration's wall
    exchange_hidden_fraction: float = 0.0  # share of exchange time the
    #                                  overlapped pipeline hides under
    #                                  compute (0.0 when serialized)
    trace_id: str = ""               # the request's causal trace id
    #                                  (obs.trace; "" with PCTPU_OBS=0)
    plan_key: str = ""               # tuning canonical key of the served
    #                                  config — the perf_gate.py history
    #                                  key and the drift-series label
    cache: str = "miss"              # content-addressed result cache
    #                                  verdict: "hit" bytes came from the
    #                                  cache (no lane, no device); "miss"
    #                                  they were executed (and stored);
    #                                  "off" the service runs uncached
    digest: str = ""                 # the request's input digest (SHA-256
    #                                  over the planar bytes; "" uncached)

    ok = True


@dataclasses.dataclass
class Rejected:
    """A typed non-result: load shed, deadline miss, or failed execution."""

    reason: str   # queue_full | deadline | invalid | error | resharding |
    #               tenant_quota | replica_unavailable | timeout
    request_id: str
    detail: str = ""
    trace_id: str = ""   # the request's causal trace id (when admitted
    #                      under an active trace; "" otherwise)
    retry_after_s: float | None = None  # back-off hint for retryable
    #                      sheds (the frontend's Retry-After header)

    ok = False

    def __post_init__(self) -> None:
        if self.retry_after_s is None:
            # Every retryable rejection carries a back-off hint, however
            # it was constructed (sites with better information — the
            # tenant bucket's exact refill time — pass their own).
            self.retry_after_s = _RETRY_AFTER_DEFAULT.get(self.reason)

    @property
    def retryable(self) -> bool:
        """True iff a client should back off and retry this reason."""
        return self.reason in RETRYABLE_REJECTS


@dataclasses.dataclass
class Snapshot:
    """One progressive-convergence stream row: the best-so-far field.

    A convergence job streams one of these per ``check_every``-iteration
    chunk; the row with ``final=True`` carries the exact bytes a
    non-progressive run of the same job would have returned (asserted in
    ``tests/test_router.py``).  ``diff`` is the max-abs single-iteration
    change the convergence decision reads — the stream IS the diff
    trajectory, so a job that dies mid-run has still delivered its
    best-so-far image plus the curve that says how converged it was.
    """

    image: np.ndarray                # uint8, same layout as the request
    iters: int                       # jacobi iterations — or V-CYCLES
    #                                  for solver="multigrid" (one row
    #                                  per cycle; diff is then the
    #                                  fine-grid residual norm)
    diff: float
    final: bool = False
    converged: bool = False          # final=True only: diff < tol
    request_id: str = ""
    effective_backend: str = ""
    effective_grid: str = ""
    plan_key: str = ""
    trace_id: str = ""
    solver: str = "jacobi"           # which convergence strategy produced
    #                                  this row (utils.config.SOLVERS)
    work_units: float = 0.0          # fine-grid work spent so far — the
    #                                  solver-comparable budget unit
    #                                  (= iters for jacobi; the
    #                                  pixel-weighted per-level sum for
    #                                  multigrid)
    mg_levels: int | None = None     # multigrid only: the level count the
    #                                  planner actually scheduled
    #                                  (post-resolution, never the cap)
    col_mode: str = "packed"         # the compiled program's RESOLVED
    #                                  column-slab transport (same
    #                                  stamping rule as batch responses)
    state: np.ndarray | None = None  # the FLOAT32 field at the valid
    #                                  extent — the resume-token payload
    #                                  (round 18), carried only when the
    #                                  job asked for it (resume_state on
    #                                  the wire): the u8 ``image`` is
    #                                  lossy, so durability needs the
    #                                  exact carries.  Final rows never
    #                                  carry it (nothing left to resume).
    cache: str = "miss"              # final rows only: "hit" when the
    #                                  converged fixed point came from the
    #                                  result cache (the stream is then
    #                                  one row, no device work)
    digest: str = ""                 # the job's rhs input digest

    ok = True


class ReleasingStream:
    """Iterator over a stream of rows that calls ``release`` exactly
    once when the stream ends, is closed, or is garbage-collected —
    including when it was never started.  A plain generator can't do
    that: its ``finally`` only runs once the body has been entered, so
    an un-started, abandoned stream would pin its resource forever
    (here: a ``max_progressive`` slot; in the router: a replica's
    in-flight load count)."""

    def __init__(self, gen, release):
        self._gen = gen
        self._release = release

    def __iter__(self):
        return self

    def __next__(self):
        return next(self._gen)

    def close(self) -> None:
        try:
            self._gen.close()
        finally:
            self._release()

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass


class ConvolutionService:
    """Micro-batched, admission-controlled serving of the stencil stack.

    ``retry_policy`` governs ``with_retry`` around batch execution:
    classified-transient failures (tunnel blips, injected faults, Mosaic
    INTERNAL crashes) are retried with deterministic backoff; terminal
    failures and exhausted retries become ``Rejected("error")`` for every
    request in the batch.  ``fallback`` (default True) lets the engine
    walk the degradation ladder per key on transient compile faults.
    """

    def __init__(self, mesh=None, *, capacity: int = 16,
                 max_batch: int = 8, max_delay_s: float = 0.005,
                 max_queue: int = 64, fallback: bool = True,
                 retry_policy=None, start: bool = True, plans=None,
                 dedup_capacity: int = 256, max_progressive: int = 2,
                 cache=None):
        from collections import OrderedDict

        from parallel_convolution_tpu.resilience.retry import RetryPolicy

        self.engine = WarmEngine(mesh, capacity=capacity, fallback=fallback,
                                 plans=plans)
        self.retry_policy = retry_policy or RetryPolicy(
            max_attempts=3, base_delay=0.05, max_delay=2.0)
        # Replica-side admission pricer: the same cost model the router
        # uses, here feeding the batcher's lane priority so an expensive
        # job never head-of-line-blocks a thumbnail (serving.pricing).
        dev = self.engine.mesh.devices.flat[0]
        self.pricer = WorkPricer(
            self.engine.grid(), getattr(dev, "platform", "cpu"),
            getattr(dev, "device_kind", ""))
        self.batcher = self._make_batcher(max_batch, max_delay_s,
                                          max_queue, start=start)
        self._ids = itertools.count(1)
        self._lock = threading.Lock()
        self._reshape_lock = threading.Lock()
        self._reshaping = False
        # request_id -> Slot: the idempotency ledger.  A hedged or
        # router-retried submission with an already-seen CLIENT-stamped id
        # joins the first submission's slot instead of executing again
        # (one device execution per request_id — and at the router tier,
        # one tenant-quota charge).  FIFO-bounded; a completed REJECTED
        # entry is evicted on the next arrival so a genuine client retry
        # after a shed re-executes.  NOTE the bound is by COUNT, not
        # bytes: completed slots pin their Response images until evicted,
        # so size dedup_capacity down for large-frame deployments
        # (256 × a 3-channel 2048² response ≈ 3 GB worst case).
        self.dedup_capacity = max(0, int(dedup_capacity))
        self._dedup: OrderedDict[str, object] = OrderedDict()
        self._dedup_lock = threading.Lock()
        # Progressive convergence jobs bypass the micro-batcher (they are
        # long, chunked, and fence per chunk) but are still bounded:
        # at most this many run concurrently, beyond which submissions
        # shed typed-retryable queue_full.
        self.max_progressive = max(1, int(max_progressive))
        self._progressive_active = 0
        # Content-addressed result cache (serving.cache), consulted in
        # _admit AHEAD of the batcher so a hit never touches a lane, a
        # compile, or the device.  ``cache`` is a ResultCache, True (a
        # default in-memory tier), or None/False (off — the default:
        # duplicate-sensitive drills construct services bare, and the
        # serving entrypoints opt in explicitly).
        if cache is True:
            cache = cache_mod.ResultCache()
        # NOT ``cache or None``: an EMPTY ResultCache is falsy (__len__).
        self.cache = cache if cache is not None else None
        # The legacy stats dict, now a view over the obs registry: every
        # write mirrors into pctpu_service_stats{key=...} (obs.metrics),
        # so the admission-control ledger is one /metrics scrape away.
        self.stats = obs_metrics.MirroredStats(obs_metrics.gauge(
            "pctpu_service_stats", "service admission/completion counters",
            ("key",)), initial={
            "submitted": 0, "completed": 0, "retries": 0,
            "rejected_queue_full": 0, "rejected_deadline": 0,
            "rejected_invalid": 0, "rejected_error": 0,
            "rejected_resharding": 0, "client_timeouts": 0,
            "reshapes": 0, "deduped": 0, "progressive": 0,
            "rejected_stale_epoch": 0, "cache_hits": 0, "cache_misses": 0,
        })
        # Router-epoch fence (round 19): the highest epoch any router
        # has ever stamped on a request to THIS replica.  A request
        # carrying a LOWER epoch comes from a zombie — a router that
        # lost a fenced takeover — and is rejected before any work, so
        # a stale active can never double-deliver after the standby
        # took over.  Process memory on purpose: a replica restart
        # clears its dedup ledger too, and the fence re-ratchets on the
        # first request from the live router.
        # Router-epoch fences, keyed by shard label (round 21).  The
        # empty key "" is the unsharded/legacy lineage; a replica serving
        # N shards holds N independent ratchets, so fencing shard A's
        # zombie owner never rejects the same process's LIVE ownership
        # of shard B.
        self._fences: dict[str, int] = {}

    def _make_batcher(self, max_batch: int, max_delay_s: float,
                      max_queue: int, start: bool = True) -> MicroBatcher:
        """The one construction site for this service's batcher (used by
        ``__init__`` AND ``reshape``, so the continuous-batching wiring
        — shape-bucketed lanes via ``engine.bucket_key``, the collector's
        host-side ``_prepare_batch`` — survives a mesh swap)."""
        return MicroBatcher(
            self._execute_batch, max_batch=max_batch,
            max_delay_s=max_delay_s, max_queue=max_queue, start=start,
            lane_of=engine_mod.bucket_key, prepare=self._prepare_batch)

    # -- admission -----------------------------------------------------------
    def _bump(self, counter: str, n: int = 1) -> None:
        with self._lock:
            self.stats[counter] += n

    def _shed(self, reason: str, rid: str, detail: str = "",
              counter: str | None = None, n: int = 1,
              trace=None, retry_after_s: float | None = None) -> Rejected:
        """One path for every typed rejection: the legacy counter bump,
        the admission event, and the Rejected value.  ``trace`` is the
        request's :class:`obs.trace.SpanContext` when it was admitted
        under an active trace — the rejection then joins the tree.
        Retryable reasons carry a back-off hint (``retry_after_s``,
        defaulted per reason) that the frontend turns into Retry-After."""
        if counter is not None:
            self._bump(counter, n)
        if obs_metrics.enabled():
            obs_metrics.counter(
                "pctpu_admission_total",
                "typed request outcomes at the admission boundary",
                ("outcome",)).inc(n, outcome=reason)
            obs_events.emit(
                "admission", outcome=reason, request_id=rid,
                detail=detail[:200],
                **({"trace_id": trace.trace_id} if trace is not None
                   else {}))
        # retry_after_s=None defers to Rejected.__post_init__'s
        # per-reason default — one site owns the defaulting rule.
        return Rejected(reason, rid, detail=detail,
                        trace_id=trace.trace_id if trace is not None else "",
                        retry_after_s=retry_after_s)

    def _validate(self, req: Request,
                  progressive: bool = False) -> tuple[EngineKey, str,
                                                      np.ndarray]:
        """Terminal ValueError on any contract violation (→ ``invalid``).

        Returns ``(key, plan_source, planar)`` — provenance is
        per-REQUEST (an auto and an explicit request can share a key)."""
        from parallel_convolution_tpu.ops.filters import get_filter
        from parallel_convolution_tpu.utils import imageio

        if req.solver != "jacobi" and not progressive:
            # Only convergence jobs have a solver choice: a fixed-count
            # V-cycle workload does not exist, so the batch path sheds it
            # here instead of compiling a meaningless key.
            raise ValueError(
                f"solver={req.solver!r} is only valid for convergence "
                "jobs (/v1/converge); the batch path is solver-less")
        if req.volume is not None:
            return self._validate_volume(req)
        if req.image is None:
            raise ValueError("request carries neither image nor volume")
        img = np.asarray(req.image)
        if img.dtype != np.uint8 or img.ndim not in (2, 3) or (
                img.ndim == 3 and img.shape[-1] != 3):
            raise ValueError(
                f"image must be uint8 (H, W) or (H, W, 3), got "
                f"{img.dtype} {img.shape}")
        planar = imageio.interleaved_to_planar(img).astype(np.float32)
        key, plan_source = self.engine.resolve_key(
            planar.shape, filter_name=req.filter_name, storage=req.storage,
            iters=int(req.iters),
            fuse=None if req.fuse is None else int(req.fuse),
            boundary=req.boundary,
            quantize=bool(req.quantize), backend=req.backend,
            overlap=req.overlap, col_mode=req.col_mode, solver=req.solver,
            mg_levels=(None if req.mg_levels is None
                       else int(req.mg_levels)))
        key.validate()
        filt = get_filter(key.filter_name)
        R, C = key.grid
        if (min(-(-planar.shape[1] // R), -(-planar.shape[2] // C))
                < filt.radius * key.fuse):
            raise ValueError(
                f"per-device block smaller than radius*fuse "
                f"({filt.radius}*{key.fuse}) for image "
                f"{planar.shape[1:]} on grid {key.grid}")
        if key.boundary == "periodic" and (
                planar.shape[1] % R or planar.shape[2] % C):
            raise ValueError(
                "periodic boundary requires grid-divisible dimensions")
        return key, plan_source, planar

    def _validate_volume(self, req: Request) -> tuple[EngineKey, str,
                                                      np.ndarray]:
        """The rank-3 arm of :meth:`_validate`: ``planar`` is the
        (2, D, H, W) float32 volume itself.  Quantize/storage are
        CLAMPED (volumes are float fields — the u8 knobs have no rank-3
        meaning, so every spelling of a volume request shares one key
        rather than shedding on an inapplicable default)."""
        from parallel_convolution_tpu.utils.config import (
            VOLUME_FIELDS, VOLUME_RADII,
        )

        if req.image is not None:
            raise ValueError("request carries both image and volume")
        vol = np.asarray(req.volume)
        if vol.ndim != 4 or vol.shape[0] != VOLUME_FIELDS:
            raise ValueError(
                f"volume must be ({VOLUME_FIELDS}, D, H, W) float32, "
                f"got shape {vol.shape}")
        if vol.dtype != np.float32:
            raise ValueError(
                f"volume must be float32, got {vol.dtype}")
        if req.solver != "jacobi":
            raise ValueError(
                "rank-3 convergence is the chunked-jacobi driver; "
                f"solver={req.solver!r} is rank-2 only")
        planar = np.ascontiguousarray(vol, dtype=np.float32)
        D, H, W = planar.shape[1:]
        key, plan_source = self.engine.resolve_key(
            (D, H, W), rank=3, filter_name=req.filter_name,
            storage="f32", iters=int(req.iters),
            fuse=1 if req.fuse is None else int(req.fuse),
            boundary=req.boundary, quantize=False, backend=req.backend,
            overlap=req.overlap, col_mode=req.col_mode,
            solver=req.solver)
        key.validate()
        r = VOLUME_RADII[key.filter_name]
        R, C = key.grid
        if min(-(-H // R), -(-W // C)) < r * key.fuse:
            raise ValueError(
                f"per-device block smaller than radius*fuse "
                f"({r}*{key.fuse}) for volume plane ({H}, {W}) on grid "
                f"{key.grid}")
        if key.boundary == "periodic":
            if H % R or W % C:
                raise ValueError(
                    "periodic boundary requires grid-divisible "
                    "dimensions")
            if D < r * key.fuse:
                raise ValueError(
                    f"periodic depth wrap needs D >= radius*fuse "
                    f"({r}*{key.fuse}), got D={D}")
        return key, plan_source, planar

    def submit(self, req: Request, wait: bool = True,
               timeout: float | None = None):
        """Admit + (optionally) await one request.

        ``wait=True`` returns a :class:`Response` or :class:`Rejected`;
        ``wait=False`` returns the queue :class:`Slot` (or the immediate
        ``Rejected``) so callers can multiplex.

        A CLIENT-stamped ``request_id`` is an idempotency key: a second
        submission with the same id while the first is in flight (a
        hedge) or completed (a router retry after a lost response) joins
        the first one's slot — one device execution, one result, counted
        in ``stats["deduped"]``.  A completed REJECTED outcome does NOT
        stick: the retry after a shed re-executes.
        """
        rid = req.request_id or f"r{next(self._ids)}"
        self._bump("submitted")
        placeholder = None
        if req.request_id is not None and self.dedup_capacity:
            from parallel_convolution_tpu.serving.batcher import Slot

            with self._dedup_lock:
                cached = self._dedup.get(rid)
                if (cached is not None and cached.done()
                        and isinstance(cached.result(0), Rejected)):
                    # A shed/failed attempt: the retry is a fresh request.
                    self._dedup.pop(rid, None)
                    cached = None
                if cached is None:
                    placeholder = Slot()
                    self._dedup[rid] = placeholder
                    while len(self._dedup) > self.dedup_capacity:
                        self._dedup.popitem(last=False)
            if placeholder is None:
                self._bump("deduped")
                if not wait:
                    return cached
                result = cached.result(timeout)
                if result is None:
                    return self._shed("timeout", rid,
                                      detail="client wait timed out",
                                      counter="client_timeouts")
                return result
        outcome, root = self._admit(req, rid, placeholder)
        if isinstance(outcome, Rejected):
            if placeholder is not None:
                with self._dedup_lock:
                    self._dedup.pop(rid, None)
                placeholder.set(outcome)
            return outcome
        if not wait:
            return outcome
        result = outcome.result(timeout)
        if result is None:
            # NOT a server-side shed: the caller gave up waiting while the
            # request may still be executing (and will later count as
            # completed).  Distinct reason + counter so an unresponsive
            # service can never reconcile as healthy load shedding.
            return self._shed("timeout", rid,
                              detail="client wait timed out",
                              counter="client_timeouts", trace=root)
        return result

    def _admit(self, req: Request, rid: str, slot=None):
        """Validate + enqueue one request; returns ``(outcome, root)``
        where outcome is the queue Slot or a typed Rejected and root the
        request's trace context (so later sheds — the client-timeout
        path — keep their trace linkage).  ``slot`` (the dedup
        placeholder) becomes the item's slot so hedges that reserved it
        rendezvous correctly."""
        # The request's causal root: the transport's `request` span when
        # one is active (frontend.InProcessClient / the HTTP handler),
        # else the admission span below becomes the root — either way a
        # traced request has exactly ONE root (obs.trace).
        parent = obs_trace.current()
        root = parent
        with obs_trace.span("admission", request_id=rid,
                            backend=req.backend) as asp:
            if root is None:
                root = asp.context
            asp.set(outcome="admitted")
            if self._reshaping:
                # The mesh is being swapped under us: shed with a typed,
                # retryable reason (the window is one drain + re-warm
                # long).
                asp.set(outcome="resharding")
                return self._shed("resharding", rid,
                                  detail="mesh reshape in progress; retry",
                                  counter="rejected_resharding",
                                  trace=root), root
            try:
                key, plan_source, planar = self._validate(req)
            except Exception as e:  # noqa: BLE001 — typed contract errors
                asp.set(outcome="invalid")
                return self._shed("invalid", rid, detail=str(e),
                                  counter="rejected_invalid",
                                  trace=root), root
            digest, ckey = "", ""
            if self.cache is not None:
                # Content-addressed lookup AHEAD of the batcher: a hit is
                # served right here — no lane, no queue, no device.  The
                # key folds the input digest with the FULL compile
                # identity, so equal keys are byte-identical answers by
                # construction (the cache_smoke oracle gate).
                t_lookup = time.monotonic()
                digest = cache_mod.input_digest(planar)
                ckey = cache_mod.result_key(digest, key)
                got = self.cache.get(ckey)
                if got is not None:
                    hit = self._hit_response(
                        req, rid, got, digest=digest, root=root,
                        plan_source=plan_source,
                        lookup_s=time.monotonic() - t_lookup)
                    asp.set(outcome="cache_hit", cache="hit",
                            digest=digest)
                    out_slot = slot
                    if out_slot is None:
                        from parallel_convolution_tpu.serving.batcher \
                            import Slot

                        out_slot = Slot()
                    out_slot.set(hit)
                    return out_slot, root
                asp.set(cache="miss", digest=digest)
                self._bump("cache_misses")
            deadline_at = (time.monotonic() + req.deadline_s
                           if req.deadline_s is not None else None)
            if key.rank == 3:
                price_body = {
                    "rows": planar.shape[2], "cols": planar.shape[3],
                    "depth": planar.shape[1], "mode": "volume",
                    "filter": key.filter_name, "iters": key.iters,
                    "fuse": key.fuse, "boundary": key.boundary}
            else:
                price_body = {
                    "rows": planar.shape[1], "cols": planar.shape[2],
                    "mode": "rgb" if req.image.ndim == 3 else "grey",
                    "filter": key.filter_name, "iters": key.iters,
                    "backend": key.backend, "storage": key.storage,
                    "fuse": key.fuse, "boundary": key.boundary,
                    "quantize": key.quantize}
            payload = {"planar": planar, "rid": rid,
                       "rgb": (key.rank == 2 and req.image.ndim == 3),
                       "rank": key.rank,
                       "digest": digest, "ckey": ckey,
                       "backend": req.backend, "plan_source": plan_source,
                       # Predicted device-seconds: the batcher's lane-
                       # priority input (cheap lanes flush first when
                       # several are due — anti head-of-line-blocking).
                       "cost_units": self.pricer.price(price_body),
                       # The context the worker thread re-enters: queue
                       # span parent, batch-span link, response trace_id.
                       "trace": root}
            out_slot = self.batcher.try_submit(key, payload, deadline_at,
                                               slot=slot)
            if out_slot is None:
                asp.set(outcome="queue_full")
                return self._shed(
                    "queue_full", rid,
                    detail=f"queue depth >= {self.batcher.max_queue}",
                    counter="rejected_queue_full", trace=root), root
        return out_slot, root

    def _hit_response(self, req: Request, rid: str, got, *, digest: str,
                      root, plan_source: str, lookup_s: float) -> Response:
        """Rebuild a served Response from one cache entry.  The stored
        image layout always matches the request's (grey vs RGB changes
        the planar shape, which changes the digest), and the stamped
        provenance is the EXECUTING request's — the one that paid."""
        arrays, meta = got
        per = {"queue": 0.0, "cache": round(lookup_s, 6),
               "total": round(lookup_s, 6)}
        self._bump("cache_hits")
        self._bump("completed")
        if obs_metrics.enabled():
            obs_metrics.counter(
                "pctpu_admission_total",
                "typed request outcomes at the admission boundary",
                ("outcome",)).inc(outcome="cache_hit")
            obs_events.emit(
                "admission", outcome="cache_hit", request_id=rid,
                digest=digest[:16],
                **({"trace_id": root.trace_id} if root is not None
                   else {}))
        return Response(
            # Copy: the memory tier's array is shared; an in-process
            # caller mutating its response must not poison the cache.
            image=np.array(arrays["image"]),
            effective_backend=str(meta.get("effective_backend", "")),
            backend=req.backend, request_id=rid,
            batch_size=1, phases=per,
            plan_source=plan_source,
            predicted_gpx_per_chip=meta.get("predicted_gpx_per_chip"),
            effective_grid=str(meta.get("effective_grid", "")),
            overlap=bool(meta.get("overlap", False)),
            col_mode=str(meta.get("col_mode", "packed")),
            exchange_fraction=float(meta.get("exchange_fraction", 0.0)),
            exchange_hidden_fraction=float(
                meta.get("exchange_hidden_fraction", 0.0)),
            trace_id=root.trace_id if root is not None else "",
            plan_key=str(meta.get("plan_key", "")),
            cache="hit", digest=digest)

    # -- execution (batcher collector + executor threads) ---------------------
    def _prepare_batch(self, lane: EngineKey, items) -> dict:
        """Host-side flush assembly, run on the batcher's COLLECTOR
        thread while the executor still runs the previous flush — the
        overlap that keeps the device full (continuous batching).

        Deadline-expired items shed here (before any stacking work is
        spent on them).  A UNIFORM flush (every item shares one original
        key) executes at that exact key with a plain ``np.stack`` — zero
        padding, byte-for-byte the pre-lane behavior.  A MIXED flush
        executes at the lane's bucket key: each planar lands in the
        top-left corner of a zeroed (C, bH, bW) slab, which
        ``engine.bucket_key`` already proved results-invariant for the
        keys it co-batches (iters==1, zero boundary, jacobi)."""
        start = time.monotonic()
        live = []
        for it in items:
            if it.deadline_at is not None and start > it.deadline_at:
                it.slot.set(self._shed(
                    "deadline", it.payload["rid"],
                    detail=f"queued {start - it.enqueued_at:.3f}s past "
                           "deadline",
                    counter="rejected_deadline",
                    trace=it.payload.get("trace")))
            else:
                live.append(it)
        if not live:
            return {"live": live, "stacked": None, "exec_key": lane,
                    "start": start}
        if all(it.key == live[0].key for it in live):
            exec_key = live[0].key
            stacked = np.stack([it.payload["planar"] for it in live])
        else:
            exec_key = lane
            c, bh, bw = exec_key.shape
            stacked = np.zeros((len(live), c, bh, bw), np.float32)
            for i, it in enumerate(live):
                p = it.payload["planar"]
                stacked[i, :, :p.shape[1], :p.shape[2]] = p
        return {"live": live, "stacked": stacked, "exec_key": exec_key,
                "start": start}

    def _execute_batch(self, lane: EngineKey, items,
                       prepared: dict | None = None) -> None:
        from parallel_convolution_tpu.resilience.retry import with_retry
        from parallel_convolution_tpu.utils import imageio

        if prepared is None:  # direct callers (no collector stage)
            prepared = self._prepare_batch(lane, items)
        live = prepared["live"]
        start = prepared["start"]
        key = prepared["exec_key"]
        if not live:
            return
        if key.grid != self.engine.grid():
            # The submit-vs-reshape race: a request that passed the
            # _reshaping check keyed against the old grid, then landed on
            # the post-swap batcher.  Shed it typed-and-retryable — the
            # stale-grid ValueError in run_batch must stay a caller-bug
            # backstop, never a client-visible "error".
            for it in live:
                it.slot.set(self._shed(
                    "resharding", it.payload["rid"],
                    detail="mesh resharded while queued; retry",
                    counter="rejected_resharding",
                    trace=it.payload.get("trace")))
            return
        stacked = prepared["stacked"]
        timer = PhaseTimer()

        def attempt():
            return self.engine.run_batch(key, stacked, timer=timer)

        def on_retry(attempt_no, exc, delay):
            self._bump("retries")

        # The batch-join span (obs.trace): ONE span per flush, parented
        # to the first traced request (whose trace natively owns the
        # shared compile/device work — "who paid") and LINKING every
        # co-batched request's root, so each of the N traces can find
        # the batch it rode.  The engine phases below run on this worker
        # thread inside this span, becoming its children.
        traces = [it.payload.get("trace") for it in live]
        primary = next((c for c in traces if c is not None), None)
        with obs_trace.span(
                "batch", parent=primary,
                links=[c for c in traces if c is not None],
                n_requests=len(live)) as bsp:
            now_ts = time.time()
            for it in live:
                c = it.payload.get("trace")
                if c is not None:
                    q = start - it.enqueued_at
                    # Synthetic queue span: enqueue → batch collect, from
                    # the batcher's own clocks, child of the request root.
                    obs_trace.emit_span(
                        "queue", trace_id=c.trace_id,
                        parent_id=c.span_id, start_ts=now_ts - q,
                        dur_s=q, request_id=it.payload["rid"])
            try:
                out, info = with_retry(attempt, self.retry_policy,
                                       on_retry=on_retry)
            except Exception as e:  # noqa: BLE001 — typed, never a hang
                bsp.set(outcome="error")
                for it in live:
                    it.slot.set(self._shed("error", it.payload["rid"],
                                           detail=repr(e)[:500],
                                           counter="rejected_error",
                                           trace=it.payload.get("trace")))
                return
            bsp.set(batch_size=info["batch_size"],
                    effective_backend=info["effective_backend"],
                    plan_key=info.get("plan_key", ""))
            phases = dict(info["phases"])
            if key.rank == 3:
                # Volumes are float fields: no u8 quantization, no
                # interleave — the (2, D, H, W) f32 block IS the
                # response body.  Rank-3 lanes are exact-key (bucket_key
                # identity), so the engine already cropped.
                u8 = None
            else:
                u8 = np.clip(np.rint(out), 0.0, 255.0).astype(np.uint8)
            for i, it in enumerate(live):
                if key.rank == 3:
                    image = np.ascontiguousarray(out[i], dtype=np.float32)
                else:
                    # Crop back to the item's own geometry: a mixed-lane
                    # flush executed at the bucket extent; the pad margin
                    # is throwaway by the bucket_key invariant.
                    h0, w0 = it.payload["planar"].shape[1:]
                    plane = u8[i][:, :h0, :w0]
                    image = (imageio.planar_to_interleaved(plane)
                             if it.payload["rgb"] else plane[0])
                queue_s = start - it.enqueued_at
                per = {"queue": round(queue_s, 6),
                       **{k: round(v, 6) for k, v in phases.items()},
                       }
                per["total"] = round(queue_s + sum(phases.values()), 6)
                c = it.payload.get("trace")
                if self.cache is not None and it.payload.get("ckey"):
                    # Store the FINAL response bytes (post-crop, post-
                    # interleave) so a later hit is byte-identical to
                    # this miss by construction; meta carries the stamps
                    # a hit Response needs to rebuild provenance.
                    self.cache.put(it.payload["ckey"], {"image": image}, {
                        "effective_backend": info["effective_backend"],
                        "effective_grid": info.get("effective_grid", ""),
                        "plan_key": info.get("plan_key", ""),
                        "overlap": bool(info.get("overlap", False)),
                        "col_mode": str(info.get("col_mode", "packed")),
                        "exchange_fraction": info.get(
                            "exchange_fraction", 0.0),
                        "exchange_hidden_fraction": info.get(
                            "exchange_hidden_fraction", 0.0),
                        "predicted_gpx_per_chip": info.get(
                            "predicted_gpx_per_chip"),
                    })
                it.slot.set(Response(
                    image=image,
                    effective_backend=info["effective_backend"],
                    backend=it.payload["backend"],
                    request_id=it.payload["rid"],
                    batch_size=info["batch_size"],
                    phases=per,
                    # Per-REQUEST provenance from admission time: an auto
                    # and an explicit request can share this entry, so
                    # the entry's build-time note cannot label them both.
                    plan_source=it.payload.get(
                        "plan_source", info.get("plan_source", "explicit")),
                    predicted_gpx_per_chip=info.get(
                        "predicted_gpx_per_chip"),
                    effective_grid=info.get("effective_grid", ""),
                    overlap=bool(info.get("overlap", False)),
                    col_mode=str(info.get("col_mode", "packed")),
                    exchange_fraction=info.get("exchange_fraction", 0.0),
                    exchange_hidden_fraction=info.get(
                        "exchange_hidden_fraction", 0.0),
                    trace_id=c.trace_id if c is not None else "",
                    plan_key=info.get("plan_key", ""),
                    cache="miss" if self.cache is not None else "off",
                    digest=it.payload.get("digest", ""),
                ))
                self._bump("completed")
                if obs_metrics.enabled():
                    ph = obs_metrics.histogram(
                        "pctpu_request_phase_seconds",
                        "per-request serving latency by phase",
                        ("phase", "backend"))
                    eff = info["effective_backend"]
                    for name, v in per.items():
                        ph.observe(v, phase=name, backend=eff)
                    obs_metrics.counter(
                        "pctpu_admission_total",
                        "typed request outcomes at the admission boundary",
                        ("outcome",)).inc(outcome="completed")
        if obs_metrics.enabled():
            obs_metrics.histogram(
                "pctpu_batch_size", "co-batched requests per flush", (),
                buckets=(1, 2, 4, 8, 16, 32, 64)).observe(len(live))

    # -- progressive convergence ---------------------------------------------
    def submit_progressive(self, req: Request, *, tol: float,
                           max_iters: int, check_every: int = 10,
                           resume: dict | None = None,
                           carry_state: bool = False):
        """Admit one progressive convergence job.

        Returns an immediate :class:`Rejected` (invalid / resharding /
        queue_full — the progressive-slot bound) or an ITERATOR of
        :class:`Snapshot` rows, one per ``check_every``-iteration chunk,
        ending with a ``final=True`` row whose image is byte-identical to
        the non-progressive run.  A job that fails mid-stream ends with a
        typed :class:`Rejected` row instead — AFTER the best-so-far
        snapshots already streamed, which is the point: a long Jacobi job
        interrupted by a fault or a mesh reshape has delivered its
        best-so-far image plus the diff trajectory, not a timeout.

        ``resume`` (round 18) seeds the stream from a resume token
        instead of iteration 0: a dict with ``iters``/``work_units``
        (how far the dead stream got — a ``check_every``/V-cycle
        boundary), ``diff`` (the residual there), and ``state`` (the
        DECODED (C, H, W) float32 field; ``frontend.decode_converge``
        decodes the wire form).  ``max_iters`` keeps meaning the job's
        TOTAL budget.  The token's field reshards onto THIS service's
        grid in ``_prepare`` (crop + zero-re-pad is bit-exact — the
        checkpoint-reshard invariant), so resume works across replicas
        holding different meshes; because chunk math re-aligns on the
        same boundaries, the resumed final row is byte-identical to the
        uninterrupted run's.  ``carry_state=True`` makes every snapshot
        row carry its own token state (what a durability-aware router
        asks for via the wire's ``resume_state``).

        Progressive jobs bypass the micro-batcher (chunk fences make them
        incompatible with co-batching) and are bounded by
        ``max_progressive`` concurrent jobs; the convergence-chunk
        executables are warm-cached on the engine entry like any other
        key, so a stream of jobs for one config compiles once.
        """
        rid = req.request_id or f"r{next(self._ids)}"
        self._bump("submitted")
        parent = obs_trace.current()
        root = parent
        with obs_trace.span("admission", request_id=rid,
                            backend=req.backend, progressive=True) as asp:
            if root is None:
                root = asp.context
            asp.set(outcome="admitted")
            if self._reshaping:
                asp.set(outcome="resharding")
                return self._shed("resharding", rid,
                                  detail="mesh reshape in progress; retry",
                                  counter="rejected_resharding", trace=root)
            try:
                tol, max_iters = float(tol), int(max_iters)
                check_every = int(check_every)
                if tol < 0 or max_iters < 1 or check_every < 1:
                    raise ValueError(
                        "tol >= 0, max_iters >= 1, check_every >= 1 "
                        "required")
                # The chunk program's compile identity is check_every
                # iterations — that is what keys the warm entry.  A
                # multigrid job's cadence is the V-cycle itself, so its
                # key pins iters=1: two jobs differing only in
                # check_every must share the compiled level programs.
                key, _, planar = self._validate(
                    dataclasses.replace(
                        req, iters=(1 if req.solver == "multigrid"
                                    else check_every)),
                    progressive=True)
                resume = self._validate_resume(resume, key, planar,
                                               check_every, max_iters)
            except Exception as e:  # noqa: BLE001 — typed contract errors
                asp.set(outcome="invalid")
                return self._shed("invalid", rid, detail=str(e),
                                  counter="rejected_invalid", trace=root)
            digest, fkey = "", ""
            if self.cache is not None:
                # Convergence finals are keyed on the FIXED POINT's
                # identity — (rhs digest, tol, solver, mg_levels) plus
                # the stencil key — never on check_every/max_iters.  A
                # job whose final is cached short-circuits to the one
                # final row, even a mid-stream RESUME of it (the token
                # only says where the dead stream got to; the fixed
                # point it was walking toward is already in hand).
                digest = cache_mod.input_digest(planar)
                fkey = cache_mod.converge_key(
                    digest, tol=tol, solver=key.solver,
                    mg_levels=key.mg_levels, engine_key=key)
                got = self.cache.get(fkey)
                if got is not None:
                    asp.set(outcome="cache_hit", cache="hit",
                            digest=digest)
                    return self._hit_final_stream(got, rid, digest, root)
                asp.set(cache="miss", digest=digest)
                self._bump("cache_misses")
            with self._lock:
                # Decide under the lock, shed OUTSIDE it: _shed bumps
                # counters through _bump, which takes this same
                # (non-reentrant) lock.
                slot_free = self._progressive_active < self.max_progressive
                if slot_free:
                    self._progressive_active += 1
                    self.stats["progressive"] += 1
            if not slot_free:
                asp.set(outcome="queue_full")
                return self._shed(
                    "queue_full", rid,
                    detail=f"progressive jobs >= {self.max_progressive}",
                    counter="rejected_queue_full", trace=root)
        release = self._progressive_release()
        return ReleasingStream(
            self._progressive_stream(req, rid, key, planar, tol,
                                     max_iters, check_every, root, release,
                                     resume=resume,
                                     carry_state=carry_state,
                                     digest=digest, fkey=fkey),
            release)

    def _hit_final_stream(self, got, rid: str, digest: str, root):
        """A cached convergence final as a one-row stream (no device
        work, no progressive slot — the job never starts)."""
        arrays, meta = got
        self._bump("cache_hits")
        self._bump("completed")
        if obs_metrics.enabled():
            obs_metrics.counter(
                "pctpu_admission_total",
                "typed request outcomes at the admission boundary",
                ("outcome",)).inc(outcome="cache_hit")
            obs_events.emit(
                "admission", outcome="cache_hit", request_id=rid,
                digest=digest[:16], progressive=True,
                **({"trace_id": root.trace_id} if root is not None
                   else {}))
        row = Snapshot(
            image=np.array(arrays["image"]),
            iters=int(meta.get("iters", 0)),
            diff=float(meta.get("diff", 0.0)), final=True,
            converged=True, request_id=rid,
            effective_backend=str(meta.get("effective_backend", "")),
            effective_grid=str(meta.get("effective_grid", "")),
            plan_key=str(meta.get("plan_key", "")),
            trace_id=root.trace_id if root is not None else "",
            solver=str(meta.get("solver", "jacobi")),
            work_units=float(meta.get("work_units", 0.0)),
            mg_levels=meta.get("mg_levels"),
            col_mode=str(meta.get("col_mode", "packed")),
            cache="hit", digest=digest)

        def gen():
            yield row

        return ReleasingStream(gen(), lambda: None)

    @staticmethod
    def _validate_resume(resume, key, planar, check_every, max_iters):
        """Normalize/validate one resume token against the admitted key
        (terminal ValueError → the typed ``invalid`` rejection).
        Returns ``None`` or ``{"iters", "diff", "work_units", "state"}``
        with ``state`` a (C, H, W) float32 array."""
        if resume is None:
            return None
        state = np.asarray(resume.get("state"), dtype=np.float32)
        if state.shape != tuple(planar.shape):
            raise ValueError(
                f"resume state shape {state.shape} does not match the "
                f"request's planar shape {tuple(planar.shape)}")
        iters = int(resume.get("iters", 0))
        wu = float(resume.get("work_units", iters))
        diff = float(resume.get("diff", float("inf")))
        if iters < 0 or wu < 0:
            raise ValueError(
                f"resume iters/work_units must be >= 0, got "
                f"{iters}/{wu}")
        if (key.solver == "jacobi" and iters % max(1, check_every)
                and iters != int(max_iters)):
            # Tokens are minted on chunk boundaries; an off-boundary
            # token would silently change the remaining chunk math and
            # break the byte-identity contract — reject it typed.  The
            # one legitimate off-multiple boundary is max_iters itself:
            # the final chunk is short when the budget is not a
            # check_every multiple, and its token (a stream that died
            # between the last snapshot and the final row) must resume.
            raise ValueError(
                f"resume iters={iters} is not a check_every="
                f"{check_every} boundary")
        return {"iters": iters, "diff": diff, "work_units": wu,
                "state": state}

    def _progressive_release(self):
        """One idempotent slot-release closure per admitted job: called
        by the stream generator's ``finally`` AND by the wrapper's
        close/finalizer, whichever comes first."""
        released: list = []

        def release() -> None:
            with self._lock:
                if not released:
                    released.append(True)
                    self._progressive_active -= 1

        return release

    def _progressive_stream(self, req, rid, key, planar, tol, max_iters,
                            check_every, root, release, resume=None,
                            carry_state=False, digest="", fkey=""):
        """The admitted job's generator (runs on the CONSUMER's thread)."""
        from parallel_convolution_tpu.utils import imageio

        rgb = (key.rank == 2
               and np.asarray(req.image).ndim == 3)
        grid = f"{key.grid[0]}x{key.grid[1]}"
        tid = root.trace_id if root is not None else ""

        def to_u8(plane):
            if key.rank == 3:
                # Volumes stream as float fields: the (2, D, H, W) f32
                # block passes through untouched (no u8, no interleave).
                return np.ascontiguousarray(plane, dtype=np.float32)
            u8 = np.clip(np.rint(plane), 0.0, 255.0).astype(np.uint8)
            return imageio.planar_to_interleaved(u8) if rgb else u8[0]

        try:
            try:
                entry = self.engine.entry(key)
            except Exception as e:  # noqa: BLE001 — typed, never a leak
                yield self._shed("error", rid, detail=repr(e)[:300],
                                 counter="rejected_error", trace=root)
                return
            # A resumed job seeds from the token's field and counters
            # instead of iteration 0; `last*` start at the token so a
            # token that already met the budget/tolerance still emits
            # its (byte-identical) final row below.
            start_field, start_done, start_wu = planar, 0, 0.0
            last_out, last = None, None
            if resume is not None:
                start_field = resume["state"]
                start_done = resume["iters"]
                start_wu = resume["work_units"]
                last_out = start_field
                last = (start_done, resume["diff"], start_wu)
            with obs_trace.attach(root), obs_trace.span(
                    "progressive", request_id=rid, backend=req.backend,
                    check_every=check_every,
                    resumed_at=start_done) as psp:
                try:
                    for out, done, diff, wu in self.engine.run_converge(
                            key, start_field, tol=tol, max_iters=max_iters,
                            check_every=check_every, start_done=start_done,
                            start_wu=start_wu,
                            start_diff=(last[1] if last is not None
                                        else float("inf"))):
                        last_out, last = out, (done, diff, wu)
                        yield Snapshot(
                            image=to_u8(out), iters=done, diff=diff,
                            request_id=rid,
                            effective_backend=entry.effective_backend,
                            effective_grid=grid, plan_key=entry.plan_key,
                            trace_id=tid, solver=key.solver,
                            work_units=round(float(wu), 3),
                            mg_levels=entry.mg_levels,
                            col_mode=entry.effective_col_mode,
                            state=(out if carry_state else None))
                except Exception as e:  # noqa: BLE001 — typed stream end
                    reason = ("resharding"
                              if ("resharded" in str(e) or self._reshaping)
                              else "error")
                    psp.set(outcome=reason)
                    yield self._shed(
                        reason, rid, detail=repr(e)[:300],
                        counter=f"rejected_{reason}", trace=root)
                    return
                converged = last is not None and last[1] < tol
                psp.set(outcome="completed",
                        iters=last[0] if last else 0, converged=converged)
                final_u8 = to_u8(last_out)
                if self.cache is not None and fkey and converged:
                    # Only CONVERGED finals are cacheable: an exhausted-
                    # budget final depends on max_iters, which is not
                    # part of the fixed point's key.
                    self.cache.put(fkey, {"image": final_u8}, {
                        "iters": last[0] if last else 0,
                        "diff": last[1] if last else 0.0,
                        "effective_backend": entry.effective_backend,
                        "effective_grid": grid,
                        "plan_key": entry.plan_key,
                        "solver": key.solver,
                        "work_units": (round(float(last[2]), 3)
                                       if last else 0.0),
                        "mg_levels": entry.mg_levels,
                        "col_mode": entry.effective_col_mode,
                    })
                yield Snapshot(
                    image=final_u8, iters=last[0] if last else 0,
                    diff=last[1] if last else 0.0, final=True,
                    converged=converged, request_id=rid,
                    effective_backend=entry.effective_backend,
                    effective_grid=grid, plan_key=entry.plan_key,
                    trace_id=tid, solver=key.solver,
                    work_units=round(float(last[2]), 3) if last else 0.0,
                    mg_levels=entry.mg_levels,
                    col_mode=entry.effective_col_mode,
                    cache="miss" if self.cache is not None else "off",
                    digest=digest)
                self._bump("completed")
        finally:
            release()

    # -- elastic recovery ----------------------------------------------------
    def reshape(self, mesh) -> dict:
        """Shrink (or otherwise re-grid) the serving mesh WITHOUT a
        process restart — the serve-through-shrink leg of elastic
        recovery.  ``mesh`` is a Mesh or an ``"RxC"`` spec string.

        Sequence, in order (each step's invariant):

        1. flag ``resharding`` — new submissions shed with a typed,
           retryable ``Rejected("resharding")`` (never an error, never a
           hang);
        2. drain the batcher — every in-flight/queued request completes
           on the OLD grid (its response stamps the old
           ``effective_grid``), and the single worker thread exits, so
           no execution can straddle the swap;
        3. ``engine.reshape`` — warm entries drop, the mesh swaps, the
           previously-resident keys re-warm on the new grid;
        4. a fresh batcher starts and admission reopens.

        Requests admitted afterwards re-key against the new mesh in
        ``_validate`` (``engine.resolve_key`` reads the live grid), so
        their responses stamp the new ``effective_grid``.
        """
        from parallel_convolution_tpu.parallel.mesh import (
            grid_shape, mesh_from_spec,
        )

        if isinstance(mesh, str):
            mesh = mesh_from_spec(mesh)
        grid_shape(mesh)  # malformed mesh dies HERE, before any teardown
        with self._reshape_lock:
            self._reshaping = True
            try:
                old = self.batcher
                old.close(drain=True)
                try:
                    info = self.engine.reshape(mesh)
                finally:
                    # Admission must reopen even if the engine swap blew
                    # up (per-key re-warm failures are absorbed inside
                    # reshape; anything else must not wedge the service
                    # behind a closed batcher forever).
                    self.batcher = self._make_batcher(
                        old.max_batch, old.max_delay_s, old.max_queue,
                        start=True)
                    dev = self.engine.mesh.devices.flat[0]
                    self.pricer = WorkPricer(
                        self.engine.grid(), getattr(dev, "platform", "cpu"),
                        getattr(dev, "device_kind", ""))
                self._bump("reshapes")
                if self.cache is not None:
                    # Cached metadata stamps the OLD grid's provenance
                    # (effective_grid, plan_key); serving it after the
                    # swap would lie.  Every drop is journaled dead
                    # (write-ahead), so a restart cannot resurrect them.
                    self.cache.invalidate_all()
            finally:
                self._reshaping = False
        return info

    # -- lifecycle / introspection -------------------------------------------
    def warmup(self, configs, plan_file: str | None = None) -> list[str]:
        """Pre-compile declared configs before taking traffic.

        ``configs`` are dicts with ``rows``/``cols``/``mode`` plus any
        :class:`Request` knobs (filter, iters, backend, storage, fuse,
        boundary, quantize — plus ``tile``); returns each config's
        effective backend.  ``backend="auto"`` configs (and later auto
        requests) resolve through ``plan_file`` when given (the tuner's
        emitted plans — the service boots already tuned), else the
        ambient/engine plan cache, else the cost model.
        """
        if plan_file is not None:
            from parallel_convolution_tpu.tuning import PlanCache

            self.engine.plans = PlanCache.load(plan_file)
        keys = []
        for c in configs:
            channels = 3 if c.get("mode", "grey") == "rgb" else 1
            fuse = c.get("fuse", 1)
            tile = c.get("tile")
            overlap = c.get("overlap")
            keys.append(self.engine.key_for(
                (channels, int(c["rows"]), int(c["cols"])),
                filter_name=c.get("filter", c.get("filter_name", "blur3")),
                storage=c.get("storage", "f32"),
                iters=int(c.get("iters", 1)),
                fuse=None if fuse is None else int(fuse),
                tile=None if tile is None else tuple(int(v) for v in tile),
                boundary=c.get("boundary", "zero"),
                quantize=bool(c.get("quantize", True)),
                backend=c.get("backend", "shifted"),
                # Knob parity with the request path (resolve_key settles
                # both pre-keying): a pre-warmed key must be EXACTLY the
                # key the live request will hit, or warm placement
                # compiles the wrong program and the join pays a compile
                # storm anyway.
                overlap=None if overlap is None else bool(overlap),
                col_mode=(None if c.get("col_mode") is None
                          else str(c.get("col_mode")))))
        return self.engine.warmup(keys)

    def fence(self, epoch: int, shard=None) -> int:
        """Ratchet the router-epoch fence for ``shard`` (the empty /
        ``None`` label is the unsharded lineage) to at least ``epoch``
        (the takeover propagation call — ``POST /v1/fence``); returns
        the fence after ratcheting.  Never lowers it.  Fences are
        PER-SHARD: sweeping shard A leaves shard B's ratchet alone."""
        e = int(epoch)
        s = "" if shard is None else str(shard)
        with self._lock:
            if e > self._fences.get(s, 0):
                self._fences[s] = e
            return self._fences.get(s, 0)

    def fence_epoch(self, shard=None) -> int:
        s = "" if shard is None else str(shard)
        with self._lock:
            return self._fences.get(s, 0)

    def fence_epochs(self) -> dict:
        """Every shard's fence (the recovery read for a multi-lineage
        takeover; key "" is the unsharded legacy ratchet)."""
        with self._lock:
            return dict(self._fences)

    def epoch_gate(self, epoch, shard=None) -> tuple[bool, int]:
        """Admission-time fencing: ``(admit, current_fence)``, scoped
        to ``shard``'s ratchet (``None``/"" = the unsharded lineage).

        ``None`` epoch (a direct client, no router in the path) always
        admits.  A NEWER epoch ratchets the fence and admits — the
        first request from a freshly taken-over router is itself the
        fence propagation.  A STALE epoch is refused (counted,
        evented): the caller sheds it typed non-retryable
        ``stale_epoch`` before any queueing or device work.
        """
        s = "" if shard is None else str(shard)
        if epoch is None:
            with self._lock:
                return True, self._fences.get(s, 0)
        try:
            e = int(epoch)
        except (TypeError, ValueError):
            with self._lock:
                return True, self._fences.get(s, 0)
        with self._lock:
            if e > self._fences.get(s, 0):
                self._fences[s] = e
            ok = e >= self._fences.get(s, 0)
            if not ok:
                self.stats["rejected_stale_epoch"] += 1
            cur = self._fences.get(s, 0)
        if not ok and obs_metrics.enabled():
            obs_metrics.counter(
                "pctpu_stale_epoch_rejects_total",
                "requests refused for carrying a fenced-out router "
                "epoch (zombie active after a takeover)").inc()
            obs_events.emit("router", event="stale_epoch",
                            epoch=e, fence=cur, shard=s)
        return ok, cur

    def readiness(self) -> tuple[bool, dict]:
        """The ``/readyz`` verdict: can this service usefully take a NEW
        request right now?

        Not ready while a mesh reshape is in progress (submissions shed
        ``resharding``) or while the queue is at its admission bound
        (submissions shed ``queue_full``) — exactly the two states where
        a replica router (ROADMAP item 2) should steer traffic
        elsewhere.  A DEGRADED backend tier keeps readiness true (the
        service is serving, on a lower tier) but is reported in the
        payload so the router can prefer healthy replicas.
        """
        depth = self.batcher.depth()
        bound = self.batcher.max_queue
        degraded = self.engine.degraded()
        ready = not self._reshaping and depth < bound
        warm_keys = self.engine.warm_key_count()
        return ready, {
            "ready": ready,
            "reshaping": bool(self._reshaping),
            "queue_depth": depth,
            "queue_bound": bound,
            "queue_full": depth >= bound,
            # In-flight work the batcher can't see: progressive streams
            # run on consumer threads — the autoscaler's pressure signal
            # must count them or converge load never scales the pool.
            "progressive_active": self._progressive_active,
            "progressive_bound": self.max_progressive,
            "warm_keys": warm_keys,
            "degraded": degraded,
            # The router-epoch fence (round 19): a recovering router
            # reads this off every replica to place its own epoch ABOVE
            # anything any previous active ever stamped.  Round 21 adds
            # the full per-shard map; the scalar stays the unsharded
            # lineage's ratchet for wire compatibility.
            "fence_epoch": self.fence_epoch(),
            "fence_epochs": self.fence_epochs(),
            "grid": "x".join(str(v) for v in self.engine.grid()),
        }

    def snapshot(self) -> dict:
        from parallel_convolution_tpu.utils.platform import topology

        with self._lock:
            stats = dict(self.stats)
        snap = self.engine.snapshot()
        dev = self.engine.mesh.devices.flat[0]
        return {
            "service": stats,
            "batcher": dict(self.batcher.stats),
            "engine": snap["stats"],
            "cache": (self.cache.snapshot()
                      if self.cache is not None else None),
            "resident": snap["resident"],
            "queue_depth": self.batcher.depth(),
            "mesh": "x".join(str(s)
                             for s in (self.engine.mesh.shape["x"],
                                       self.engine.mesh.shape["y"])),
            "platform": dev.platform,
            "device_kind": getattr(dev, "device_kind", "") or "",
            "fence_epoch": self.fence_epoch(),
            "fence_epochs": self.fence_epochs(),
            # Topology identity (ROADMAP item 1's keying, pulled forward
            # in r17): loadgen summaries and perf_gate.row_key consume
            # these so a future multi-host row never shares a baseline
            # with a single-host one.
            **topology(self.engine.mesh),
        }

    def close(self) -> None:
        self.batcher.close()
